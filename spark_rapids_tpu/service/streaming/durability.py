"""Streaming durability: ingest WAL + state checkpoints (PR 19).

The spill framework's bottom tier already makes disk a first-class
home for columnar state; this module extends it from *spill* (bytes we
can afford to lose — the device copy is authoritative) to
*state-of-record* (bytes that ARE the standing query after a crash).
Two artifact kinds live under ``rapids.tpu.streaming.checkpoint.dir``:

``StreamWal`` — one append-only log per streaming table at
``<root>/tables/<table>/wal.log``. ``StreamTableSource.append``
persists each validated delta here, CRC-framed and sequence-numbered,
BEFORE the delta becomes visible to any fold — so a fold interrupted
by SIGKILL can always be replayed from the log. fsync is batched
(``walSyncEvery``); the unsynced tail is charged to admission through
the service's ``extra_bytes_fn``.

``CheckpointStore`` — per-standing-query checkpoint files at
``<root>/queries/<table>/<query>/ckpt-<seq>.srck``: a JSON meta block
(sequence cursor, watermark, fold counters, plan signature) plus the
running (keys..., partials...) state in the serde wire format — the
SAME bytes the host->disk spill tier writes, so batch fidelity is
already proven by the spill round-trip tests. Files commit through
write-temp + fsync + atomic rename and carry a trailing CRC over
everything after the magic; retention keeps the newest ``retain``.

Recovery policy (exactly-once):

- the latest checkpoint that parses AND passes CRC AND matches the
  query's plan signature wins; every rejected candidate bumps the
  ``torn_rejected`` counter and recovery falls back to the next older
  one, bottoming out at a full refold from the WAL;
- the WAL suffix past the checkpoint's sequence cursor is replayed
  through the normal fold path — the cursor dedups, so each delta
  folds exactly once across the crash;
- a torn WAL TAIL record (crash mid-append) is truncated and counted,
  never fatal — the append it belonged to was never acknowledged. A
  bad record FOLLOWED by valid data is real corruption and raises a
  loud :class:`WalCorruptionError` (a ``SpillCorruptionError``), never
  silent data loss.

Checkpoint writes ride :class:`memory.catalog.AsyncBatchWriter` (the
PR 6 double-buffered spill-writer template) when
``checkpoint.asyncWrite.enabled`` — the fold returns while the
snapshot commits; pending bytes charge admission.
"""
from __future__ import annotations

import io
import json
import os
import pickle
import struct
import zlib
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.memory.catalog import (AsyncBatchWriter,
                                             SpillCorruptionError)
from spark_rapids_tpu.utils import lockorder

WAL_MAGIC = b"SRTWAL1\n"
CKPT_MAGIC = b"SRTCKP1\n"
#: record frame: body length + crc32(body), little-endian
_REC_HDR = struct.Struct("<II")
CHECKPOINT_VERSION = 1


class WalCorruptionError(SpillCorruptionError):
    """A WAL record failed to decode with valid data after it —
    mid-log corruption, not a torn tail. Chains the underlying decode
    error when there is one; raised instead of silently dropping
    acknowledged ingest."""


def safe_name(name: str) -> str:
    """Filesystem-safe, collision-free directory name for a table or
    query: sanitized human-readable prefix + crc of the exact original
    (two names that sanitize identically must not share a WAL)."""
    clean = "".join(c if c.isalnum() or c in "._-" else "_"
                    for c in name)[:80] or "_"
    return f"{clean}-{zlib.crc32(name.encode('utf-8')) & 0xffffffff:08x}"


def _fsync_dir(path: str) -> None:
    """Make a rename/create durable (fsync on the directory fd);
    best-effort on filesystems that refuse directory fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class StreamWal:
    """Append-only CRC-framed delta log for ONE streaming table.

    Layout: 8-byte magic, then records of ``body_len(4 LE) |
    crc32(body)(4 LE) | body`` where body is the pickled
    ``(seq, data, validity, num_rows)`` delta tuple (numpy-backed, the
    exact arrays ``normalize_batch`` validated)."""

    def __init__(self, directory: str, sync_every: int = 1):
        self.directory = directory
        self.path = os.path.join(directory, "wal.log")
        self.sync_every = max(int(sync_every), 1)
        self._lock = lockorder.make_lock("service.streaming.wal")
        self._fh: Optional[io.BufferedWriter] = None
        self._unsynced_records = 0
        self._unsynced_bytes = 0
        self.records_appended = 0
        os.makedirs(directory, exist_ok=True)

    # -- append --------------------------------------------------------

    def _ensure_open(self) -> io.BufferedWriter:
        if self._fh is None or self._fh.closed:
            fresh = not os.path.exists(self.path) or \
                os.path.getsize(self.path) == 0
            self._fh = open(self.path, "ab")
            if fresh:
                self._fh.write(WAL_MAGIC)
                self._fh.flush()
                os.fsync(self._fh.fileno())
                _fsync_dir(self.directory)
        return self._fh

    def append(self, seq: int, data, validity, num_rows: int) -> None:
        """Persist one delta record; returns once it is at least in
        the page cache (fsync'd every ``sync_every`` records). Called
        under the source lock — WAL order IS delta order."""
        from spark_rapids_tpu.service.streaming import stats as _stats
        from spark_rapids_tpu.shuffle.fault_injection import get_injector

        body = pickle.dumps((int(seq), dict(data), dict(validity),
                             int(num_rows)), protocol=4)
        frame = _REC_HDR.pack(len(body), zlib.crc32(body)) + body
        with self._lock:
            fh = self._ensure_open()
            if get_injector().should_truncate_wal():
                # models a crash mid-append: half the frame reaches
                # disk; replay tolerates (and truncates) the torn tail
                fh.write(frame[:len(frame) // 2])
                fh.flush()
                return
            fh.write(frame)
            fh.flush()
            self.records_appended += 1
            self._unsynced_records += 1
            self._unsynced_bytes += len(frame)
            if self._unsynced_records >= self.sync_every:
                os.fsync(fh.fileno())
                self._unsynced_records = 0
                self._unsynced_bytes = 0
        _stats.bump("wal_records")

    def sync(self) -> None:
        with self._lock:
            if self._fh is not None and not self._fh.closed:
                self._fh.flush()
                os.fsync(self._fh.fileno())
            self._unsynced_records = 0
            self._unsynced_bytes = 0

    def pending_bytes(self) -> int:
        """Appended-but-not-yet-fsync'd WAL bytes (admission charge)."""
        with self._lock:
            return self._unsynced_bytes

    def close(self) -> None:
        with self._lock:
            if self._fh is not None and not self._fh.closed:
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._fh.close()
            self._fh = None
            self._unsynced_records = 0
            self._unsynced_bytes = 0

    # -- replay --------------------------------------------------------

    def replay(self) -> List[Tuple[int, dict, dict, int]]:
        """Decode every durable record, in append order. A torn TAIL
        (incomplete frame, or the final record's CRC failing) is
        truncated off the file and counted in ``torn_rejected``; a bad
        record with valid data after it raises
        :class:`WalCorruptionError`."""
        from spark_rapids_tpu.service.streaming import stats as _stats

        with self._lock:
            if self._fh is not None and not self._fh.closed:
                self._fh.flush()
            if not os.path.exists(self.path):
                return []
            raw = open(self.path, "rb").read()
        if not raw:
            return []
        if raw[:len(WAL_MAGIC)] != WAL_MAGIC:
            raise WalCorruptionError(
                f"WAL {self.path} has a bad magic header "
                f"({raw[:8]!r}); refusing to replay")
        records: List[Tuple[int, dict, dict, int]] = []
        off = len(WAL_MAGIC)
        good_end = off
        torn = None
        while off < len(raw):
            if off + _REC_HDR.size > len(raw):
                torn = f"incomplete record header at offset {off}"
                break
            blen, crc = _REC_HDR.unpack_from(raw, off)
            body_start = off + _REC_HDR.size
            if body_start + blen > len(raw):
                torn = f"incomplete record body at offset {off}"
                break
            body = raw[body_start:body_start + blen]
            if zlib.crc32(body) != crc:
                if body_start + blen == len(raw):
                    torn = f"CRC mismatch in tail record at offset {off}"
                    break
                raise WalCorruptionError(
                    f"WAL {self.path} record at offset {off} fails its "
                    f"CRC with {len(raw) - body_start - blen} valid "
                    "bytes after it — mid-log corruption, not a torn "
                    "tail; refusing to silently drop acknowledged "
                    "ingest")
            try:
                seq, data, validity, num_rows = pickle.loads(body)
            except Exception as e:  # noqa: BLE001 - re-raised chained
                raise WalCorruptionError(
                    f"WAL {self.path} record at offset {off} passes "
                    "CRC but fails to decode") from e
            records.append((int(seq), data, validity, int(num_rows)))
            off = body_start + blen
            good_end = off
        if torn is not None:
            _stats.bump("torn_rejected")
            with self._lock:
                if self._fh is not None and not self._fh.closed:
                    self._fh.close()
                self._fh = None
                self._unsynced_records = 0
                self._unsynced_bytes = 0
                with open(self.path, "r+b") as fh:
                    fh.truncate(good_end)
                    fh.flush()
                    os.fsync(fh.fileno())
        with self._lock:
            self.records_appended = len(records)
        return records


class CheckpointStore:
    """Atomically-committed, CRC'd, retention-pruned checkpoint files
    for ONE standing query.

    File layout: 8-byte magic | meta_len(4 LE) | meta JSON |
    payload_len(8 LE) | payload (serde wire bytes; empty = no state
    yet) | crc32 over meta+payload (4 LE)."""

    SUFFIX = ".srck"

    def __init__(self, directory: str, retain: int = 2,
                 writer: Optional["_CheckpointWriter"] = None):
        self.directory = directory
        self.retain = max(int(retain), 1)
        self._writer = writer
        self._lock = lockorder.make_lock("service.streaming.checkpoint")
        os.makedirs(directory, exist_ok=True)
        self._next_seq = 1 + max(
            (s for s, _ in self._list_files()), default=0)

    # -- write ---------------------------------------------------------

    @staticmethod
    def encode(meta: dict, payload: Optional[bytes]) -> bytes:
        mjson = json.dumps(meta, sort_keys=True).encode("utf-8")
        payload = payload or b""
        return b"".join((
            CKPT_MAGIC, struct.pack("<I", len(mjson)), mjson,
            struct.pack("<Q", len(payload)), payload,
            struct.pack("<I", zlib.crc32(mjson + payload))))

    def write(self, meta: dict, payload: Optional[bytes],
              synchronous: bool = False) -> int:
        """Commit one checkpoint; returns its sequence number. Async
        (through the shared writer template) unless ``synchronous`` or
        no writer is attached — terminal checkpoints (overflow,
        suspend) are always synchronous: the process may be about to
        exit and the bytes must land first."""
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
        meta = dict(meta)
        meta["ckpt_seq"] = seq
        meta["version"] = CHECKPOINT_VERSION
        blob = self.encode(meta, payload)
        if self._writer is not None and not synchronous:
            self._writer.submit_commit(self, seq, blob)
        else:
            self._commit(seq, blob)
        return seq

    def _path_for(self, seq: int) -> str:
        return os.path.join(self.directory,
                            f"ckpt-{seq:010d}{self.SUFFIX}")

    def _commit(self, seq: int, blob: bytes) -> None:
        from spark_rapids_tpu.service.streaming import stats as _stats
        from spark_rapids_tpu.shuffle.fault_injection import get_injector

        final = self._path_for(seq)
        if get_injector().should_tear_checkpoint():
            # models a crash that beat the atomic rename: half the
            # bytes under the final name. No counter bump — the
            # process this write belonged to "died"; recovery counts
            # the reject instead.
            with open(final, "wb") as fh:
                fh.write(blob[:len(blob) // 2])
                fh.flush()
                os.fsync(fh.fileno())
            return
        tmp = os.path.join(self.directory,
                           f".ckpt-{seq:010d}{self.SUFFIX}.tmp")
        with open(tmp, "wb") as fh:
            fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, final)
        _fsync_dir(self.directory)
        _stats.bump("checkpoints_written")
        self._prune()

    def _list_files(self) -> List[Tuple[int, str]]:
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for n in names:
            if n.startswith("ckpt-") and n.endswith(self.SUFFIX):
                try:
                    seq = int(n[len("ckpt-"):-len(self.SUFFIX)])
                except ValueError:
                    continue
                out.append((seq, os.path.join(self.directory, n)))
        return sorted(out)

    def _prune(self) -> None:
        with self._lock:
            files = self._list_files()
            for _seq, path in files[:-self.retain]:
                try:
                    os.unlink(path)
                except OSError:
                    pass

    # -- read ----------------------------------------------------------

    @staticmethod
    def decode(blob: bytes) -> Tuple[dict, bytes]:
        """Parse + CRC-verify one checkpoint blob; raises on anything
        short, reordered, or bit-flipped."""
        if blob[:len(CKPT_MAGIC)] != CKPT_MAGIC:
            raise ValueError("bad checkpoint magic")
        off = len(CKPT_MAGIC)
        (mlen,) = struct.unpack_from("<I", blob, off)
        off += 4
        mjson = blob[off:off + mlen]
        if len(mjson) != mlen:
            raise ValueError("truncated checkpoint meta")
        off += mlen
        (plen,) = struct.unpack_from("<Q", blob, off)
        off += 8
        payload = blob[off:off + plen]
        if len(payload) != plen:
            raise ValueError("truncated checkpoint payload")
        off += plen
        (crc,) = struct.unpack_from("<I", blob, off)
        if zlib.crc32(mjson + payload) != crc:
            raise ValueError("checkpoint CRC mismatch")
        return json.loads(mjson.decode("utf-8")), payload

    def load_latest(self, count_rejects: bool = True
                    ) -> Optional[Tuple[dict, bytes]]:
        """Newest checkpoint that parses and passes CRC, or None.
        Invalid candidates (torn writes, bit rot) are skipped newest to
        oldest, each counted in ``torn_rejected`` (unless peeking)."""
        from spark_rapids_tpu.service.streaming import stats as _stats

        for _seq, path in reversed(self._list_files()):
            try:
                with open(path, "rb") as fh:
                    return self.decode(fh.read())
            except (ValueError, KeyError, OSError, struct.error,
                    json.JSONDecodeError):
                if count_rejects:
                    _stats.bump("torn_rejected")
        return None

    def checkpoint_count(self) -> int:
        return len(self._list_files())


class _CheckpointWriter(AsyncBatchWriter):
    """The checkpoint instantiation of the async batch-writer
    template: items are (store, seq, blob) commits; in-flight blob
    bytes are tracked for the admission charge."""

    def __init__(self, depth: int = 2):
        super().__init__(
            lockorder.make_condition("service.streaming.checkpointWriter"),
            "srt-stream-ckpt", depth)
        self._bytes = 0

    def submit_commit(self, store: CheckpointStore, seq: int,
                      blob: bytes) -> None:
        with self._cv:
            self._bytes += len(blob)
        self.submit((store, seq, blob))

    def pending_bytes(self) -> int:
        with self._cv:
            return self._bytes

    def _process(self, item) -> None:
        store, seq, blob = item
        try:
            store._commit(seq, blob)
        finally:
            with self._cv:
                self._bytes -= len(blob)

    def _on_error(self, item, exc: BaseException) -> None:
        import logging

        store, seq, _blob = item
        logging.getLogger(__name__).exception(
            "async checkpoint commit %d under %s failed; an older "
            "checkpoint (or the WAL) still covers recovery", seq,
            store.directory)


class StreamingDurability:
    """Root handle over the checkpoint directory: hands out per-table
    WALs and per-query checkpoint stores, owns the shared async
    checkpoint writer, and aggregates the pending-byte admission
    charge. One per StreamingManager; inert when the dir knob is
    unset."""

    def __init__(self, conf):
        from spark_rapids_tpu import config as cfg

        self.root = str(conf.get(cfg.STREAMING_CHECKPOINT_DIR)
                        or "").strip()
        self.enabled = bool(self.root)
        self.sync_every = conf.get(cfg.STREAMING_CHECKPOINT_WAL_SYNC)
        self.retain = conf.get(cfg.STREAMING_CHECKPOINT_RETAIN)
        self.interval_folds = max(
            int(conf.get(cfg.STREAMING_CHECKPOINT_INTERVAL)), 1)
        self.async_write = bool(
            conf.get(cfg.STREAMING_CHECKPOINT_ASYNC))
        self.on_sigterm = bool(
            conf.get(cfg.STREAMING_CHECKPOINT_ON_SIGTERM))
        self._lock = lockorder.make_lock("service.streaming.checkpoint")
        self._wals: Dict[str, StreamWal] = {}
        self._stores: Dict[Tuple[str, str], CheckpointStore] = {}
        self._writer: Optional[_CheckpointWriter] = None
        if self.enabled:
            os.makedirs(self.root, exist_ok=True)

    # -- registry ------------------------------------------------------

    def table_dir(self, table_name: str) -> str:
        return os.path.join(self.root, "tables", safe_name(table_name))

    def query_dir(self, table_name: str, query_name: str) -> str:
        return os.path.join(self.root, "queries",
                            safe_name(table_name),
                            safe_name(query_name))

    def wal_for(self, table_name: str) -> StreamWal:
        with self._lock:
            wal = self._wals.get(table_name)
            if wal is None:
                wal = StreamWal(self.table_dir(table_name),
                                sync_every=self.sync_every)
                self._wals[table_name] = wal
            return wal

    def store_for(self, table_name: str,
                  query_name: str) -> CheckpointStore:
        with self._lock:
            key = (table_name, query_name)
            store = self._stores.get(key)
            if store is None:
                if self.async_write and self._writer is None:
                    self._writer = _CheckpointWriter()
                store = CheckpointStore(
                    self.query_dir(table_name, query_name),
                    retain=self.retain, writer=self._writer)
                self._stores[key] = store
            return store

    # -- accounting ----------------------------------------------------

    def pending_bytes(self) -> int:
        """Host bytes the durability layer holds in flight: unsynced
        WAL tails + checkpoint blobs queued on the async writer —
        charged next to cached fragments and streaming state so
        durability I/O cannot stealth-OOM admission."""
        with self._lock:
            wals = list(self._wals.values())
            writer = self._writer
        n = sum(w.pending_bytes() for w in wals)
        if writer is not None:
            n += writer.pending_bytes()
        return n

    def drain(self) -> None:
        """Block until every queued checkpoint committed and every WAL
        fsync'd (graceful-shutdown barrier)."""
        with self._lock:
            wals = list(self._wals.values())
            writer = self._writer
        if writer is not None:
            writer.drain()
        for w in wals:
            w.sync()

    def close(self) -> None:
        with self._lock:
            wals = list(self._wals.values())
            writer, self._writer = self._writer, None
            self._wals = {}
            self._stores = {}
        if writer is not None:
            writer.stop()
        for w in wals:
            w.close()

    # -- startup discovery --------------------------------------------

    def recover_report(self) -> dict:
        """What the checkpoint dir holds, without loading any state:
        persisted table WALs and each persisted query's latest VALID
        checkpoint meta (invalid candidates are skipped silently here
        — register-time recovery counts the rejects). The
        ``StreamingManager.recover()`` return value."""
        report: dict = {"enabled": self.enabled, "root": self.root,
                        "tables": [], "queries": []}
        if not self.enabled:
            return report
        tdir = os.path.join(self.root, "tables")
        if os.path.isdir(tdir):
            for name in sorted(os.listdir(tdir)):
                wal_path = os.path.join(tdir, name, "wal.log")
                if os.path.exists(wal_path):
                    report["tables"].append({
                        "dir": name,
                        "wal_bytes": os.path.getsize(wal_path)})
        qdir = os.path.join(self.root, "queries")
        if os.path.isdir(qdir):
            for tname in sorted(os.listdir(qdir)):
                for qname in sorted(os.listdir(
                        os.path.join(qdir, tname))):
                    store = CheckpointStore(
                        os.path.join(qdir, tname, qname),
                        retain=self.retain)
                    loaded = store.load_latest(count_rejects=False)
                    report["queries"].append({
                        "dir": f"{tname}/{qname}",
                        "checkpoints": store.checkpoint_count(),
                        "latest_meta": loaded[0] if loaded else None})
        return report
