"""Streaming ingestion & incremental queries (PR 14).

Micro-batch appends land as versioned deltas on a StreamTableSource
(bumping the table's snapshot version so every cached result over the
old contents invalidates for free); standing queries fold each delta
into long-lived partial-aggregate state via the update/merge seam in
execs/aggregate — one update launch + one merge launch per fold,
O(batch) regardless of how much history the table holds. See
docs/streaming.md.
"""
from spark_rapids_tpu.service.streaming.manager import StreamingManager
from spark_rapids_tpu.service.streaming.source import (DeltaBatchSource,
                                                       StreamTableSource)
from spark_rapids_tpu.service.streaming.standing import (
    StandingQuery, StreamingStateOverflow)
from spark_rapids_tpu.service.streaming.state import \
    StreamingAggregateState

__all__ = [
    "StreamingManager", "StreamTableSource", "DeltaBatchSource",
    "StandingQuery", "StreamingAggregateState",
    "StreamingStateOverflow",
]
