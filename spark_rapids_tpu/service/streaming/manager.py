"""StreamingManager: the service-side registry of standing queries.

One per QueryService, like the CacheManager. It owns the source ->
standing-queries index, routes every ``ingest()`` append to the
standing queries folding that table, catches a new registration up on
deltas that landed before it existed, and aggregates the streaming
block for ServiceStats. Folding itself lives in StandingQuery /
StreamingAggregateState — the manager only decides WHO folds.

Delivery contract: ``ingest`` returns after every live standing query
over the table has folded the delta (synchronous, in-order — the
per-query sequence cursor in ``StandingQuery.drain`` makes concurrent
ingests safe without a manager-wide fold lock). A standing query that
fails folds alone; the append itself and other standing queries over
the same table are unaffected.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.plan import incremental
from spark_rapids_tpu.service.streaming import stats as _stats
from spark_rapids_tpu.service.streaming.standing import StandingQuery
from spark_rapids_tpu.utils import lockorder

#: terminal standing queries kept in the registry for stats history
FINISHED_RETENTION = 64


class StreamingManager:
    def __init__(self, conf):
        from spark_rapids_tpu.service.streaming.durability import \
            StreamingDurability

        self.conf = conf
        self._lock = lockorder.make_lock("service.streaming.state")
        self._standing: Dict[int, StandingQuery] = {}
        #: id(source) -> standing queries folding that table
        self._by_source: Dict[int, List[StandingQuery]] = {}
        self._finished_order: List[int] = []
        self._shutdown = False
        #: durability layer (PR 19); inert unless
        #: rapids.tpu.streaming.checkpoint.dir is set
        self.durability = StreamingDurability(conf)

    # -- durability (PR 19) ------------------------------------------------

    def attach_source(self, source) -> None:
        """Make a streaming table durable: replay its WAL (restart
        recovery — the rebuilt deltas are what batch queries and
        standing-query catch-up see) and route every future append
        through the log. No-op when durability is off; idempotent."""
        if not self.durability.enabled:
            return
        wal = self.durability.wal_for(source.name)
        if getattr(source, "_wal", None) is wal:
            return
        records = wal.replay()
        if records and source.num_appends == 0:
            source.restore_deltas(records)
        source.attach_wal(wal)

    def recover(self) -> dict:
        """Startup discovery over the checkpoint dir: which tables have
        WALs, which queries have checkpoints and how far they got. The
        actual state loads happen lazily — WAL replay when the table is
        re-created (``attach_source``), checkpoint restore when the
        query re-registers — so recovery cost tracks what the caller
        actually resumes. Invoked from QueryService startup and the
        host-loss recovery path; returns the report for telemetry."""
        return self.durability.recover_report()

    def durability_pending_bytes(self) -> int:
        """In-flight durability bytes (unsynced WAL + queued checkpoint
        blobs) for the service admission charge."""
        if not self.durability.enabled:
            return 0
        return self.durability.pending_bytes()

    # -- registration ------------------------------------------------------

    def register_standing(self, df_or_plan, tenant: str = "default",
                          name: Optional[str] = None,
                          event_time_col: Optional[str] = None,
                          window_col: Optional[str] = None,
                          watermark_ms: Optional[int] = None,
                          late_policy: Optional[str] = None,
                          max_state_bytes: Optional[int] = None,
                          deadline: Optional[float] = None
                          ) -> StandingQuery:
        """Validate + register a continuous query; returns its handle
        after catching up on every delta already appended to the table
        (one fold per pre-existing micro-batch, so registration cost is
        O(existing data) exactly once and O(batch) forever after)."""
        if not self.conf.get(cfg.STREAMING_ENABLED):
            raise RuntimeError(
                "streaming is disabled "
                f"({cfg.STREAMING_ENABLED.key}=false)")
        with self._lock:
            if self._shutdown:
                raise RuntimeError("QueryService is shut down")
        plan = getattr(df_or_plan, "_plan", df_or_plan)
        info = incremental.analyze(plan)
        if watermark_ms is None:
            watermark_ms = self.conf.get(cfg.STREAMING_WATERMARK_MS)
        if late_policy is None:
            late_policy = self.conf.get(cfg.STREAMING_LATE_POLICY)
        if max_state_bytes is None:
            max_state_bytes = self.conf.get(
                cfg.STREAMING_MAX_STATE_BYTES)
        sq = StandingQuery(tenant, plan, info, self.conf, name=name,
                           event_time_col=event_time_col,
                           window_col=window_col,
                           watermark_ms=watermark_ms,
                           late_policy=late_policy,
                           max_state_bytes=max_state_bytes,
                           deadline=deadline)
        with self._lock:
            if self._shutdown:
                sq.cancel()
                raise RuntimeError("QueryService is shut down")
            self._standing[sq.query_id] = sq
            self._by_source.setdefault(id(sq.source), []).append(sq)
        _stats.bump("standing_registered")
        if self.durability.enabled and \
                getattr(sq.source, "name", None):
            # durability wiring BEFORE the catch-up drain: a restored
            # checkpoint advances the sequence cursor, so the drain
            # below replays exactly the WAL suffix past it — each
            # delta folds once across a restart (exactly-once). Note
            # the checkpoint identity is (table, query name): pass a
            # stable ``name`` to resume across processes.
            sq.attach_durability(
                self.durability.store_for(sq.source.name, sq.name),
                self.durability.interval_folds)
            sq.restore_from_checkpoint()
        # catch-up: deltas appended before registration fold now; any
        # append racing this call is folded exactly once — either by
        # its own ingest (the index is already published) or here (the
        # sequence cursor dedups)
        sq.drain()
        return sq

    # -- ingestion ---------------------------------------------------------

    def ingest(self, source, data, validity: Optional[dict] = None
               ) -> int:
        """Append one micro-batch to ``source`` and fold it into every
        live standing query over that table; returns the rows landed.
        The append itself (and its snapshot bump) happens even with no
        standing queries registered — batch queries still see it."""
        with self._lock:
            if self._shutdown:
                raise RuntimeError("QueryService is shut down")
        delta = source.append(data, validity)
        with self._lock:
            targets = [sq for sq in self._by_source.get(id(source), ())
                       if not sq.terminal]
        for sq in targets:
            sq.drain()
            if sq.terminal:
                self._retire(sq)
        return delta.num_rows

    def _retire(self, sq: StandingQuery) -> None:
        """Move a terminal standing query out of the source index (so
        future ingests stop considering it) while keeping it in the
        bounded registry for stats history."""
        with self._lock:
            lst = self._by_source.get(id(sq.source))
            if lst is not None:
                self._by_source[id(sq.source)] = \
                    [s for s in lst if s is not sq]
                if not self._by_source[id(sq.source)]:
                    del self._by_source[id(sq.source)]
            if sq.query_id not in self._finished_order:
                self._finished_order.append(sq.query_id)
            while len(self._finished_order) > FINISHED_RETENTION:
                self._standing.pop(self._finished_order.pop(0), None)

    # -- lookup / cancel ---------------------------------------------------

    def standing(self, standing_id: int) -> Optional[StandingQuery]:
        with self._lock:
            return self._standing.get(standing_id)

    def list_standing(self) -> List[StandingQuery]:
        with self._lock:
            return list(self._standing.values())

    def cancel_standing(self, standing_id: int) -> bool:
        sq = self.standing(standing_id)
        if sq is None:
            return False
        ok = sq.cancel()
        self._retire(sq)
        return ok

    # -- accounting --------------------------------------------------------

    def standing_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._standing.values()
                       if not s.terminal)

    def device_resident_bytes(self) -> int:
        """Streaming state currently sitting in HBM — charged against
        the admission budget next to the cache's device-resident
        fragments, so standing-query state and inflight batch queries
        never overcommit the device between them."""
        with self._lock:
            live = [s for s in self._standing.values()
                    if not s.terminal]
        return sum(s.agg_state.device_resident_bytes() for s in live)

    def stats(self) -> dict:
        """The ServiceStats ``streaming`` block: process counters plus
        this service's standing-query registry."""
        with self._lock:
            sqs = list(self._standing.values())
        live = [s for s in sqs if not s.terminal]
        out = dict(_stats.snapshot())
        out.update({
            "standing_live": len(live),
            "state_bytes": sum(s.agg_state.state_bytes()
                               for s in live),
            "device_resident_bytes": sum(
                s.agg_state.device_resident_bytes() for s in live),
            "watermark_lag_ms": max(
                (s.watermark_lag_ms for s in live), default=0),
            "standing": [s.info() for s in sqs],
        })
        return out

    # -- teardown ----------------------------------------------------------

    def shutdown(self) -> None:
        """Stop every live standing query and refuse future work. With
        durability on this is graceful: each query writes a final
        checkpoint and parks as SUSPENDED (restartable), queued
        checkpoint commits drain, WAL tails fsync. Without durability
        it is the original cancel — state discarded through the normal
        teardown."""
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            sqs = list(self._standing.values())
        durable = self.durability.enabled
        for sq in sqs:
            if not sq.terminal:
                if durable:
                    sq.suspend()
                else:
                    sq.cancel()
        if durable:
            self.durability.drain()
        self.durability.close()
