"""StandingQuery: register once, fold forever, emit on demand.

Lifecycle::

    REGISTERED --fold--> FOLDING --ok--> EMITTING --fold--> FOLDING ...
         |                  |                |
         +---- cancel ------+--- cancel ----+---> CANCELLED
         +---- error/deadline/state-overflow ---> FAILED

Terminal transitions run the SAME teardown as a cancelled batch query
(PR 2): close the running state and ``remove_owner`` the catalog tag,
so nothing a fold ever registered — running partials, delta-side
shuffle blocks, delta-side broadcast builds — can outlive the query.
The leak fence asserts ``owner_refcounts(tag)`` is empty afterwards.

Watermarks: with an ``event_time_col`` (int milliseconds in the stream
schema) the query keeps ``wm = max(event_time seen) - watermark_ms``,
monotonically non-decreasing. A row arriving at-or-below the current
watermark is LATE: policy ``merge`` folds it through the same merge
specs as on-time rows (aggregates self-correct on the next emit),
``drop`` discards it host-side before the update launch. With a
``window_col`` (a grouping column holding each window's END in
milliseconds), ``results(final_only=True)`` returns only windows whose
end is at-or-below the watermark — finalized, no in-flight data can
still move them on-time; only late-merge can, which is the documented
late-data contract.
"""
from __future__ import annotations

import itertools
import time
from typing import Optional

import numpy as np

from spark_rapids_tpu.plan import incremental
from spark_rapids_tpu.service.types import (DeadlineExceeded,
                                            QueryCancelled)
from spark_rapids_tpu.utils import lockorder

_STANDING_IDS = itertools.count(1)

LATE_POLICIES = ("merge", "drop")

#: lifecycle states (string-valued like QueryState, but a standing
#: query has no QUEUED/ADMITTED — folds are service-internal pushes,
#: not admitted submissions)
REGISTERED = "REGISTERED"
FOLDING = "FOLDING"
EMITTING = "EMITTING"
CANCELLED = "CANCELLED"
FAILED = "FAILED"
TERMINAL = frozenset({CANCELLED, FAILED})


class StandingCancelled(RuntimeError):
    """Internal fold-abort signal raised by the cancel check."""


class StreamingStateOverflow(RuntimeError):
    """The running state outgrew rapids.tpu.streaming.maxStateBytes;
    the standing query FAILED and its state was torn down."""


class StandingQuery:
    """One registered continuous query over one streaming table. The
    handle the service returns from ``register_standing`` — callers
    poll ``state``, read ``results()``, and ``cancel()``."""

    def __init__(self, tenant: str, plan,
                 info: incremental.IncrementalInfo, conf, *,
                 name: Optional[str] = None,
                 event_time_col: Optional[str] = None,
                 window_col: Optional[str] = None,
                 watermark_ms: int = 0, late_policy: str = "merge",
                 max_state_bytes: int = 0,
                 deadline: Optional[float] = None):
        from spark_rapids_tpu.service.streaming.state import \
            StreamingAggregateState

        self.query_id = next(_STANDING_IDS)
        self.name = name or f"standing{self.query_id}"
        self.tenant = tenant
        self.plan = plan
        self.source = info.stream_source
        stream_schema = info.stream_source.schema()
        if event_time_col is not None and \
                event_time_col not in stream_schema.names:
            raise ValueError(
                f"event_time_col {event_time_col!r} is not a column of "
                f"the streaming table ({list(stream_schema.names)})")
        out_names = info.output_names()
        if window_col is not None and window_col not in out_names:
            raise ValueError(
                f"window_col {window_col!r} is not an output column "
                f"({list(out_names)}) — it must be a grouping column "
                f"holding each window's end in milliseconds")
        if late_policy not in LATE_POLICIES:
            raise ValueError(f"late_policy must be one of "
                             f"{LATE_POLICIES}, got {late_policy!r}")
        self.event_time_col = event_time_col
        self.window_col = window_col
        self.watermark_ms = int(watermark_ms)
        self.late_policy = late_policy
        self.max_state_bytes = int(max_state_bytes)
        self.deadline_s = deadline
        self.registered_at = time.perf_counter()
        self.agg_state = StreamingAggregateState(info, conf,
                                                self.owner_tag)
        self.state = REGISTERED
        self.error: Optional[BaseException] = None
        self._cancel_requested = False
        self._next_seq = 0
        self._lock = lockorder.make_rlock("service.streaming.standing")
        #: event-time watermark in ms (None until the first timed row)
        self.watermark: Optional[int] = None
        self._max_event: Optional[int] = None
        self.late_rows_remerged = 0
        self.late_rows_dropped = 0
        self.last_fold_wall_s = 0.0
        self.last_fold_dispatches = 0.0
        self.fold_dispatches = 0.0
        self.retry: dict = {}
        #: test seam: called at every fold step boundary (the
        #: deterministic way to exercise cancel-mid-fold)
        self._fold_hook = None

    @property
    def owner_tag(self):
        return ("svc-stream", self.query_id)

    @property
    def terminal(self) -> bool:
        return self.state in TERMINAL

    @property
    def folds(self) -> int:
        return self.agg_state.folds

    @property
    def rows_folded(self) -> int:
        return self.agg_state.rows_folded

    # -- folding -------------------------------------------------------

    def drain(self) -> int:
        """Fold every not-yet-folded delta of the source, in append
        order; returns the number of deltas folded. Idempotent and
        safe under concurrent callers (ingest + registration catch-up):
        the per-query lock serializes, the sequence cursor dedups."""
        n = 0
        with self._lock:
            while not self.terminal:
                if self.deadline_s is not None and \
                        time.perf_counter() - self.registered_at > \
                        self.deadline_s:
                    self._teardown(FAILED, DeadlineExceeded(
                        f"standing query {self.query_id} exceeded its "
                        f"{self.deadline_s:.3f}s deadline"))
                    break
                pending = self.source.deltas_from(self._next_seq)
                if not pending:
                    break
                for delta in pending:
                    if self.terminal:
                        break
                    self._fold_one(delta)
                    n += 1
        return n

    def _cancel_check(self) -> None:
        if self._fold_hook is not None:
            self._fold_hook()
        if self._cancel_requested:
            raise StandingCancelled()

    def _fold_one(self, delta) -> None:
        """One micro-batch: late-data handling host-side, then the
        update+merge launches. Caller holds the lock."""
        from spark_rapids_tpu.service.streaming import stats as _stats
        from spark_rapids_tpu.utils import dispatch as _disp

        self._next_seq = delta.seq + 1
        data, validity, n = delta.data, delta.validity, delta.num_rows
        self.state = FOLDING
        t0 = time.perf_counter()
        pre = _disp.snapshot() if _disp.installed() else None
        try:
            self._cancel_check()
            if self.event_time_col is not None and n:
                data, validity, n = self._handle_late(data, validity, n)
            self.agg_state.fold(data, validity, n,
                                cancel_check=self._cancel_check)
            if self.max_state_bytes and \
                    self.agg_state.state_bytes() > self.max_state_bytes:
                raise StreamingStateOverflow(
                    f"standing query {self.query_id} state "
                    f"({self.agg_state.state_bytes()} bytes) exceeds "
                    f"rapids.tpu.streaming.maxStateBytes="
                    f"{self.max_state_bytes} — raise the bound or "
                    f"window the aggregation")
        except StandingCancelled:
            self._teardown(CANCELLED)
            return
        except BaseException as e:
            # the standing query dies; the ingest that fed it must not
            # (other standing queries and the append itself are fine)
            self._teardown(FAILED, e)
            return
        finally:
            self.last_fold_wall_s = time.perf_counter() - t0
            if pre is not None:
                d = float(_disp.delta(pre)["dispatch_count"])
                self.last_fold_dispatches = d
                self.fold_dispatches += d
                _stats.bump("fold_dispatches", int(d))
        _stats.bump("folds")
        _stats.bump("rows_folded", n)
        self.state = EMITTING

    def _handle_late(self, data, validity, n):
        """Split one arriving batch against the CURRENT watermark, then
        advance it. Late rows re-merge (policy merge) or are filtered
        host-side (policy drop); either way the watermark advances from
        the batch max so out-of-order arrival cannot retreat it."""
        from spark_rapids_tpu.service.streaming import stats as _stats

        ev = np.asarray(data[self.event_time_col]).astype(np.int64)
        wm = self.watermark
        if wm is not None:
            late = ev <= wm
            n_late = int(late.sum())
            if n_late:
                if self.late_policy == "drop":
                    keep = ~late
                    data = {k: v[keep] for k, v in data.items()}
                    validity = {k: v[keep]
                                for k, v in validity.items()}
                    n = int(keep.sum())
                    self.late_rows_dropped += n_late
                    _stats.bump("late_rows_dropped", n_late)
                else:
                    self.late_rows_remerged += n_late
                    _stats.bump("late_rows_remerged", n_late)
        if len(ev):
            batch_max = int(ev.max())
            self._max_event = batch_max if self._max_event is None \
                else max(self._max_event, batch_max)
            cand = self._max_event - self.watermark_ms
            self.watermark = cand if wm is None else max(wm, cand)
        return data, validity, n

    @property
    def watermark_lag_ms(self) -> int:
        """How far the watermark trails the newest event seen (>= the
        configured delay; grows only if the watermark is held back)."""
        if self._max_event is None or self.watermark is None:
            return 0
        return self._max_event - self.watermark

    # -- emission ------------------------------------------------------

    def results(self, final_only: bool = False):
        """Current aggregate as a pandas frame. ``final_only`` keeps
        only windows whose end is at-or-below the watermark (requires
        ``window_col``); without a watermark yet, nothing is final."""
        from spark_rapids_tpu.service.streaming import stats as _stats

        with self._lock:
            if self.state == CANCELLED:
                raise QueryCancelled(
                    f"standing query {self.query_id} was cancelled")
            if self.state == FAILED:
                raise self.error or RuntimeError(
                    f"standing query {self.query_id} failed")
            frame = self.agg_state.emit()
            _stats.bump("emits")
            if final_only:
                if self.window_col is None:
                    raise ValueError(
                        "results(final_only=True) requires the query "
                        "to be registered with window_col")
                if self.watermark is None:
                    return frame.iloc[0:0]
                return frame[frame[self.window_col] <=
                             self.watermark].reset_index(drop=True)
            return frame

    # -- cancel / teardown ---------------------------------------------

    def cancel(self) -> bool:
        """Request cancellation; returns True when the query is (or
        already was) torn down on return. A fold in flight aborts at
        its next step boundary — this call then blocks briefly on the
        query lock until that teardown completes, so the caller never
        observes a cancelled query still holding catalog state."""
        self._cancel_requested = True
        with self._lock:
            if not self.terminal:
                self._teardown(CANCELLED)
            return self.state == CANCELLED

    def _teardown(self, state: str,
                  error: Optional[BaseException] = None) -> None:
        """Idempotent terminal transition: release EVERYTHING the query
        holds (running state + all owner-tagged catalog buffers + the
        per-owner retry ledger)."""
        from spark_rapids_tpu.memory import retry as _retry
        from spark_rapids_tpu.service.streaming import stats as _stats

        if self.terminal:
            return
        self.state = state
        self.error = error
        self.agg_state.close()
        self.retry = _retry.pop_owner_stats(self.owner_tag)
        _stats.bump("standing_cancelled" if state == CANCELLED
                    else "standing_failed")

    # -- observability -------------------------------------------------

    def info(self) -> dict:
        return {
            "standing_id": self.query_id,
            "name": self.name,
            "tenant": self.tenant,
            "state": self.state,
            "folds": self.folds,
            "rows_folded": self.rows_folded,
            "state_bytes": self.agg_state.state_bytes(),
            "watermark": self.watermark,
            "watermark_lag_ms": self.watermark_lag_ms,
            "late_rows_remerged": self.late_rows_remerged,
            "late_rows_dropped": self.late_rows_dropped,
            "last_fold_wall_s": round(self.last_fold_wall_s, 6),
            "last_fold_dispatches": self.last_fold_dispatches,
            "fold_dispatches": self.fold_dispatches,
        }
