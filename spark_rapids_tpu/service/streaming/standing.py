"""StandingQuery: register once, fold forever, emit on demand.

Lifecycle::

    REGISTERED --fold--> FOLDING --ok--> EMITTING --fold--> FOLDING ...
         |                  |                |
         +---- cancel ------+--- cancel ----+---> CANCELLED
         +---- error/deadline/state-overflow ---> FAILED

Terminal transitions run the SAME teardown as a cancelled batch query
(PR 2): close the running state and ``remove_owner`` the catalog tag,
so nothing a fold ever registered — running partials, delta-side
shuffle blocks, delta-side broadcast builds — can outlive the query.
The leak fence asserts ``owner_refcounts(tag)`` is empty afterwards.

Watermarks: with an ``event_time_col`` (int milliseconds in the stream
schema) the query keeps ``wm = max(event_time seen) - watermark_ms``,
monotonically non-decreasing. A row arriving at-or-below the current
watermark is LATE: policy ``merge`` folds it through the same merge
specs as on-time rows (aggregates self-correct on the next emit),
``drop`` discards it host-side before the update launch. With a
``window_col`` (a grouping column holding each window's END in
milliseconds), ``results(final_only=True)`` returns only windows whose
end is at-or-below the watermark — finalized, no in-flight data can
still move them on-time; only late-merge can, which is the documented
late-data contract.
"""
from __future__ import annotations

import itertools
import time
from typing import Optional

import numpy as np

from spark_rapids_tpu.plan import incremental
from spark_rapids_tpu.service.types import (DeadlineExceeded,
                                            QueryCancelled)
from spark_rapids_tpu.utils import lockorder

_STANDING_IDS = itertools.count(1)

LATE_POLICIES = ("merge", "drop")

#: lifecycle states (string-valued like QueryState, but a standing
#: query has no QUEUED/ADMITTED — folds are service-internal pushes,
#: not admitted submissions)
REGISTERED = "REGISTERED"
FOLDING = "FOLDING"
EMITTING = "EMITTING"
CANCELLED = "CANCELLED"
FAILED = "FAILED"
#: graceful-shutdown terminal (PR 19): state checkpointed, query
#: restartable by re-registering against the same checkpoint dir —
#: unlike CANCELLED/FAILED the work is parked, not discarded
SUSPENDED = "SUSPENDED"
TERMINAL = frozenset({CANCELLED, FAILED, SUSPENDED})


class StandingCancelled(RuntimeError):
    """Internal fold-abort signal raised by the cancel check."""


class StreamingStateOverflow(RuntimeError):
    """The running state outgrew rapids.tpu.streaming.maxStateBytes;
    the standing query FAILED and its state was torn down."""


class StandingQuery:
    """One registered continuous query over one streaming table. The
    handle the service returns from ``register_standing`` — callers
    poll ``state``, read ``results()``, and ``cancel()``."""

    def __init__(self, tenant: str, plan,
                 info: incremental.IncrementalInfo, conf, *,
                 name: Optional[str] = None,
                 event_time_col: Optional[str] = None,
                 window_col: Optional[str] = None,
                 watermark_ms: int = 0, late_policy: str = "merge",
                 max_state_bytes: int = 0,
                 deadline: Optional[float] = None):
        from spark_rapids_tpu.service.streaming.state import \
            StreamingAggregateState

        self.query_id = next(_STANDING_IDS)
        self.name = name or f"standing{self.query_id}"
        self.tenant = tenant
        self.plan = plan
        self.source = info.stream_source
        stream_schema = info.stream_source.schema()
        if event_time_col is not None and \
                event_time_col not in stream_schema.names:
            raise ValueError(
                f"event_time_col {event_time_col!r} is not a column of "
                f"the streaming table ({list(stream_schema.names)})")
        out_names = info.output_names()
        if window_col is not None and window_col not in out_names:
            raise ValueError(
                f"window_col {window_col!r} is not an output column "
                f"({list(out_names)}) — it must be a grouping column "
                f"holding each window's end in milliseconds")
        if late_policy not in LATE_POLICIES:
            raise ValueError(f"late_policy must be one of "
                             f"{LATE_POLICIES}, got {late_policy!r}")
        self.event_time_col = event_time_col
        self.window_col = window_col
        self.watermark_ms = int(watermark_ms)
        self.late_policy = late_policy
        self.max_state_bytes = int(max_state_bytes)
        self.deadline_s = deadline
        self.registered_at = time.perf_counter()
        self.agg_state = StreamingAggregateState(info, conf,
                                                self.owner_tag)
        #: plan signature a checkpoint must match to be restored: the
        #: stream schema plus the query's output columns — a changed
        #: query shape silently adopting old partials would be wrong
        #: answers, so a mismatch falls back to a full WAL refold
        self.signature = {
            "stream": [[n, getattr(t, "name", str(t))]
                       for n, t in zip(stream_schema.names,
                                       stream_schema.types)],
            "output": list(out_names),
        }
        #: durability hooks (PR 19); attached by the manager when the
        #: checkpoint dir is configured
        self._ckpt_store = None
        self._ckpt_interval = 1
        self.state = REGISTERED
        self.error: Optional[BaseException] = None
        self._cancel_requested = False
        self._next_seq = 0
        self._lock = lockorder.make_rlock("service.streaming.standing")
        #: event-time watermark in ms (None until the first timed row)
        self.watermark: Optional[int] = None
        self._max_event: Optional[int] = None
        self.late_rows_remerged = 0
        self.late_rows_dropped = 0
        self.last_fold_wall_s = 0.0
        self.last_fold_dispatches = 0.0
        self.fold_dispatches = 0.0
        self.retry: dict = {}
        #: test seam: called at every fold step boundary (the
        #: deterministic way to exercise cancel-mid-fold)
        self._fold_hook = None

    @property
    def owner_tag(self):
        return ("svc-stream", self.query_id)

    @property
    def terminal(self) -> bool:
        return self.state in TERMINAL

    @property
    def folds(self) -> int:
        return self.agg_state.folds

    @property
    def rows_folded(self) -> int:
        return self.agg_state.rows_folded

    # -- folding -------------------------------------------------------

    def drain(self) -> int:
        """Fold every not-yet-folded delta of the source, in append
        order; returns the number of deltas folded. Idempotent and
        safe under concurrent callers (ingest + registration catch-up):
        the per-query lock serializes, the sequence cursor dedups."""
        n = 0
        with self._lock:
            while not self.terminal:
                if self.deadline_s is not None and \
                        time.perf_counter() - self.registered_at > \
                        self.deadline_s:
                    self._teardown(FAILED, DeadlineExceeded(
                        f"standing query {self.query_id} exceeded its "
                        f"{self.deadline_s:.3f}s deadline"))
                    break
                pending = self.source.deltas_from(self._next_seq)
                if not pending:
                    break
                for delta in pending:
                    if self.terminal:
                        break
                    self._fold_one(delta)
                    n += 1
        return n

    def _cancel_check(self) -> None:
        if self._fold_hook is not None:
            self._fold_hook()
        if self._cancel_requested:
            raise StandingCancelled()

    def _fold_one(self, delta) -> None:
        """One micro-batch: late-data handling host-side, then the
        update+merge launches. Caller holds the lock. With durability
        attached, a recoverable in-fold fault (fetch/transport) gets
        ONE local retry — the running state only swaps as the fold's
        last step, so re-driving the delta is safe — before the query
        fails over to restart recovery."""
        from spark_rapids_tpu.runtime import recovery as _recovery
        from spark_rapids_tpu.service.streaming import stats as _stats
        from spark_rapids_tpu.shuffle.fault_injection import get_injector
        from spark_rapids_tpu.shuffle.iterator import \
            ShuffleFetchFailedError
        from spark_rapids_tpu.shuffle.transport import TransportError
        from spark_rapids_tpu.utils import dispatch as _disp

        self._next_seq = delta.seq + 1
        n = delta.num_rows
        self.state = FOLDING
        if get_injector().should_crash_at_fold():
            # models an unclean host death mid-fold: the WAL already
            # holds this delta (append is write-ahead), no checkpoint
            # holds this fold — restart recovery must refold it
            import os
            import signal
            os.kill(os.getpid(), signal.SIGKILL)
        t0 = time.perf_counter()
        pre = _disp.snapshot() if _disp.installed() else None
        try:
            attempts = 2 if self._ckpt_store is not None else 1
            for attempt in range(attempts):
                wm_save = (self.watermark, self._max_event,
                           self.late_rows_remerged,
                           self.late_rows_dropped)
                data, validity, n = (delta.data, delta.validity,
                                     delta.num_rows)
                try:
                    self._cancel_check()
                    if self.event_time_col is not None and n:
                        data, validity, n = self._handle_late(
                            data, validity, n)
                    self.agg_state.fold(
                        data, validity, n,
                        cancel_check=self._cancel_check)
                    break
                except (ShuffleFetchFailedError, TransportError):
                    if attempt + 1 >= attempts:
                        raise
                    # rewind the watermark/late accounting the failed
                    # attempt advanced, then re-drive the same delta
                    (self.watermark, self._max_event,
                     self.late_rows_remerged,
                     self.late_rows_dropped) = wm_save
                    _recovery.bump("streaming_restores")
            if self.max_state_bytes and \
                    self.agg_state.state_bytes() > self.max_state_bytes:
                raise StreamingStateOverflow(
                    f"standing query {self.query_id} state "
                    f"({self.agg_state.state_bytes()} bytes) exceeds "
                    f"rapids.tpu.streaming.maxStateBytes="
                    f"{self.max_state_bytes} — raise the bound or "
                    f"window the aggregation")
        except StandingCancelled:
            self._teardown(CANCELLED)
            return
        except StreamingStateOverflow as e:
            # the fold that tripped the bound COMPLETED (the check runs
            # after the state swap) — persist it before failing, so a
            # restart with a raised budget resumes instead of refolding
            # the whole stream
            self._final_checkpoint("state-overflow")
            self._teardown(FAILED, e)
            return
        except BaseException as e:
            # the standing query dies; the ingest that fed it must not
            # (other standing queries and the append itself are fine)
            self._teardown(FAILED, e)
            return
        finally:
            self.last_fold_wall_s = time.perf_counter() - t0
            if pre is not None:
                d = float(_disp.delta(pre)["dispatch_count"])
                self.last_fold_dispatches = d
                self.fold_dispatches += d
                _stats.bump("fold_dispatches", int(d))
        _stats.bump("folds")
        _stats.bump("rows_folded", n)
        self.state = EMITTING
        self._maybe_checkpoint()

    def _handle_late(self, data, validity, n):
        """Split one arriving batch against the CURRENT watermark, then
        advance it. Late rows re-merge (policy merge) or are filtered
        host-side (policy drop); either way the watermark advances from
        the batch max so out-of-order arrival cannot retreat it."""
        from spark_rapids_tpu.service.streaming import stats as _stats

        ev = np.asarray(data[self.event_time_col]).astype(np.int64)
        wm = self.watermark
        if wm is not None:
            late = ev <= wm
            n_late = int(late.sum())
            if n_late:
                if self.late_policy == "drop":
                    keep = ~late
                    data = {k: v[keep] for k, v in data.items()}
                    validity = {k: v[keep]
                                for k, v in validity.items()}
                    n = int(keep.sum())
                    self.late_rows_dropped += n_late
                    _stats.bump("late_rows_dropped", n_late)
                else:
                    self.late_rows_remerged += n_late
                    _stats.bump("late_rows_remerged", n_late)
        if len(ev):
            batch_max = int(ev.max())
            self._max_event = batch_max if self._max_event is None \
                else max(self._max_event, batch_max)
            cand = self._max_event - self.watermark_ms
            self.watermark = cand if wm is None else max(wm, cand)
        return data, validity, n

    # -- durability (PR 19) --------------------------------------------

    def attach_durability(self, store, interval: int = 1) -> None:
        """Wire this query to its checkpoint store; folds checkpoint
        every ``interval`` folds and terminal transitions write final
        checkpoints. Must run before the catch-up drain."""
        self._ckpt_store = store
        self._ckpt_interval = max(int(interval), 1)

    def _ckpt_meta(self) -> dict:
        return {
            "query": self.name,
            "tenant": self.tenant,
            "table": getattr(self.source, "name", None),
            "signature": self.signature,
            "cursor": self._next_seq,
            "watermark": self.watermark,
            "max_event": self._max_event,
            "late_rows_remerged": self.late_rows_remerged,
            "late_rows_dropped": self.late_rows_dropped,
            "folds": self.folds,
            "rows_folded": self.rows_folded,
        }

    def checkpoint(self, synchronous: bool = False) -> Optional[int]:
        """Snapshot (running state, sequence cursor, watermark, late
        counters) to the checkpoint store; returns the checkpoint
        sequence, or None when durability is off. Caller holds the
        query lock (fold boundary) — the snapshot is consistent with
        the cursor by construction."""
        if self._ckpt_store is None:
            return None
        payload = self.agg_state.snapshot_host()
        meta = self._ckpt_meta()
        meta["has_state"] = payload is not None
        return self._ckpt_store.write(meta, payload,
                                      synchronous=synchronous)

    def _maybe_checkpoint(self) -> None:
        if self._ckpt_store is None or \
                self.folds % self._ckpt_interval != 0:
            return
        try:
            self.checkpoint()
        except OSError:
            import logging
            logging.getLogger(__name__).exception(
                "checkpoint of standing query %d failed; the query "
                "keeps folding — recovery falls back to an older "
                "checkpoint or the WAL", self.query_id)

    def _final_checkpoint(self, why: str) -> None:
        """Synchronous terminal-transition checkpoint (overflow,
        suspend): the process may be about to exit, the bytes must
        land now. Runs BEFORE teardown closes the state."""
        from spark_rapids_tpu.service.streaming import stats as _stats

        if self._ckpt_store is None:
            return
        try:
            self.checkpoint(synchronous=True)
            _stats.bump("final_checkpoints")
        except OSError:
            import logging
            logging.getLogger(__name__).exception(
                "final (%s) checkpoint of standing query %d failed; "
                "recovery falls back to the last periodic checkpoint "
                "or the WAL", why, self.query_id)

    def restore_from_checkpoint(self) -> bool:
        """Adopt the newest valid checkpoint whose plan signature
        matches; returns True when state+cursor were restored. Runs at
        registration BEFORE the catch-up drain, so the drain replays
        exactly the WAL suffix past the checkpoint cursor — each delta
        folds exactly once across the restart. No valid or matching
        checkpoint -> False, and the ordinary catch-up performs a full
        refold from the (replayed) source."""
        from spark_rapids_tpu.runtime import recovery as _recovery
        from spark_rapids_tpu.service.streaming import stats as _stats

        if self._ckpt_store is None:
            return False
        with self._lock:
            loaded = self._ckpt_store.load_latest()
            if loaded is None:
                return False
            meta, payload = loaded
            if meta.get("signature") != self.signature:
                import logging
                logging.getLogger(__name__).warning(
                    "checkpoint for standing query %r has a different "
                    "plan signature; ignoring it and refolding from "
                    "the WAL", self.name)
                return False
            has_state = bool(meta.get("has_state"))
            self.agg_state.restore_running(
                payload if has_state else None,
                meta.get("folds", 0), meta.get("rows_folded", 0))
            self._next_seq = int(meta.get("cursor", 0))
            self.watermark = meta.get("watermark")
            self._max_event = meta.get("max_event")
            self.late_rows_remerged = int(
                meta.get("late_rows_remerged", 0))
            self.late_rows_dropped = int(
                meta.get("late_rows_dropped", 0))
            self.state = EMITTING if has_state else REGISTERED
        _stats.bump("recoveries")
        _recovery.bump("streaming_restores")
        return True

    def suspend(self) -> bool:
        """Graceful-shutdown terminal: write a final synchronous
        checkpoint, then tear down to SUSPENDED. The query's answer
        survives — a restart against the same checkpoint dir resumes
        it — which is why service shutdown prefers this over
        ``cancel()`` when durability is on."""
        with self._lock:
            if self.terminal:
                return self.state == SUSPENDED
            self._final_checkpoint("suspend")
            self._teardown(SUSPENDED)
            return True

    @property
    def watermark_lag_ms(self) -> int:
        """How far the watermark trails the newest event seen (>= the
        configured delay; grows only if the watermark is held back)."""
        if self._max_event is None or self.watermark is None:
            return 0
        return self._max_event - self.watermark

    # -- emission ------------------------------------------------------

    def results(self, final_only: bool = False):
        """Current aggregate as a pandas frame. ``final_only`` keeps
        only windows whose end is at-or-below the watermark (requires
        ``window_col``); without a watermark yet, nothing is final."""
        from spark_rapids_tpu.service.streaming import stats as _stats

        with self._lock:
            if self.state == CANCELLED:
                raise QueryCancelled(
                    f"standing query {self.query_id} was cancelled")
            if self.state == SUSPENDED:
                raise QueryCancelled(
                    f"standing query {self.query_id} was suspended at "
                    "shutdown; its state is checkpointed under "
                    "rapids.tpu.streaming.checkpoint.dir — register "
                    "the query again to resume and read results there")
            if self.state == FAILED:
                raise self.error or RuntimeError(
                    f"standing query {self.query_id} failed")
            frame = self.agg_state.emit()
            _stats.bump("emits")
            if final_only:
                if self.window_col is None:
                    raise ValueError(
                        "results(final_only=True) requires the query "
                        "to be registered with window_col")
                if self.watermark is None:
                    return frame.iloc[0:0]
                return frame[frame[self.window_col] <=
                             self.watermark].reset_index(drop=True)
            return frame

    # -- cancel / teardown ---------------------------------------------

    def cancel(self) -> bool:
        """Request cancellation; returns True when the query is (or
        already was) torn down on return. A fold in flight aborts at
        its next step boundary — this call then blocks briefly on the
        query lock until that teardown completes, so the caller never
        observes a cancelled query still holding catalog state."""
        self._cancel_requested = True
        with self._lock:
            if not self.terminal:
                self._teardown(CANCELLED)
            return self.state == CANCELLED

    def _teardown(self, state: str,
                  error: Optional[BaseException] = None) -> None:
        """Idempotent terminal transition: release EVERYTHING the query
        holds (running state + all owner-tagged catalog buffers + the
        per-owner retry ledger)."""
        from spark_rapids_tpu.memory import retry as _retry
        from spark_rapids_tpu.service.streaming import stats as _stats

        if self.terminal:
            return
        self.state = state
        self.error = error
        self.agg_state.close()
        self.retry = _retry.pop_owner_stats(self.owner_tag)
        _stats.bump({CANCELLED: "standing_cancelled",
                     SUSPENDED: "standing_suspended"}.get(
                         state, "standing_failed"))

    # -- observability -------------------------------------------------

    def info(self) -> dict:
        return {
            "standing_id": self.query_id,
            "name": self.name,
            "tenant": self.tenant,
            "state": self.state,
            "folds": self.folds,
            "rows_folded": self.rows_folded,
            "state_bytes": self.agg_state.state_bytes(),
            "watermark": self.watermark,
            "watermark_lag_ms": self.watermark_lag_ms,
            "late_rows_remerged": self.late_rows_remerged,
            "late_rows_dropped": self.late_rows_dropped,
            "last_fold_wall_s": round(self.last_fold_wall_s, 6),
            "last_fold_dispatches": self.last_fold_dispatches,
            "fold_dispatches": self.fold_dispatches,
            "durable": self._ckpt_store is not None,
        }
