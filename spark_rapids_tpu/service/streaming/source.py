"""Streaming table sources: versioned micro-batch deltas.

``StreamTableSource`` is the append surface. Each ``append()`` lands a
validated micro-batch delta and bumps the table's snapshot version
(service/cache/snapshots) — so it is the third snapshot writer after
view replacement and file mtime changes, and every cached result or
fragment computed over the old contents misses for free. ``read_host``
returns the concatenation of ALL deltas: a batch query over the table
sees exactly what a standing query has folded, which is what makes the
batch engine the oracle for incremental-vs-batch equivalence.

``DeltaBatchSource`` is the mutable leaf the per-fold exec tree reads:
the streaming state points it at one micro-batch, drives the tree, and
moves on — the fold's cost tracks the delta, never the table.
"""
from __future__ import annotations

import itertools
from typing import Dict, Optional, Tuple

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.plan.nodes import DataSource
from spark_rapids_tpu.utils import lockorder

#: process-global uid stream — cache identities must differ across two
#: same-named tables in different Sessions (same reasoning as the
#: service's global query ids)
_STREAM_UIDS = itertools.count(1)


def normalize_batch(data, schema: Schema,
                    validity: Optional[dict] = None
                    ) -> Tuple[Dict[str, np.ndarray],
                               Dict[str, np.ndarray], int]:
    """Validate one micro-batch against ``schema``: every column
    present, equal lengths, numpy-backed. Accepts a dict of columns or
    a pandas DataFrame (NaN/None -> validity mask, like
    Session.create_dataframe). Returns (data, validity, n_rows)."""
    import pandas as pd

    validity = dict(validity or {})
    if isinstance(data, pd.DataFrame):
        cols: Dict[str, np.ndarray] = {}
        for name in data.columns:
            s = data[name]
            if s.dtype == object or str(s.dtype) == "string":
                cols[name] = np.array(
                    [None if v is None or (isinstance(v, float) and
                                           np.isnan(v)) else v
                     for v in s], dtype=object)
            else:
                isna = s.isna().to_numpy(dtype=bool)
                cols[name] = s.fillna(0).to_numpy()
                if isna.any():
                    validity[name] = ~isna
        data = cols
    missing = [n for n in schema.names if n not in data]
    if missing:
        raise ValueError(f"append is missing columns {missing}; the "
                         f"table schema is {list(schema.names)}")
    out: Dict[str, np.ndarray] = {}
    n = None
    for name, t in zip(schema.names, schema.types):
        arr = data[name]
        if t is dt.STRING:
            arr = np.asarray(arr, dtype=object)
        else:
            arr = np.asarray(arr)
        if n is None:
            n = len(arr)
        elif len(arr) != n:
            raise ValueError(
                f"ragged append: column {name!r} has {len(arr)} rows, "
                f"expected {n}")
        out[name] = arr
    vout = {k: np.asarray(v, dtype=bool) for k, v in validity.items()
            if k in out}
    return out, vout, int(n or 0)


def _empty_columns(schema: Schema) -> Dict[str, np.ndarray]:
    return {name: np.empty(0, dtype=object) if t is dt.STRING
            else np.zeros(0, dtype=t.np_dtype)
            for name, t in zip(schema.names, schema.types)}


def _concat_deltas(schema: Schema, deltas) -> tuple:
    """(data, validity) over a delta list — the all-true filler makes
    per-delta validity compose with deltas that had none."""
    if not deltas:
        return _empty_columns(schema), {}
    if len(deltas) == 1:
        d = deltas[0]
        return dict(d.data), dict(d.validity)
    data: Dict[str, np.ndarray] = {}
    validity: Dict[str, np.ndarray] = {}
    for name in schema.names:
        data[name] = np.concatenate([d.data[name] for d in deltas])
        if any(name in d.validity for d in deltas):
            validity[name] = np.concatenate(
                [d.validity.get(name,
                                np.ones(d.num_rows, dtype=bool))
                 for d in deltas])
    return data, validity


class _Delta:
    __slots__ = ("seq", "data", "validity", "num_rows")

    def __init__(self, seq: int, data, validity, num_rows: int):
        self.seq = seq
        self.data = data
        self.validity = validity
        self.num_rows = num_rows


class StreamTableSource(DataSource):
    """Appendable host table. Thread-safe: appends and reads copy the
    delta list under the source lock and do the heavy concatenation
    outside it."""

    #: the marker plan/incremental.py recognizes streaming scans by
    is_streaming = True

    def __init__(self, name: str, schema: Schema):
        self.name = name
        self._schema = schema
        self._uid = next(_STREAM_UIDS)
        self._deltas: list = []
        self._total_rows = 0
        # independent of len(_deltas): after a WAL replay that
        # truncated a torn tail, the next append must continue the
        # durable numbering, and restored deltas keep their logged seqs
        self._next_seq = 0
        #: durability hook (PR 19): when attached, every append is
        #: persisted to this WAL before the delta becomes visible
        self._wal = None
        self._lock = lockorder.make_lock("service.streaming.source")

    # -- DataSource ----------------------------------------------------

    def schema(self) -> Schema:
        return self._schema

    def read_host(self):
        with self._lock:
            deltas = list(self._deltas)
        return _concat_deltas(self._schema, deltas)

    def estimated_row_count(self):
        with self._lock:
            return self._total_rows

    # -- append surface ------------------------------------------------

    def append(self, data, validity: Optional[dict] = None) -> _Delta:
        """Land one micro-batch; returns its delta record. Bumping the
        snapshot version HERE (not in the service) means even a bare
        source append — no service, no standing queries — invalidates
        every cached result computed over the old contents."""
        from spark_rapids_tpu.service.cache import snapshots
        from spark_rapids_tpu.service.streaming import stats as _stats

        ndata, nvalidity, n = normalize_batch(data, self._schema,
                                              validity)
        with self._lock:
            delta = _Delta(self._next_seq, ndata, nvalidity, n)
            if self._wal is not None:
                # write-ahead: under the source lock so WAL order is
                # delta order, BEFORE the delta is appended so no fold
                # can ever see rows the log does not cover
                self._wal.append(delta.seq, ndata, nvalidity, n)
            self._next_seq += 1
            self._deltas.append(delta)
            self._total_rows += n
        snapshots.bump(self)
        _stats.bump("appends")
        _stats.bump("rows_appended", n)
        return delta

    @property
    def num_appends(self) -> int:
        with self._lock:
            return len(self._deltas)

    @property
    def total_rows(self) -> int:
        with self._lock:
            return self._total_rows

    def deltas_from(self, seq: int) -> list:
        """Deltas with sequence >= ``seq`` (registration catch-up)."""
        with self._lock:
            return [d for d in self._deltas if d.seq >= seq]

    # -- durability (PR 19) --------------------------------------------

    def attach_wal(self, wal) -> None:
        """Route every future append through ``wal`` first. Idempotent;
        attaching a DIFFERENT wal to a live source is a wiring bug."""
        with self._lock:
            if self._wal is wal:
                return
            if self._wal is not None:
                raise RuntimeError(
                    f"stream table {self.name!r} already has a WAL "
                    "attached")
            self._wal = wal

    def restore_deltas(self, records) -> int:
        """Rebuild the delta list from replayed WAL records
        ``(seq, data, validity, num_rows)`` — restart recovery, before
        any standing query registers. Only valid on an empty source."""
        from spark_rapids_tpu.service.cache import snapshots
        from spark_rapids_tpu.service.streaming import stats as _stats

        rows = 0
        with self._lock:
            if self._deltas:
                raise RuntimeError(
                    f"stream table {self.name!r} already has "
                    f"{len(self._deltas)} deltas; WAL restore must "
                    "run before any append")
            for seq, data, validity, n in records:
                self._deltas.append(_Delta(int(seq), data, validity,
                                           int(n)))
                self._total_rows += int(n)
                self._next_seq = max(self._next_seq, int(seq) + 1)
                rows += int(n)
        if records:
            snapshots.bump(self)
            _stats.bump("wal_replays")
        return rows

    # -- semantic-cache protocol (service/cache/snapshots) -------------

    def cache_identity(self):
        return ("stream-table", self.name, self._uid)

    def cache_version(self):
        with self._lock:
            return len(self._deltas)


class DeltaBatchSource(DataSource):
    """The per-fold leaf: holds exactly one micro-batch at a time.
    Deliberately NOT cache-keyable (no cache_identity): a fold's exec
    tree must never be confused with a cacheable batch plan."""

    def __init__(self, schema: Schema):
        self._schema = schema
        self._data = _empty_columns(schema)
        self._validity: dict = {}
        self._rows = 0

    def schema(self) -> Schema:
        return self._schema

    def set_delta(self, data, validity, num_rows: int) -> None:
        self._data = data
        self._validity = validity
        self._rows = num_rows

    def clear(self) -> None:
        self.set_delta(_empty_columns(self._schema), {}, 0)

    def read_host(self):
        return self._data, self._validity

    def estimated_row_count(self):
        return self._rows
