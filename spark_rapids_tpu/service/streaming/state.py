"""StreamingAggregateState: long-lived partial columns, folded per batch.

The incremental engine in one picture::

    micro-batch ──delta exec tree──> raw rows
        ──update_partials──> delta partials        (1 update launch)
        ──merge_partials(running, delta)──> running' (1 merge launch)

``running`` is the (keys..., partials...) merge-schema batch from
execs/aggregate's update/merge split, held across folds as a
SpillableBatch: owner-tagged in the catalog so it rides the
device->host->disk spill chain between folds, counts against the
service's admission footprint while device-resident, and one
``remove_owner`` call tears it down on cancel. Each fold's cost tracks
the micro-batch — the running state is touched only by the single
merge, never rescanned.

The delta exec tree is planned ONCE (apply_overrides over the delta
subplan from plan/incremental) and re-driven per fold. Exec-side
materializations that read the delta (shuffle blocks, delta-side
broadcast builds) are reset each fold; dimension-side broadcast builds
and fused-chain prepared builds are delta-unreachable and survive — the
PR 13 inline-build tables stay device-resident across folds for free.

Both fold launches run under the OOM retry ladder at their own sites
(``streaming.fold.update`` / ``streaming.fold.merge``): a fold that
trips device pressure spills, retries, and splits exactly like a batch
aggregation — and the fault injector can target a fold without touching
batch queries.
"""
from __future__ import annotations

from typing import Optional

from spark_rapids_tpu.memory.catalog import (StorageTier, get_catalog,
                                             set_buffer_owner)
from spark_rapids_tpu.memory.priorities import STREAMING_STATE_PRIORITY
from spark_rapids_tpu.memory.spillable import SpillableBatch
from spark_rapids_tpu.plan import incremental

UPDATE_SITE = "streaming.fold.update"
MERGE_SITE = "streaming.fold.merge"


class StreamingAggregateState:
    """Device-resident incremental aggregate for ONE standing query.
    Not thread-safe: the owning StandingQuery serializes folds under
    its lock."""

    def __init__(self, info: incremental.IncrementalInfo, conf,
                 owner_tag):
        from spark_rapids_tpu.execs.aggregate import HashAggregateExec
        from spark_rapids_tpu.plan.overrides import apply_overrides
        from spark_rapids_tpu.service.streaming.source import \
            DeltaBatchSource

        self.owner_tag = owner_tag
        self.schema = info.aggregate.output_schema()
        #: rename-only projection above the aggregate — applied to the
        #: EMITTED frame only, the running partials never see it
        self.projection = info.projection
        self.output_names = info.output_names()
        self.delta_source = DeltaBatchSource(info.stream_source.schema())
        delta_plan = incremental.substitute_source(
            info.child, info.stream_source, self.delta_source)
        self._child_exec = apply_overrides(delta_plan, conf)
        # the aggregate exec is built directly (not via the planner):
        # its execute() loop is never driven — the state drives the
        # update/merge seam methods so the running partials survive
        # across folds instead of dying with each execute()
        self._agg = HashAggregateExec(
            info.aggregate.grouping, info.aggregate.aggs,
            self._child_exec, self.schema, mode="complete", conf=conf)
        self._running: Optional[SpillableBatch] = None
        self.folds = 0
        self.rows_folded = 0

    # -- fold ----------------------------------------------------------

    def fold(self, data, validity, num_rows: int,
             cancel_check=None) -> int:
        """Fold one micro-batch into the running partials; returns the
        rows folded. ``cancel_check`` (if given) is called at step
        boundaries and may raise to abort the fold — the running state
        is swapped only as the LAST step, so an aborted fold leaves the
        previous state intact."""
        prev_owner = set_buffer_owner(self.owner_tag)
        try:
            self.delta_source.set_delta(data, validity, num_rows)
            self._reset_delta_path()
            try:
                parts = []
                for p in range(self._child_exec.num_partitions):
                    for b in self._child_exec.execute(p):
                        if b.realized_num_rows() == 0:
                            continue
                        parts.append(self._agg.update_partials(
                            b, site=UPDATE_SITE))
                        if cancel_check is not None:
                            cancel_check()
            finally:
                self.delta_source.clear()
            if not parts:
                self.folds += 1
                return 0
            part = parts[0]
            for extra in parts[1:]:
                part = self._agg.merge_partials(part, extra,
                                                site=MERGE_SITE)
            if cancel_check is not None:
                cancel_check()
            if self._running is None:
                merged = part
            else:
                with self._running.acquired() as rb:
                    merged = self._agg.merge_partials(rb, part,
                                                      site=MERGE_SITE)
            old, self._running = self._running, SpillableBatch(
                merged, STREAMING_STATE_PRIORITY)
            if old is not None:
                old.close()
            self.folds += 1
            self.rows_folded += num_rows
            return num_rows
        finally:
            set_buffer_owner(prev_owner)

    # -- emit ----------------------------------------------------------

    def emit(self):
        """Finalize the running partials into a pandas frame (the
        partials are NOT consumed — folding continues)."""
        import pandas as pd

        from spark_rapids_tpu.utils import dispatch as _disp

        if self._running is None:
            return pd.DataFrame({n: pd.Series([], dtype=object)
                                 for n in self.output_names})
        prev_owner = set_buffer_owner(self.owner_tag)
        try:
            with self._running.acquired() as rb:
                out = self._agg.finalize_partials(rb)
            tok = _disp.enter_stage("result_sync")
            try:
                frame = out.to_pandas(self.schema)
            finally:
                _disp.exit_stage(tok)
        finally:
            set_buffer_owner(prev_owner)
        if self.projection is not None:
            frame = pd.DataFrame(
                {name: frame.iloc[:, ordinal]
                 for name, ordinal in self.projection})
        return frame

    # -- checkpoint snapshot / restore (PR 19) -------------------------

    def snapshot_host(self) -> Optional[bytes]:
        """The running (keys..., partials...) batch in the serde wire
        format — the checkpoint payload. None when nothing has folded
        yet. Read-only: the partials keep folding afterwards."""
        from spark_rapids_tpu.columnar import serde

        if self._running is None:
            return None
        prev_owner = set_buffer_owner(self.owner_tag)
        try:
            with self._running.acquired() as rb:
                return serde.serialize_host_batch(serde.to_host_batch(rb))
        finally:
            set_buffer_owner(prev_owner)

    def restore_running(self, payload: Optional[bytes], folds: int,
                        rows_folded: int) -> None:
        """Adopt a checkpointed running state (inverse of
        ``snapshot_host``); only valid before the first fold."""
        from spark_rapids_tpu.columnar import serde

        if self._running is not None:
            raise RuntimeError("restore_running on a state that has "
                               "already folded")
        if payload:
            prev_owner = set_buffer_owner(self.owner_tag)
            try:
                db = serde.to_device_batch(
                    serde.deserialize_host_batch(payload))
                self._running = SpillableBatch(db,
                                               STREAMING_STATE_PRIORITY)
            finally:
                set_buffer_owner(prev_owner)
        self.folds = int(folds)
        self.rows_folded = int(rows_folded)

    # -- accounting / teardown -----------------------------------------

    def state_bytes(self) -> int:
        """Running-state size at device width (the admission and
        maxStateBytes currency, whatever tier it currently sits on)."""
        return self._running.device_memory_size() \
            if self._running is not None else 0

    def device_resident_bytes(self) -> int:
        if self._running is None:
            return 0
        cat = get_catalog()
        try:
            on_device = cat.tier_of(self._running.buffer_id) is \
                StorageTier.DEVICE
        except KeyError:
            return 0
        return self._running.device_memory_size() if on_device else 0

    def close(self) -> None:
        """Drop the running state and every catalog buffer the fold
        machinery registered under this query's owner tag (shuffle
        blocks, delta-side broadcast builds) — the cancel/deadline
        teardown path, same contract as Query finalize."""
        if self._running is not None:
            self._running.close()
            self._running = None
        get_catalog().remove_owner(self.owner_tag)

    # -- per-fold exec-state reset -------------------------------------

    def _reaches_delta(self, e, memo) -> bool:
        r = memo.get(id(e))
        if r is None:
            r = getattr(e, "source", None) is self.delta_source or any(
                self._reaches_delta(c, memo)
                for c in getattr(e, "children", ()))
            memo[id(e)] = r
        return r

    def _reset_delta_path(self) -> None:
        """Clear materialize-once exec state that READ the previous
        delta; dimension-side state (delta-unreachable) is left alone
        so build tables stay resident across folds."""
        from spark_rapids_tpu.execs.adaptive import \
            AdaptiveShuffleReaderExec
        from spark_rapids_tpu.execs.exchange import (
            BroadcastExchangeExec, ShuffleExchangeExec)
        from spark_rapids_tpu.execs.fused import FusedChainExec

        memo: dict = {}
        stack = [self._child_exec]
        seen: set = set()
        while stack:
            e = stack.pop()
            if id(e) in seen:
                continue
            seen.add(id(e))
            if isinstance(e, ShuffleExchangeExec) and \
                    e._blocks is not None and \
                    self._reaches_delta(e, memo):
                for handles in e._blocks.values():
                    for h in handles:
                        h.close()
                e._blocks = None
            elif isinstance(e, BroadcastExchangeExec) and \
                    e._cached is not None and \
                    self._reaches_delta(e, memo):
                e._cached.close()
                e._cached = None
            elif isinstance(e, AdaptiveShuffleReaderExec) and \
                    self._reaches_delta(e, memo):
                e._groups = None
            elif isinstance(e, FusedChainExec):
                if any(self._reaches_delta(b, memo) for b in e.builds):
                    with e._prep_lock:
                        e._preps = None
                        e._preps_ok = None
                stack.append(e.fallback)
                stack.extend(e.builds)
            stack.extend(getattr(e, "children", ()))
