"""Concurrent query service: the subsystem that turns the engine from a
library (one blocking ``collect()`` at a time) into a server.

The reference's whole concurrency story is passive building blocks —
GpuSemaphore bounds device entry (GpuSemaphore.scala:27-161), spill
priorities age stalled tasks' buffers out (SpillPriorities.scala:32-60)
— and relies on Spark's scheduler to drive them. Standalone, this
package IS that scheduler:

- ``QueryService`` (query_service.py): the front door. ``submit()``
  returns a ``QueryHandle`` (poll/result/cancel, per-query deadline);
  states QUEUED -> ADMITTED -> RUNNING -> DONE/FAILED/CANCELLED/SHED.
- ``AdmissionController`` (admission.py): estimates each query's peak
  HBM footprint from the optimizer's footer-stat cardinalities
  (plan/optimizer.estimate_footprint_bytes) and admits against the
  device budget plus TpuSemaphore permits, with a bounded priority
  queue, weighted-round-robin tenant fairness, and load shedding
  (``ServiceOverloaded``) once the queue limit is hit.
- ``StageScheduler`` (scheduler.py): interleaves admitted queries'
  per-stage programs on the single dispatch path — cooperative yields
  at stage boundaries, cancellation/deadline checks between stages,
  and spill-priority demotion for batches owned by stalled queries.
- ``ServiceStats`` (stats.py): queue depth, queue/run-time histograms
  with p50/p95/p99, admitted/shed/cancelled counts, per-query dispatch
  counts, and the cross-tenant compile-cache hit rate (shared programs
  are the multi-tenant win: tenant B's q1 reuses tenant A's
  executables).
- ``batching/`` (the serving layer — docs/service.md "Micro-batching
  & SLOs"): shape-bucket registry + AOT warmup
  (``QueryService.register_template``), the micro-batcher coalescing
  compatible stage dispatches from different queries into one physical
  launch, and the open-loop Poisson SLO harness behind
  ``benchmarks/service_bench.py --open-loop`` and
  ``scripts/slo_check.py``.
"""
from spark_rapids_tpu.service.types import (DeadlineExceeded,  # noqa: F401
                                            OutOfCoreRejected,
                                            QueryCancelled, QueryHandle,
                                            QueryState, ServiceOverloaded)
from spark_rapids_tpu.service.query_service import \
    QueryService  # noqa: F401
from spark_rapids_tpu.service.stats import ServiceStats  # noqa: F401
from spark_rapids_tpu.service.batching import (MicroBatcher,  # noqa: F401
                                               ShapeBucketRegistry)

__all__ = ["QueryService", "QueryHandle", "QueryState",
           "ServiceOverloaded", "OutOfCoreRejected", "DeadlineExceeded",
           "QueryCancelled", "ServiceStats", "MicroBatcher",
           "ShapeBucketRegistry"]
