"""Admission control: HBM-budget + permit gating with tenant fairness.

The reference admits work onto the device with a counting semaphore
(GpuSemaphore.scala:27-161) and trusts Spark's scheduler for fairness;
standalone, the service needs the scheduler half too. This controller
keeps a bounded priority queue per tenant and admits in weighted
round-robin order, charging each query's estimated peak HBM footprint
(plan/optimizer.estimate_footprint_bytes — footer-stat cardinalities x
row widths) against the device budget, so the admitted set is expected
to fit without thrashing the spill catalog. Shedding (not queueing)
past the queue limit is the backpressure signal.
"""
from __future__ import annotations

import bisect
import itertools
import time
from typing import Dict, List, Optional

from spark_rapids_tpu.service.types import Query, QueryState


def parse_fairness_weights(spec: str) -> Dict[str, int]:
    """'tenantA:2,tenantB:1' -> {tenantA: 2, tenantB: 1}; malformed
    entries are ignored (a service must not crash on a bad knob)."""
    out: Dict[str, int] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        name, _, w = part.rpartition(":")
        try:
            out[name.strip()] = max(int(w), 1)
        except ValueError:
            continue
    return out


class _TenantQueue:
    """FIFO within a priority level; higher priority first. The sort
    key list mirrors the entry list for bisect insertion."""

    def __init__(self, weight: int):
        self.weight = weight
        self.credits = weight
        self._keys: List[tuple] = []   # (-priority, seq)
        self._items: List[Query] = []
        self._seq = itertools.count()

    def __len__(self) -> int:
        return len(self._items)

    def push(self, q: Query) -> None:
        key = (-q.priority, next(self._seq))
        i = bisect.bisect_right(self._keys, key)
        self._keys.insert(i, key)
        self._items.insert(i, q)

    def head(self) -> Optional[Query]:
        return self._items[0] if self._items else None

    def pop_head(self) -> Query:
        self._keys.pop(0)
        return self._items.pop(0)

    def remove(self, q: Query) -> bool:
        try:
            i = self._items.index(q)
        except ValueError:
            return False
        self._items.pop(i)
        self._keys.pop(i)
        return True


class AdmissionController:
    """NOT thread-safe by itself: every method runs under the service
    lock (one lock for queue + admission + scheduler state keeps the
    invariants simple; contention is per-stage-slice, not per-row)."""

    def __init__(self, queue_limit: int, max_concurrent: int,
                 budget_bytes: Optional[int], semaphore,
                 weights: Optional[Dict[str, int]] = None):
        self.queue_limit = max(queue_limit, 1)
        self.max_concurrent = max(max_concurrent, 1)
        self.budget_bytes = budget_bytes  # None = no HBM accounting
        # None = resolve the process semaphore live at each check:
        # runtime.initialize() REPLACES the global instance (a new
        # concurrentTpuTasks value), and a captured reference would
        # keep gating on the orphaned one forever. An explicit instance
        # (tests) is honored as-is.
        self.semaphore = semaphore
        # optional callable charging extra device-resident bytes
        # against the HBM budget — the service points it at the
        # semantic cache's READY fragments so cached data and inflight
        # queries share one accounting (a full cache narrows admission
        # instead of overcommitting the device)
        self.extra_bytes_fn = None
        self._weights = dict(weights or {})
        self._tenants: Dict[str, _TenantQueue] = {}
        self._rr: List[str] = []   # WRR cycle order (arrival order)
        self._rr_pos = 0
        self.queued_count = 0
        self.inflight: set = set()            # ADMITTED + RUNNING
        self.inflight_bytes = 0

    # -- queue side -------------------------------------------------------

    def queue_depth(self) -> int:
        return self.queued_count

    def would_shed(self, tenant: str) -> bool:
        """Backpressure with a fairness-aware band: below the queue
        limit nobody sheds; at 2x the limit everybody does (overload is
        overload); in between only tenants at/above their fair share of
        the queue shed — a flooding tenant cannot fill every slot and
        starve a light tenant at the front door."""
        if self.queued_count < self.queue_limit:
            return False
        if self.queued_count >= 2 * self.queue_limit:
            return True
        tq = self._tenants.get(tenant)
        mine = len(tq) if tq is not None else 0
        share = max(self.queue_limit // max(len(self._tenants), 1), 1)
        return mine >= share

    def offer(self, q: Query) -> None:
        """Enqueue for admission; caller has already checked
        ``would_shed`` and raised ServiceOverloaded."""
        tq = self._tenants.get(q.tenant)
        if tq is None:
            tq = _TenantQueue(self._weights.get(q.tenant, 1))
            self._tenants[q.tenant] = tq
            self._rr.append(q.tenant)
        tq.push(q)
        self.queued_count += 1

    def remove_queued(self, q: Query) -> bool:
        """Cancel/expiry of a still-queued query."""
        tq = self._tenants.get(q.tenant)
        if tq is not None and tq.remove(q):
            self.queued_count -= 1
            if len(tq) == 0:
                self._prune_tenant(q.tenant)
            return True
        return False

    def _prune_tenant(self, tenant: str) -> None:
        """Drop a drained tenant from the WRR cycle: tenants are
        per-submitter keys ('millions of users'), so empty queues must
        not accumulate in the scan (they re-register on next offer)."""
        self._tenants.pop(tenant, None)
        try:
            i = self._rr.index(tenant)
        except ValueError:
            return
        self._rr.pop(i)
        if self._rr_pos > i:
            self._rr_pos -= 1
        if self._rr:
            self._rr_pos %= len(self._rr)
        else:
            self._rr_pos = 0

    # -- admission side ---------------------------------------------------

    def current_semaphore(self):
        if self.semaphore is not None:
            return self.semaphore
        from spark_rapids_tpu.memory import semaphore as sem

        return sem.get()

    def current_budget(self) -> Optional[int]:
        """Live HBM budget: an explicit configured budget wins; else the
        runtime catalog's device budget AS OF NOW — the service may be
        built before runtime.initialize(), and a budget captured then
        (None, or a stale value) would disable/miscalibrate HBM
        admission for the life of the service."""
        if self.budget_bytes is not None:
            return self.budget_bytes
        from spark_rapids_tpu import runtime

        env = runtime.get_env()
        return env.catalog.device_budget if env is not None else None

    def _fits(self, q: Query) -> bool:
        if len(self.inflight) >= self.max_concurrent:
            return False
        if not self.inflight:
            # an empty device admits anything: a query whose footprint
            # exceeds the whole budget must eventually run solo (the
            # spill catalog absorbs the estimate being wrong), and the
            # service must never deadlock on its own estimate
            return True
        semaphore = self.current_semaphore()
        if semaphore is not None and semaphore.available() <= 0:
            # all device-entry permits busy: adding more admitted
            # queries only builds a convoy at the semaphore
            return False
        budget = self.current_budget()
        # charge, not footprint: an out-of-core query is charged a
        # capped share of HBM (the service set q.charge at submit) —
        # its real working set lives in the spill chain, so billing
        # the full over-budget footprint would park it behind every
        # in-flight query until the device drained
        if budget is not None:
            extra = int(self.extra_bytes_fn()) \
                if self.extra_bytes_fn is not None else 0
            if self.inflight_bytes + extra + q.charge > budget:
                return False
        return True

    def next_admissible(self) -> Optional[Query]:
        """WRR pop: scan tenants from the cycle pointer, take the first
        whose head query fits budget+permits. An unfit head does not
        block other tenants (it re-checks every admission round and is
        guaranteed in once the inflight set drains — see _fits)."""
        n = len(self._rr)
        for off in range(n):
            i = (self._rr_pos + off) % n
            tq = self._tenants[self._rr[i]]
            head = tq.head()
            if head is None or not self._fits(head):
                continue
            tq.pop_head()
            self.queued_count -= 1
            tq.credits -= 1
            if tq.credits <= 0 or len(tq) == 0:
                tq.credits = tq.weight
                self._rr_pos = (i + 1) % n
            else:
                self._rr_pos = i  # weight remaining: stay on tenant
            if len(tq) == 0:
                self._prune_tenant(head.tenant)
            return head
        return None

    def admit(self, q: Query) -> None:
        q.state = QueryState.ADMITTED
        q.admitted_at = time.perf_counter()
        self.inflight.add(q)
        self.inflight_bytes += q.charge

    def release(self, q: Query) -> None:
        """Completion/cancel/expiry of an admitted query frees its
        budget charge (the service then pumps admission again)."""
        if q in self.inflight:
            self.inflight.discard(q)
            self.inflight_bytes -= q.charge
