"""QueryService: the multi-tenant front door over the engine.

``submit()`` plans the query on the caller thread (override planning +
stage cutting + footprint estimation are cheap host work), then hands
the physical tree to admission; scheduler workers drive admitted
queries' stage slices cooperatively. One service per Session — it owns
nothing global except through the runtime singletons the engine already
uses (catalog, semaphore, program caches), which is precisely why
concurrent queries compose: every shared structure below the service
was already concurrent-safe for intra-query task threads.
"""
from __future__ import annotations

import itertools
import threading
from spark_rapids_tpu.utils import lockorder
import time
from typing import Dict, Optional

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.memory.catalog import get_catalog
from spark_rapids_tpu.service.admission import (AdmissionController,
                                                parse_fairness_weights)
from spark_rapids_tpu.service.autoscaler import ClusterAutoscaler
from spark_rapids_tpu.service.cache.manager import CacheManager
from spark_rapids_tpu.service.scheduler import StageScheduler
from spark_rapids_tpu.service.stats import Histogram, ServiceStats
from spark_rapids_tpu.service.types import (DeadlineExceeded,
                                            OutOfCoreRejected, Query,
                                            QueryCancelled, QueryHandle,
                                            QueryState, ServiceOverloaded)

# process-global id stream: query ids must be unique ACROSS services —
# per-query dispatch telemetry (utils/dispatch._query_counts) and
# catalog owner tags key on them, and two Sessions each numbering from
# 1 would corrupt each other's buckets
_GLOBAL_QUERY_IDS = itertools.count(1)

#: terminal queries kept for stats()/per_query history; older ones are
#: evicted from the registry (their handles keep working — a handle
#: references the Query object directly)
FINISHED_RETENTION = 256


class QueryService:
    def __init__(self, conf: Optional[RapidsConf] = None, session=None):
        self.conf = conf if isinstance(conf, RapidsConf) else \
            RapidsConf(conf)
        self.session = session
        self._lock = lockorder.make_rlock("service.query")
        self._done_cv = lockorder.make_condition("service.query", lock=self._lock)   # result() waits
        self._work_cv = lockorder.make_condition("service.query", lock=self._lock)   # workers wait
        self._queries: Dict[int, Query] = {}
        self._finished_order: list = []  # terminal qids, oldest first
        self._counters = {"submitted": 0, "admitted": 0, "shed": 0,
                          "done": 0, "failed": 0, "cancelled": 0,
                          "deadline_expired": 0,
                          "admitted_out_of_core": 0,
                          "oom_retries": 0, "oom_splits": 0,
                          "scale_ups": 0, "scale_downs": 0}
        self._queue_time = Histogram()
        self._run_time = Histogram()
        self._shutdown = False
        self._pumping = False
        self.admission = AdmissionController(
            queue_limit=self.conf.get(cfg.SERVICE_QUEUE_LIMIT),
            max_concurrent=self.conf.get(cfg.SERVICE_MAX_CONCURRENT),
            budget_bytes=self._resolve_budget(),
            semaphore=None,  # resolve live: runtime init may replace it
            weights=parse_fairness_weights(
                self.conf.get(cfg.SERVICE_FAIRNESS_WEIGHTS)))
        self.scheduler = StageScheduler(
            self, n_workers=self.conf.get(cfg.SERVICE_MAX_CONCURRENT))
        # queue-pressure autoscaler (service/autoscaler.py): observes
        # every admission pump, grows the session cluster through the
        # elastic-membership seam when queries keep queuing
        self.autoscaler = ClusterAutoscaler(self.conf)
        # semantic result & fragment cache (service/cache): per-service
        # like the admission ledger. Its device-resident fragment bytes
        # charge the admission budget so cached data and inflight
        # queries never overcommit HBM between them.
        self.cache = CacheManager(self.conf)
        # streaming ingestion & standing queries (service/streaming):
        # long-lived aggregate state is device-resident between folds,
        # so it charges the admission budget alongside cached fragments
        from spark_rapids_tpu.service.streaming.manager import \
            StreamingManager

        self.streaming = StreamingManager(self.conf)
        # pending checkpoint/WAL host buffers charge admission too: a
        # burst of async checkpoint blobs is real host memory, and the
        # admission ledger is the one place that sees every subsystem
        from spark_rapids_tpu.io import scanpipe

        self.admission.extra_bytes_fn = lambda: (
            self.cache.device_resident_bytes()
            + self.streaming.device_resident_bytes()
            + self.streaming.durability_pending_bytes()
            # scan-pipeline backpressure: packed slices queued for
            # upload + device-resident scan-cache landings
            + scanpipe.admission_bytes())
        # restart recovery (PR 19): discover what the checkpoint dir
        # holds; the actual WAL replays / checkpoint restores run when
        # the caller re-creates its tables and re-registers its queries
        self.recovery_report = self.streaming.recover()
        self._sigterm_prev = None
        self._install_sigterm()
        #: result-cache key -> live leader Query (single-flight)
        self._result_leaders: Dict = {}
        # cross-tenant micro-batching (service/batching): the ladder
        # growth installs process-wide (capacities are compared across
        # subsystems — one ladder per process; last service wins, the
        # intended deployment is one service per process anyway)
        from spark_rapids_tpu.ops import buckets as _ladder
        from spark_rapids_tpu.service.batching import (MicroBatcher,
                                                       get_registry)

        _ladder.set_ladder_growth(
            self.conf.get(cfg.SERVICE_BATCHING_BUCKET_GROWTH))
        self.batcher = MicroBatcher(
            window_s=self.conf.get(cfg.SERVICE_BATCHING_WINDOW_MS)
            / 1e3,
            max_batch=self.conf.get(cfg.SERVICE_BATCHING_MAX),
            enabled=self.conf.get(cfg.SERVICE_BATCHING_ENABLED),
            registry=get_registry(),
            inflight_fn=lambda: len(self.admission.inflight))
        self._templates: list = []   # (name, plan) for warmup replay

    def _resolve_budget(self) -> Optional[int]:
        """Only an EXPLICIT configured budget is captured; None lets
        admission resolve the runtime device budget live (the runtime
        commonly initializes after the service is constructed)."""
        explicit = self.conf.get(cfg.SERVICE_ADMISSION_BUDGET)
        return explicit if explicit else None

    # -- front door -------------------------------------------------------

    def submit(self, df_or_plan, tenant: str = "default",
               priority: int = 0,
               deadline: Optional[float] = None) -> QueryHandle:
        """Plan + enqueue a query; returns immediately with a handle.
        Raises ServiceOverloaded (state SHED) past the queue limit.
        ``deadline`` is seconds from submission (queue + run time); the
        conf default applies when None."""
        plan = getattr(df_or_plan, "_plan", df_or_plan)
        if deadline is None:
            d = self.conf.get(cfg.SERVICE_DEFAULT_DEADLINE)
            deadline = d if d and d > 0 else None
        # shed BEFORE any planning: under overload — exactly when the
        # backpressure signal matters — a rejection must not pay the
        # planner walk, and result_key is already a plan walk with an
        # os.stat per source file, so even IT comes after this check
        with self._lock:
            if self._shutdown:
                raise RuntimeError("QueryService is shut down")
            self._counters["submitted"] += 1
            if self.admission.would_shed(tenant):
                raise self._shed_locked(plan, tenant, priority, deadline)
        # result tier: an exact hit needs no planning and no device
        # work; a live leader for the same key absorbs this submit as
        # a single-flight follower
        ckey = self.cache.result_key(plan)
        if ckey is not None:
            with self._lock:
                if self._shutdown:
                    raise RuntimeError("QueryService is shut down")
                served = self._serve_cached_locked(ckey, tenant,
                                                   priority, deadline)
                if served is not None:
                    return served
        try:
            planned = self._plan_query(plan, tenant)
        except OutOfCoreRejected as err:
            with self._lock:
                rec = self._record_shed_locked(tenant, priority,
                                               deadline)
            err.query_id = rec.query_id
            raise
        # from here the grafted fragment registrations/pins are this
        # frame's responsibility until a Query takes them over — any
        # exit without a handoff must release them, or the PENDING
        # entries block every future capture of the same keys forever
        pending_frags = planned["pending"]
        served_frags = planned["served"]
        try:
            with self._lock:
                if self._shutdown:
                    raise RuntimeError("QueryService is shut down")
                if self.admission.would_shed(tenant):
                    # concurrent submitters planned past the first
                    # check and filled the queue meanwhile — the bound
                    # is hard
                    raise self._shed_locked(plan, tenant, priority,
                                            deadline)
                if ckey is not None:
                    # a concurrent identical submit may have become
                    # leader (or finished) while this thread planned
                    served = self._serve_cached_locked(ckey, tenant,
                                                       priority,
                                                       deadline,
                                                       count=False)
                    if served is not None:
                        self.cache.abort_pending(pending_frags)
                        self.cache.release_served(served_frags)
                        pending_frags, served_frags = [], []
                        return served
                q = Query(next(_GLOBAL_QUERY_IDS), tenant, plan,
                          planned["exec"], priority, deadline,
                          planned["footprint"], planned["stages"],
                          self._done_cv)
                # ownership of the fragment registrations/pins moves
                # to the query (finalize aborts/releases them)
                q.pending_fragments, pending_frags = pending_frags, []
                q.served_fragments, served_frags = served_frags, []
                if ckey is not None:
                    q.result_cache_key = ckey
                    self._result_leaders[ckey] = q
                if planned["out_of_core"]:
                    q.out_of_core = True
                    q.charge = planned["charge"]
                self._queries[q.query_id] = q
                self.admission.offer(q)
                self._pump_locked()
            return QueryHandle(self, q)
        except BaseException:
            self.cache.abort_pending(pending_frags)
            self.cache.release_served(served_frags)
            raise

    def _plan_query(self, plan, tenant: str) -> dict:
        """The planning core shared by submit() and single-flight
        follower promotion: fragment graft, footprint estimate, the
        out-of-core decision, physical planning and stage cutting. On
        ANY failure — including OutOfCoreRejected(policy=shed), which
        the caller records — the grafted fragment registrations and
        graft-time pins are released before the exception propagates,
        so a planner error can never leak PENDING registry entries."""
        from spark_rapids_tpu.plan.optimizer import (
            estimate_footprint_bytes, cut_stages)
        from spark_rapids_tpu.plan.overrides import apply_overrides

        # fragment tier: replace READY cached stage roots with serve
        # leaves (pinned at graft — see CacheManager.graft_fragments),
        # wrap first-seen ones in capture nodes; footprint and physical
        # planning run on the grafted plan (a serve leaf costs what it
        # stores, not what its subtree would recompute)
        plan_to_run, pending, served = self.cache.graft_fragments(plan)
        try:
            # AQE runtime stats (replan rule 3b): measured exchange
            # cardinalities from earlier runs answer for nodes the
            # static estimator cannot, tightening admission over time
            runtime_rows = None
            if self.conf.get(cfg.ADAPTIVE_ENABLED) and \
                    self.conf.get(cfg.ADAPTIVE_RUNTIME_STATS):
                from spark_rapids_tpu.execs import adaptive

                runtime_rows = adaptive.plan_cardinality_rows
            footprint = estimate_footprint_bytes(
                plan_to_run, default_rows=self.conf.get(
                    cfg.SERVICE_DEFAULT_ROW_ESTIMATE),
                runtime_rows=runtime_rows)
            # out-of-core decision BEFORE physical planning: a query
            # whose estimated peak exceeds the WHOLE device budget can
            # never fit, so either shed it now (policy=shed) or plan it
            # with a forced-splitting batch budget so every staging
            # exec takes its bucketed out-of-core path and the spill
            # chain absorbs the overflow (ROADMAP item 3)
            plan_conf = self.conf
            out_of_core = False
            charge = None
            budget = self.admission.current_budget()
            if budget is not None and footprint > budget and \
                    self.conf.get(cfg.SERVICE_OUT_OF_CORE):
                policy = str(self.conf.get(
                    cfg.SERVICE_OUT_OF_CORE_POLICY)).strip().lower()
                if policy == "shed":
                    raise OutOfCoreRejected(tenant, footprint, budget)
                out_of_core = True
                forced = max(budget // 4, 1 << 20)
                plan_conf = self.conf.with_overrides(
                    {cfg.BATCH_SIZE_BYTES.key: forced})
                # charge half the device: the forced-splitting plan
                # bounds the resident working set far below the
                # footprint, and a whale must not occupy the whole
                # budget ledger while it spills
                charge = min(footprint, max(budget // 2, 1))
            exec_ = apply_overrides(plan_to_run, plan_conf)
            stages = cut_stages(exec_)
        except BaseException:
            self.cache.abort_pending(pending)
            self.cache.release_served(served)
            raise
        return {"exec": exec_, "stages": stages,
                "footprint": footprint, "out_of_core": out_of_core,
                "charge": charge, "pending": pending, "served": served}

    # -- streaming front door (service/streaming) -------------------------

    def ingest(self, table, data, validity: Optional[dict] = None
               ) -> int:
        """Append one micro-batch to a streaming table (a
        StreamTableSource or the name of one registered as a temp view
        on this service's Session) and fold it into every standing
        query over it; returns the rows landed."""
        return self.streaming.ingest(self._resolve_stream(table), data,
                                     validity)

    def register_standing(self, df_or_plan, tenant: str = "default",
                          **kwargs):
        """Register a continuous aggregation over a streaming table;
        returns a StandingQuery handle (results()/cancel()). See
        StreamingManager.register_standing for the knob set."""
        return self.streaming.register_standing(df_or_plan, tenant,
                                                **kwargs)

    def _resolve_stream(self, table):
        from spark_rapids_tpu.plan.incremental import \
            is_streaming_source

        if isinstance(table, str):
            if self.session is None:
                raise ValueError(
                    f"cannot resolve streaming table {table!r}: the "
                    "service has no Session — pass the "
                    "StreamTableSource itself")
            table = self.session.streaming_table(table)
        if not is_streaming_source(table):
            raise ValueError(
                f"{type(table).__name__} is not a streaming table — "
                "create one with Session.create_streaming_table")
        return table

    # -- warmup (ROADMAP item 2: AOT-warm the progcache at startup) -------

    def register_template(self, df_or_plan, name: Optional[str] = None,
                          max_rung: Optional[int] = None):
        """Register a query template the service expects tenants to
        run. With ``rapids.tpu.service.warmup.enabled`` the template is
        warmed immediately (returns the warmup report); otherwise it is
        only recorded for a later explicit ``warmup()`` call.
        ``max_rung`` caps the ladder replay: a single-query caller that
        knows its input capacity skips compiling rungs above it."""
        plan = getattr(df_or_plan, "_plan", df_or_plan)
        entry = (name or f"template{len(self._templates)}", plan)
        self._templates.append(entry)
        if self.conf.get(cfg.SERVICE_WARMUP_ENABLED):
            return self.warmup([entry], max_rung=max_rung)
        return None

    def warmup(self, templates=None, timeout: float = 600.0,
               max_rung: Optional[int] = None) -> dict:
        """Run each template once under the reserved ``__warmup__``
        tenant — tracing + compiling its stage programs into the
        in-process chain-key cache and the persistent compile cache —
        then (warmup.ladder) replay the recorded stage programs across
        the capacity-ladder rungs so smaller buckets are compiled too.
        The first REAL tenant request then starts hot instead of
        eating the cold compile."""
        t0 = time.perf_counter()
        todo = list(self._templates) if templates is None \
            else list(templates)
        ran = errors = 0
        for _name, plan in todo:
            try:
                self.submit(plan, tenant="__warmup__").result(
                    timeout=timeout)
                ran += 1
            except Exception as e:
                from spark_rapids_tpu.memory.retry import is_oom_error

                if is_oom_error(e):
                    # an OOM that survived the in-query retry ladder is
                    # a capacity fault, not a bad template: surface it
                    # instead of shipping a service that admits load it
                    # cannot hold (tpulint TPU401)
                    raise
                errors += 1   # warmup is advisory: a template that
                #               cannot run fails ITS tenant later, not
                #               service startup
        ladder: dict = {}
        if self.batcher.registry is not None and \
                self.conf.get(cfg.SERVICE_WARMUP_LADDER):
            ladder = self.batcher.registry.warm(max_rung=max_rung)
        coalesced = self.batcher.warm_coalesced()
        return {"templates": ran, "errors": errors, "ladder": ladder,
                "coalesced": coalesced,
                "seconds": round(time.perf_counter() - t0, 3)}

    def _record_shed_locked(self, tenant: str, priority: int,
                            deadline) -> Query:
        """Record a rejection as a terminal SHED query so the lifecycle
        is observable (stats().per_query history)."""
        q = Query(next(_GLOBAL_QUERY_IDS), tenant, None, None,
                  priority, deadline, 0, [], self._done_cv)
        q.state = QueryState.SHED
        q.finished_at = time.perf_counter()
        self._queries[q.query_id] = q
        self._retain_locked(q)
        self._counters["shed"] += 1
        return q

    def _serve_cached_locked(self, ckey, tenant: str, priority: int,
                             deadline, count: bool = True):
        """Serve a result-cache hit, or register behind a live leader.
        Returns a handle, or None when this submit must run (and lead).
        Hits finalize DONE immediately with zero device work; followers
        park until the leader finalizes. Both stamp admitted/started so
        stats never sees a DONE query without timing."""
        frame = self.cache.lookup_result(ckey, count=count)
        if frame is not None:
            q = Query(next(_GLOBAL_QUERY_IDS), tenant, None, None,
                      priority, deadline, 0, [], self._done_cv)
            q.cache_hit = True
            q.admitted_at = q.started_at = time.perf_counter()
            q.result = frame
            self._queries[q.query_id] = q
            self._finalize_locked(q, QueryState.DONE)
            return QueryHandle(self, q)
        leader = self._result_leaders.get(ckey)
        if leader is not None and not leader.terminal:
            q = Query(next(_GLOBAL_QUERY_IDS), tenant, None, None,
                      priority, deadline, 0, [], self._done_cv)
            q.cache_hit = True
            self.cache.note_follower()
            leader.cache_followers.append(q)
            self._queries[q.query_id] = q
            return QueryHandle(self, q)
        return None

    def _shed_locked(self, plan, tenant: str, priority: int,
                     deadline) -> ServiceOverloaded:
        """Record + build the overload rejection — the caller gets no
        handle back, but the exception carries the id for gateway-side
        correlation."""
        q = self._record_shed_locked(tenant, priority, deadline)
        err = ServiceOverloaded(
            tenant, self.admission.queue_depth(),
            self.admission.queue_limit)
        err.query_id = q.query_id
        return err

    def stats(self) -> ServiceStats:
        from spark_rapids_tpu.memory import retry as _retry
        from spark_rapids_tpu.runtime import recovery as _recovery
        from spark_rapids_tpu.utils import dispatch as _disp
        from spark_rapids_tpu.utils import progcache

        with self._lock:
            qcounts = _disp.query_counts()
            qcoal = _disp.query_coalesced_counts()
            per_query = []
            running = 0
            for q in self._queries.values():
                if q.state is QueryState.RUNNING:
                    running += 1
                per_query.append({
                    "query_id": q.query_id,
                    "tenant": q.tenant,
                    "state": q.state.value,
                    "footprint_bytes": q.footprint,
                    "out_of_core": q.out_of_core,
                    "slices": q.slices_done,
                    # float: coalesced launches contribute a 1/K share
                    # so per-query counts SUM to physical launches
                    "dispatches": round(qcounts.get(q.query_id,
                                                    q.dispatches), 4),
                    "coalesced_dispatches": qcoal.get(q.query_id,
                                                      q.coalesced),
                    # live queries read the retry map; terminal ones
                    # keep the snapshot finalize popped
                    "retry": q.retry or _retry.owner_stats(
                        q.owner_tag),
                    "queue_time_s": q.queue_time_s(),
                    "run_time_s": q.run_time_s(),
                })
            semaphore = self.admission.current_semaphore()
            return ServiceStats(
                retry=_retry.stats(),
                batching=self.batcher.stats(),
                cache=self.cache.stats(),
                streaming=self.streaming.stats(),
                recovery=_recovery.snapshot(),
                autoscaler=self.autoscaler.stats(),
                queue_depth=self.admission.queue_depth(),
                running=running,
                admitted_inflight=len(self.admission.inflight),
                inflight_bytes=self.admission.inflight_bytes,
                budget_bytes=self.admission.current_budget(),
                counters=dict(self._counters),
                queue_time_hist=self._queue_time.snapshot(),
                run_time_hist=self._run_time.snapshot(),
                per_query=per_query,
                progcache=progcache.stats(),
                semaphore={
                    "available": semaphore.available(),
                    "max": semaphore.max_permits,
                })

    # -- graceful termination (PR 19) -------------------------------------

    def _install_sigterm(self) -> None:
        """With durability on, SIGTERM means checkpoint-then-drain, not
        query slaughter: standing queries suspend behind a final
        checkpoint and queued durability writes land before the process
        exits. Main-thread only (signal API constraint); the previous
        handler is chained and restored at shutdown."""
        import signal
        import threading

        if not (self.streaming.durability.enabled
                and self.streaming.durability.on_sigterm
                and threading.current_thread()
                is threading.main_thread()):
            return

        def _on_sigterm(signum, frame):
            self.shutdown(cancel_running=False)
            prev = self._sigterm_prev
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                signal.raise_signal(signal.SIGTERM)

        try:
            self._sigterm_prev = signal.signal(signal.SIGTERM,
                                               _on_sigterm)
        except (ValueError, OSError):
            self._sigterm_prev = None

    def _restore_sigterm(self) -> None:
        import signal
        import threading

        if self._sigterm_prev is None or threading.current_thread() \
                is not threading.main_thread():
            return
        try:
            signal.signal(signal.SIGTERM, self._sigterm_prev)
        except (ValueError, OSError):
            pass
        self._sigterm_prev = None

    def shutdown(self, cancel_running: bool = True) -> None:
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            for q in list(self._queries.values()):
                if not q.terminal:
                    if q.state is QueryState.QUEUED:
                        self.admission.remove_queued(q)
                        self._finalize_locked(q, QueryState.CANCELLED)
                    elif cancel_running:
                        q.cancel_requested = True
            self.scheduler.stop()
        self.scheduler.join()
        # workers are gone: no future slice will observe the cancel
        # flags, so finalize whatever they left mid-flight here — a
        # waiter blocked in result() must terminate, and the queries'
        # admission charges + catalog buffers must release
        with self._lock:
            for q in list(self._queries.values()):
                if not q.terminal:
                    self._finalize_locked(q, QueryState.CANCELLED)
        # standing queries first: their teardown (suspend-with-final-
        # checkpoint when durable, cancel otherwise) releases the
        # owner-tagged streaming state through the catalog, and no fold
        # can be in flight once ingest starts refusing work
        self.streaming.shutdown()
        # workers joined and every query finalized: no capture or serve
        # can still be touching an entry's spillable handles
        self.cache.close()
        self._restore_sigterm()

    # -- handle backends --------------------------------------------------

    def _poll(self, q: Query) -> QueryState:
        with self._lock:
            self._maybe_expire_locked(q)
            return q.state

    def _result(self, q: Query, timeout: Optional[float]):
        deadline = None if timeout is None else \
            time.perf_counter() + timeout
        with self._lock:
            while True:
                self._maybe_expire_locked(q)
                if q.terminal:
                    break
                wait = None
                if q.deadline_at is not None:
                    # floor keeps the re-check from busy-looping while
                    # an overdue RUNNING query finishes its slice (the
                    # scheduler, not this waiter, expires it)
                    wait = max(q.deadline_at - time.perf_counter(),
                               0.25)
                if deadline is not None:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"query {q.query_id} still "
                            f"{q.state.value} after {timeout}s")
                    wait = remaining if wait is None else \
                        min(wait, remaining)
                self._done_cv.wait(wait)
            if q.state is QueryState.DONE:
                return q.result
            if q.state is QueryState.CANCELLED:
                raise QueryCancelled(
                    f"query {q.query_id} was cancelled")
            raise q.error or RuntimeError(
                f"query {q.query_id} {q.state.value}")

    def _cancel(self, q: Query) -> bool:
        with self._lock:
            if q.terminal:
                return q.state is QueryState.CANCELLED
            if q.state is QueryState.QUEUED:
                self.admission.remove_queued(q)
                self._finalize_locked(q, QueryState.CANCELLED)
                return True
            # admitted/running: flag it; a stalled query in the ready
            # deque finalizes via its next slice's interrupt check
            q.cancel_requested = True
            return True

    # -- internals --------------------------------------------------------

    def _maybe_expire_locked(self, q: Query) -> None:
        """Lazily expire an overdue query that no worker is driving:
        QUEUED (still in admission), or ADMITTED and parked in the
        ready deque (a stalled query may never reach a worker while a
        long slice hogs maxConcurrent — its deadline must still fire).
        A RUNNING query is expired by its own slice-boundary check."""
        if q.terminal or not q.deadline_expired():
            return
        if q.state is QueryState.QUEUED:
            self.admission.remove_queued(q)
            where = "while queued"
        elif q.state is QueryState.ADMITTED and self.scheduler.drop(q):
            where = "while awaiting a scheduler slot"
        else:
            return
        self._finalize_locked(
            q, QueryState.FAILED,
            DeadlineExceeded(
                f"query {q.query_id} exceeded its "
                f"{q.deadline_s:.3f}s deadline {where}"))

    def _pump_locked(self) -> None:
        """Admit queries while capacity allows (called on submit and on
        every release). Reentrancy guard: expiring a queued query below
        calls _finalize_locked, whose own tail pump must not recurse —
        one stack frame per expired query would blow the stack on a
        deep queue of dead deadlines; the guard makes the inner call a
        no-op and this loop re-scans instead."""
        if self._pumping:
            return
        self._pumping = True
        try:
            while True:
                nxt = self.admission.next_admissible()
                if nxt is None:
                    # nothing admissible: queued work is admission
                    # pressure (maybe grow a host), an empty queue is
                    # idleness (maybe shrink one past the sustained-
                    # idle window) — the autoscaler sees both
                    pre_downs = self.autoscaler.scale_downs
                    eid = self.autoscaler.observe(
                        self.admission.queue_depth(),
                        len(self.admission.inflight))
                    if eid is not None:
                        self._counters["scale_ups"] += 1
                    self._counters["scale_downs"] += \
                        self.autoscaler.scale_downs - pre_downs
                    return
                if nxt.deadline_expired():
                    self._finalize_locked(
                        nxt, QueryState.FAILED,
                        DeadlineExceeded(
                            f"query {nxt.query_id} exceeded its "
                            f"deadline while queued"))
                    continue
                self.admission.admit(nxt)
                self._counters["admitted"] += 1
                if nxt.out_of_core:
                    self._counters["admitted_out_of_core"] += 1
                self.scheduler.enqueue(nxt)
        finally:
            self._pumping = False

    def _finalize(self, q: Query, state: QueryState,
                  error: Optional[BaseException] = None) -> None:
        if state is QueryState.DONE and q.result is None:
            # assemble OUTSIDE the lock: the finishing worker still owns
            # the query exclusively, and a multi-GB pd.concat must not
            # stall every submit/poll/worker on the service lock
            q.result = self._assemble(q)
        with self._lock:
            self._finalize_locked(q, state, error)

    def _finalize_locked(self, q: Query, state: QueryState,
                         error: Optional[BaseException] = None) -> None:
        from spark_rapids_tpu.memory import retry as _retry
        from spark_rapids_tpu.utils import dispatch as _disp

        if q.terminal:
            return
        if state is QueryState.DONE and q.cancel_requested:
            # cancel() already told its caller the query will not
            # complete — honor that even when the final slice raced it
            # to the finish (flag and transition share this lock, so
            # the race closes here); the assembled result is discarded
            state = QueryState.CANCELLED
            q.result = None
        if state is QueryState.DONE and q.result is None:
            q.result = self._assemble(q)  # _finalize pre-assembles
        q.state = state
        q.error = error
        q.finished_at = time.perf_counter()
        q.dispatches = _disp.pop_query_count(q.query_id)
        q.coalesced = _disp.pop_query_coalesced(q.query_id)
        q.retry = _retry.pop_owner_stats(q.owner_tag)
        self._counters["oom_retries"] += q.retry["oom_retries"]
        self._counters["oom_splits"] += q.retry["oom_splits"]
        # semantic cache bookkeeping — BEFORE q.plan is dropped below,
        # because publish revalidates the plan's fingerprint against
        # current snapshot versions (a table bumped while this query
        # ran must not install a stale result under a fresh key)
        if q.result_cache_key is not None:
            if self._result_leaders.get(q.result_cache_key) is q:
                self._result_leaders.pop(q.result_cache_key, None)
            if state is QueryState.DONE and q.result is not None \
                    and q.plan is not None:
                self.cache.publish_result(q.result_cache_key, q.plan,
                                          q.result)
        if q.pending_fragments:
            # capture entries this query registered but never published
            # (failed/cancelled, or the capture path was never driven):
            # drop them so a future query can retry the capture
            self.cache.abort_pending(q.pending_fragments)
            q.pending_fragments = []
        if q.served_fragments:
            # graft-time pins on the READY entries this query's serve
            # leaves referenced — held since submit so eviction could
            # not close the stored parts while the query sat queued
            self.cache.release_served(q.served_fragments)
            q.served_fragments = []
        followers = [f for f in q.cache_followers if not f.terminal]
        q.cache_followers = []
        if followers:
            if state is QueryState.DONE and q.result is not None:
                for f in followers:
                    f.result = q.result.copy()
                    f.admitted_at = f.started_at = time.perf_counter()
                    self._finalize_locked(f, QueryState.DONE)
            else:
                self._promote_follower_locked(q, state, error,
                                              followers)
        # release every resource the query may still hold: admission
        # charge, catalog buffers (an abandoned exec tree must not leak
        # staged batches), and its execution cursor
        self.admission.release(q)
        get_catalog().remove_owner(q.owner_tag)
        # drop the heavy execution state: the retention registry keeps
        # up to FINISHED_RETENTION terminal queries for stats history,
        # and pinning each one's exec/plan tree and staged frames would
        # grow host RAM with query size, not query count. q.result
        # stays — handle.result() after completion is the contract.
        q._iters = {}
        q.frames = {}
        q.exec = None
        q.plan = None
        if state is QueryState.DONE:
            self._counters["done"] += 1
        elif state is QueryState.CANCELLED:
            self._counters["cancelled"] += 1
        elif state is QueryState.FAILED:
            self._counters["failed"] += 1
            if isinstance(error, DeadlineExceeded):
                self._counters["deadline_expired"] += 1
        qt, rt = q.queue_time_s(), q.run_time_s()
        if qt is not None:
            self._queue_time.add(qt)
        if rt is not None and q.admitted_at is not None:
            self._run_time.add(rt)
        self.scheduler.drop(q)
        self._retain_locked(q)
        self._pump_locked()
        self._done_cv.notify_all()

    def _promote_follower_locked(self, leader: Query, state: QueryState,
                                 error, followers) -> None:
        """The single-flight leader finalized WITHOUT a result
        (cancelled / failed / deadline-expired). Followers are
        independent client submissions that only parked on the
        leader's computation as an optimization — they must not
        inherit its fate: promote the first live one to a fresh leader
        that computes the shared plan itself; the rest stay parked
        behind the new leader (and are promoted in turn if it dies
        too). Falls back to propagating the leader's terminal state
        only when promotion is impossible (service shutting down, plan
        already dropped); a failed replan fails the followers with the
        REPLAN's error, their own."""
        plan, ckey = leader.plan, leader.result_cache_key
        if self._shutdown or plan is None:
            for f in followers:
                self._finalize_locked(f, state, error)
            return
        new_leader, rest = followers[0], followers[1:]
        try:
            planned = self._plan_query(plan, new_leader.tenant)
        except Exception as e:
            for f in followers:
                self._finalize_locked(f, QueryState.FAILED, e)
            return
        from spark_rapids_tpu.execs import adaptive as adaptive_exec

        new_leader.plan = plan
        new_leader.exec = planned["exec"]
        new_leader.stages = planned["stages"]
        new_leader.footprint = planned["footprint"]
        new_leader.out_of_core = planned["out_of_core"]
        new_leader.charge = planned["charge"] \
            if planned["out_of_core"] else planned["footprint"]
        new_leader.pending_fragments = planned["pending"]
        new_leader.served_fragments = planned["served"]
        new_leader.cache_hit = False
        new_leader.cache_followers = rest
        new_leader.result_cache_key = ckey
        if ckey is not None:
            self._result_leaders[ckey] = new_leader
        with adaptive_exec.planning_mode():
            new_leader.planned_partitions = \
                planned["exec"].num_partitions
        self.admission.offer(new_leader)
        # the finalize that triggered this promotion ends in
        # _pump_locked, which admits the new leader if capacity allows

    def _retain_locked(self, q: Query) -> None:
        """Bounded history: a service alive for days must not pin every
        finished query's result frame + exec tree in the registry."""
        self._finished_order.append(q.query_id)
        while len(self._finished_order) > FINISHED_RETENTION:
            self._queries.pop(self._finished_order.pop(0), None)

    def _assemble(self, q: Query):
        """Partition-then-batch order concat — identical row order to
        the serial collect() path (execs/base.collect)."""
        import pandas as pd

        frames = [f for p in sorted(q.frames) for f in q.frames[p]]
        if not frames:
            exec_ = q.exec
            if exec_ is None:
                # an outside finalize (cancel/shutdown) already dropped
                # the tree; _finalize_locked discards this result anyway
                return None
            cols = {n: pd.Series([], dtype=object)
                    for n in exec_.schema.names}
            return pd.DataFrame(cols)
        return pd.concat(frames, ignore_index=True)
