"""Queue-pressure autoscaler: scale-up as a recovery event.

Watches the admission queue from the service's pump and, under
sustained pressure, invokes ``ClusterRuntime.add_host`` — the SAME
elastic-membership seam the lineage-recovery ladder drives when a host
dies (runtime/cluster.py): a new slot spawns, registers with the
transport, and the next task placement can target it. No separate
deployment path, no stage pause; the only difference from recovery is
who asked.

The observer runs under the service lock (rank 20) and the scale-up
takes the cluster recover lock (rank 50) — the same outer-to-inner
direction every service-to-runtime call already follows. Spawning a
process under the service lock is bounded by the cooldown and the
worker ceiling, and costs far less than the queued work it unblocks.
"""
from __future__ import annotations

import time
from typing import Optional

from spark_rapids_tpu import config as cfg


class ClusterAutoscaler:
    """Decides, per admission pump, whether the cluster should grow.

    NOT thread-safe on its own: the service calls ``observe`` under its
    lock, which is the only writer."""

    def __init__(self, conf):
        self.enabled = bool(conf.get(cfg.CLUSTER_AUTOSCALE_ENABLED)
                            and conf.get(cfg.CLUSTER_ENABLED))
        self.queue_high = max(
            conf.get(cfg.CLUSTER_AUTOSCALE_QUEUE_HIGH), 1)
        self.max_workers = max(
            conf.get(cfg.CLUSTER_AUTOSCALE_MAX_WORKERS), 1)
        self.cooldown_s = max(
            conf.get(cfg.CLUSTER_AUTOSCALE_COOLDOWN_SEC), 0.0)
        self.scale_ups = 0
        self.last_reason = ""
        self.last_executor_id = ""
        self._last_at: Optional[float] = None

    def observe(self, queue_depth: int, inflight: int) -> Optional[str]:
        """One pressure observation; returns the new executor id when a
        scale-up fired, else None. Grows only a cluster the session
        already runs (runtime.cluster.active_cluster) — the autoscaler
        never CREATES membership, it extends it."""
        if not self.enabled or queue_depth < self.queue_high:
            return None
        now = time.monotonic()
        if self._last_at is not None and \
                now - self._last_at < self.cooldown_s:
            return None
        from spark_rapids_tpu.runtime.cluster import active_cluster

        runtime = active_cluster()
        if runtime is None:
            return None
        if len(runtime.live_worker_slots()) >= self.max_workers:
            return None
        reason = (f"queue depth {queue_depth} >= {self.queue_high} "
                  f"with {inflight} inflight")
        try:
            eid = runtime.add_host(reason=f"autoscaler: {reason}")
        except (OSError, AssertionError, ValueError):
            # the host would not spawn: admission pressure stays, the
            # next pump (past cooldown) tries again
            self._last_at = now
            return None
        self.scale_ups += 1
        self.last_reason = reason
        self.last_executor_id = eid
        self._last_at = now
        return eid

    def stats(self) -> dict:
        return {"enabled": self.enabled,
                "scale_ups": self.scale_ups,
                "queue_depth_high": self.queue_high,
                "max_workers": self.max_workers,
                "cooldown_sec": self.cooldown_s,
                "last_reason": self.last_reason,
                "last_executor_id": self.last_executor_id}
