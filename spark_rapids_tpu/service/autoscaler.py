"""Queue-pressure autoscaler: scale-up AND scale-down as recovery events.

Watches the admission queue from the service's pump and, under
sustained pressure, invokes ``ClusterRuntime.add_host`` — the SAME
elastic-membership seam the lineage-recovery ladder drives when a host
dies (runtime/cluster.py): a new slot spawns, registers with the
transport, and the next task placement can target it. No separate
deployment path, no stage pause; the only difference from recovery is
who asked.

Scale-DOWN (PR 19) is the mirror image through the mirror seam: when
the queue sits at-or-below ``queueDepthLow`` with nothing inflight for
``idleSec`` straight (and the cooldown since the last scale event — in
either direction — has passed), the newest worker is decommissioned
through ``ClusterRuntime.remove_host``, the planned-removal path PR 18
built: its slot generations die, its map outputs invalidate, anything
a straggling query still needs re-runs via lineage. The floor is
``minWorkers``; disabled entirely while ``queueDepthLow`` is negative
(the default), so existing deployments keep today's grow-only shape.

The observer runs under the service lock (rank 20) and the scale
actions take the cluster recover lock (rank 50) — the same
outer-to-inner direction every service-to-runtime call already
follows. Spawning a process under the service lock is bounded by the
cooldown and the worker ceiling, and costs far less than the queued
work it unblocks.
"""
from __future__ import annotations

import time
from typing import Optional

from spark_rapids_tpu import config as cfg


class ClusterAutoscaler:
    """Decides, per admission pump, whether the cluster should grow or
    shrink.

    NOT thread-safe on its own: the service calls ``observe`` under its
    lock, which is the only writer. Idle time is measured across
    observations, and observations only happen on pumps (submit and
    release) — a fully quiescent service shrinks on its NEXT pump after
    the idle window, not on a timer."""

    def __init__(self, conf):
        self.enabled = bool(conf.get(cfg.CLUSTER_AUTOSCALE_ENABLED)
                            and conf.get(cfg.CLUSTER_ENABLED))
        self.queue_high = max(
            conf.get(cfg.CLUSTER_AUTOSCALE_QUEUE_HIGH), 1)
        #: negative = scale-down disabled (the default)
        self.queue_low = conf.get(cfg.CLUSTER_AUTOSCALE_QUEUE_LOW)
        self.max_workers = max(
            conf.get(cfg.CLUSTER_AUTOSCALE_MAX_WORKERS), 1)
        self.min_workers = max(
            conf.get(cfg.CLUSTER_AUTOSCALE_MIN_WORKERS), 1)
        self.cooldown_s = max(
            conf.get(cfg.CLUSTER_AUTOSCALE_COOLDOWN_SEC), 0.0)
        self.idle_s = max(conf.get(cfg.CLUSTER_AUTOSCALE_IDLE_SEC), 0.0)
        self.scale_ups = 0
        self.scale_downs = 0
        self.last_reason = ""
        self.last_executor_id = ""
        self.last_removed_executor_id = ""
        self._last_at: Optional[float] = None
        self._idle_since: Optional[float] = None

    def observe(self, queue_depth: int, inflight: int) -> Optional[str]:
        """One observation; returns the new executor id when a
        scale-UP fired, else None (scale-downs report through
        ``scale_downs``/``last_removed_executor_id``). Only ever
        resizes a cluster the session already runs
        (runtime.cluster.active_cluster) — the autoscaler never CREATES
        membership, it extends or trims it."""
        if not self.enabled:
            return None
        if queue_depth >= self.queue_high:
            self._idle_since = None
            return self._maybe_scale_up(queue_depth, inflight)
        if self.queue_low >= 0 and queue_depth <= self.queue_low \
                and inflight == 0:
            self._maybe_scale_down(queue_depth)
        else:
            # neither pressured nor idle: the idle window restarts
            self._idle_since = None
        return None

    def _maybe_scale_up(self, queue_depth: int,
                        inflight: int) -> Optional[str]:
        now = time.monotonic()
        if self._last_at is not None and \
                now - self._last_at < self.cooldown_s:
            return None
        from spark_rapids_tpu.runtime.cluster import active_cluster

        runtime = active_cluster()
        if runtime is None:
            return None
        if len(runtime.live_worker_slots()) >= self.max_workers:
            return None
        reason = (f"queue depth {queue_depth} >= {self.queue_high} "
                  f"with {inflight} inflight")
        try:
            eid = runtime.add_host(reason=f"autoscaler: {reason}")
        except (OSError, AssertionError, ValueError):
            # the host would not spawn: admission pressure stays, the
            # next pump (past cooldown) tries again
            self._last_at = now
            return None
        self.scale_ups += 1
        self.last_reason = reason
        self.last_executor_id = eid
        self._last_at = now
        return eid

    def _maybe_scale_down(self, queue_depth: int) -> None:
        now = time.monotonic()
        if self._idle_since is None:
            self._idle_since = now
            return
        if now - self._idle_since < self.idle_s:
            return
        if self._last_at is not None and \
                now - self._last_at < self.cooldown_s:
            return
        from spark_rapids_tpu.runtime.cluster import active_cluster

        runtime = active_cluster()
        if runtime is None:
            return
        slots = runtime.live_worker_slots()
        if len(slots) <= self.min_workers:
            return
        victim = slots[-1]  # newest first out: LIFO keeps warm hosts
        reason = (f"queue depth {queue_depth} <= {self.queue_low} with "
                  f"0 inflight for {now - self._idle_since:.1f}s")
        try:
            runtime.remove_host(victim,
                                reason=f"autoscaler: {reason}")
        except (OSError, AssertionError, ValueError, KeyError):
            # decommission refused (e.g. the slot just died on its
            # own); stay idle-armed, the next pump re-evaluates
            self._last_at = now
            return
        self.scale_downs += 1
        self.last_reason = reason
        self.last_removed_executor_id = victim
        self._last_at = now
        self._idle_since = None

    def stats(self) -> dict:
        return {"enabled": self.enabled,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "queue_depth_high": self.queue_high,
                "queue_depth_low": self.queue_low,
                "max_workers": self.max_workers,
                "min_workers": self.min_workers,
                "idle_sec": self.idle_s,
                "cooldown_sec": self.cooldown_s,
                "last_reason": self.last_reason,
                "last_executor_id": self.last_executor_id,
                "last_removed_executor_id":
                    self.last_removed_executor_id}
