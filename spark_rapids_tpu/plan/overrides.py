"""TpuOverrides: the plan-rewrite layer.

Reference: GpuOverrides.scala (rule registry, :536-1932), RapidsMeta.scala
(wrapper tree with tagging reasons, :66-832), GpuTransitionOverrides.scala
(transition/coalesce insertion). Flow (GpuOverrides.scala:1946-1964):

    wrap(plan) -> tag_for_tpu() (children first, with per-op config gates
    and type checks) -> explain -> convert_if_needed() -> coalesce/transition
    insertion.

Subtrees that cannot run on TPU execute on the CPU engine via
CpuFallbackExec; TPU-able children beneath a CPU node still accelerate —
their results cross the device boundary through a precomputed-frame source
(GpuBringBackToHost / HostColumnarToGpu analogues).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Type

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.execs import adaptive as adaptive_exec
from spark_rapids_tpu.execs import aggregate as agg_exec
from spark_rapids_tpu.execs import basic, batching, exchange, joins, sort, \
    window
from spark_rapids_tpu.execs.base import TpuExec
from spark_rapids_tpu.expressions import aggregates as aggfn
from spark_rapids_tpu.expressions import arithmetic, bitwise, cast, \
    conditional, constraints, datetime as dtexpr, math as mathexpr, \
    nondeterministic, predicates, strings
from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Expression, Literal)
from spark_rapids_tpu.plan import nodes as pn


def _session_mesh(conf):
    from spark_rapids_tpu.parallel.mesh import session_mesh

    return session_mesh(conf)


def _in_program_mesh(conf, node, op, **kw):
    """The SPMD whole-stage gate (parallel/spmd.py): the mesh when this
    shuffle boundary folds into the compiled program as an in-program
    all_to_all, else None with the fallback reason recorded for run
    telemetry. Row estimates feed the inProgram.minRows floor.

    ``cluster_local=True`` because every caller here lowers a Mesh*Exec
    SUBTREE: in cluster mode the subtree ships to one executor whole and
    its collective spans only that process's local mesh — the DCN gate
    applies to the cross-process exchanges, not to these."""
    from spark_rapids_tpu.parallel import spmd
    from spark_rapids_tpu.plan.optimizer import estimate_rows

    est = None
    try:
        est = estimate_rows(node.children[0]) if node.children else None
    except Exception:  # estimation must never block planning
        est = None
    return spmd.in_program_mesh(conf, op, est_rows=est,
                                cluster_local=True, **kw)


def _cluster_mode(conf) -> bool:
    return conf is not None and conf.get(cfg.CLUSTER_ENABLED)

# ---------------------------------------------------------------------------
# Expression rule registry (ExprRule analogue, GpuOverrides.scala:536-1621)
# ---------------------------------------------------------------------------


class ExprRule:
    def __init__(self, klass: Type[Expression], incompat: bool = False,
                 desc: str = ""):
        self.klass = klass
        self.incompat = incompat
        self.flag = cfg.register_op_flag(
            "expression", klass.__name__,
            desc or f"TPU replacement of {klass.__name__}",
            incompat="TPU approximation differs in ulps from java.lang.Math"
            if incompat else None)

    def tag(self, e: Expression, meta: "NodeMeta", conf: RapidsConf):
        if not conf.get(self.flag) and not (
                self.incompat and conf.get(cfg.INCOMPATIBLE_OPS)):
            if self.incompat:
                meta.will_not_work(
                    f"expression {self.klass.__name__} is incompatible "
                    f"(enable {self.flag.key} or "
                    f"{cfg.INCOMPATIBLE_OPS.key})")
            else:
                meta.will_not_work(
                    f"expression {self.klass.__name__} disabled by "
                    f"{self.flag.key}")
        if isinstance(e, cast.Cast):
            self._tag_cast(e, meta, conf)
        tag_self = getattr(e, "tag_self", None)
        if tag_self is not None:
            # expression-specific gate (e.g. RegExpReplace's regex-free
            # pattern requirement)
            tag_self(meta, conf)

    @staticmethod
    def _tag_cast(e: cast.Cast, meta: "NodeMeta", conf: RapidsConf):
        src = e.children[0].dtype
        if src.is_floating and e.to is dt.STRING and \
                not conf.get(cfg.CAST_FLOAT_TO_STRING):
            meta.will_not_work(
                f"cast float->string needs {cfg.CAST_FLOAT_TO_STRING.key}")
        if src is dt.STRING and e.to.is_floating and \
                not conf.get(cfg.CAST_STRING_TO_FLOAT):
            meta.will_not_work(
                f"cast string->float needs {cfg.CAST_STRING_TO_FLOAT.key}")
        if src is dt.STRING and e.to is dt.TIMESTAMP and \
                not conf.get(cfg.CAST_STRING_TO_TIMESTAMP):
            meta.will_not_work(
                f"cast string->timestamp needs "
                f"{cfg.CAST_STRING_TO_TIMESTAMP.key}")


_EXPR_RULES: Dict[Type[Expression], ExprRule] = {}


def _register_exprs():
    import inspect

    for mod in (arithmetic, bitwise, predicates, conditional, constraints,
                mathexpr, dtexpr, nondeterministic, strings, cast, aggfn):
        for _, klass in inspect.getmembers(mod, inspect.isclass):
            if not issubclass(klass, Expression):
                continue
            if klass.__module__ != mod.__name__:
                continue
            if klass.__name__.startswith("_"):
                continue
            if vars(klass).get("abstract", False):  # own attr only:
                continue  # subclasses of an abstract template register
            incompat = bool(getattr(klass, "incompat", False))
            _EXPR_RULES[klass] = ExprRule(klass, incompat)
    for klass in (BoundReference, Literal, Alias):
        _EXPR_RULES[klass] = ExprRule(klass)


_register_exprs()


def tag_expression(e: Expression, meta: "NodeMeta", conf: RapidsConf):
    rule = _EXPR_RULES.get(type(e))
    if rule is None:
        meta.will_not_work(
            f"expression {type(e).__name__} has no TPU implementation")
        return
    rule.tag(e, meta, conf)
    for c in e.children:
        if c is not None:
            tag_expression(c, meta, conf)


# ---------------------------------------------------------------------------
# Node metas
# ---------------------------------------------------------------------------


class NodeMeta:
    """SparkPlanMeta analogue (RapidsMeta.scala:418): per-node tag state.

    A plan node OBJECT referenced from several tree positions (CTE
    reuse — plan_statement shares each CTE's plan node across its
    references) gets ONE meta and converts to ONE exec: exchanges and
    broadcasts under the shared subtree then materialize once for every
    consumer (Spark's ReuseExchange/ReuseSubquery role)."""

    def __init__(self, node: pn.PlanNode, conf: RapidsConf, _memo=None):
        self.node = node
        self.conf = conf
        _memo = {} if _memo is None else _memo
        self.children = [NodeMeta._shared(c, conf, _memo)
                         for c in node.children]
        self.reasons: List[str] = []
        self.rule = _NODE_RULES.get(type(node))
        self._converted: Optional[TpuExec] = None
        self._tagged = False

    @staticmethod
    def _shared(node: pn.PlanNode, conf: RapidsConf,
                memo: dict) -> "NodeMeta":
        hit = memo.get(id(node))
        if hit is None:
            hit = NodeMeta(node, conf, memo)
            memo[id(node)] = hit  # meta holds node: id stays pinned
        return hit

    def will_not_work(self, reason: str):
        if reason not in self.reasons:
            self.reasons.append(reason)

    @property
    def can_run(self) -> bool:
        return not self.reasons

    def tag_for_tpu(self):
        if self._tagged:
            return
        self._tagged = True
        for c in self.children:
            c.tag_for_tpu()
        if not self.conf.get(cfg.SQL_ENABLED):
            self.will_not_work(f"{cfg.SQL_ENABLED.key} is false")
            return
        if self.rule is None:
            self.will_not_work(
                f"node {self.node.name} has no TPU implementation")
            return
        flag = cfg.register_op_flag("exec", type(self.node).__name__,
                                    f"TPU replacement of {self.node.name}")
        if not self.conf.get(flag):
            self.will_not_work(f"exec disabled by {flag.key}")
            return
        self.rule.tag(self)

    def explain(self, indent: int = 0, only_not_on_tpu: bool = False
                ) -> str:
        mark = "*" if self.can_run else "!"
        line = "  " * indent + f"{mark} {self.node.describe()}"
        if self.reasons:
            line += "  <-- " + "; ".join(self.reasons)
        lines = [] if (only_not_on_tpu and self.can_run) else [line]
        for c in self.children:
            sub = c.explain(indent + 1, only_not_on_tpu)
            if sub:
                lines.append(sub)
        return "\n".join(lines)

    # -- conversion ----------------------------------------------------

    def convert(self) -> TpuExec:
        if self._converted is not None:
            return self._converted
        if self.can_run:
            tpu_children = [c.convert() for c in self.children]
            self._converted = self.rule.convert(self, tpu_children)
        else:
            self._converted = self._convert_fallback()
        return self._converted

    def _convert_fallback(self) -> TpuExec:
        """Run this node on the CPU engine. TPU-able children still
        accelerate: their device output crosses back through a
        precomputed-frame source."""
        tpu_subtrees: List[TpuExec] = []
        new_children: List[pn.PlanNode] = []
        for c in self.children:
            if c.can_run:
                child_exec = insert_coalesce(c.convert())
                tpu_subtrees.append(child_exec)
                new_children.append(pn.ScanNode(_DeferredTpuSource(
                    child_exec, c.node.output_schema())))
            else:
                new_children.append(c._fallback_plan())
        node = self.node.with_children(new_children) if self.children \
            else self.node
        return basic.CpuFallbackExec(node, self.node.output_schema(),
                                     self.reasons, tpu_subtrees)

    def _fallback_plan(self) -> pn.PlanNode:
        """Plan node for CPU execution with TPU-able descendants swapped
        for deferred device sources."""
        if self.can_run:
            child_exec = insert_coalesce(self.convert())
            return pn.ScanNode(_DeferredTpuSource(
                child_exec, self.node.output_schema()))
        if not self.children:
            return self.node
        return self.node.with_children(
            [c._fallback_plan() for c in self.children])


class _DeferredTpuSource(pn.DataSource):
    """DataSource over a TPU exec's (lazily collected) output — the
    GpuBringBackToHost boundary."""

    def __init__(self, exec_: TpuExec, schema: Schema):
        self.exec = exec_
        self._schema = schema

    def schema(self) -> Schema:
        return self._schema

    def read_host(self):
        import numpy as np

        from spark_rapids_tpu.execs.interop import batch_to_frame

        frames = []
        for p in range(self.exec.num_partitions):
            for b in self.exec.execute(p):
                if b.realized_num_rows() == 0:
                    continue
                frames.append(batch_to_frame(b, self._schema))
        data: Dict[str, np.ndarray] = {}
        validity: Dict[str, np.ndarray] = {}
        for i, name in enumerate(self._schema.names):
            typ = self._schema.types[i]
            if frames:
                data[name] = np.concatenate(
                    [f.cols[i].data for f in frames])
                validity[name] = np.concatenate(
                    [f.cols[i].valid_mask() for f in frames])
            else:
                data[name] = np.array(
                    [], dtype=object if typ is dt.STRING else typ.np_dtype)
                validity[name] = np.array([], dtype=bool)
        return data, validity


# ---------------------------------------------------------------------------
# Node rules (ExecRule analogue)
# ---------------------------------------------------------------------------


class NodeRule:
    def tag(self, meta: NodeMeta):
        pass

    def convert(self, meta: NodeMeta, children: List[TpuExec]) -> TpuExec:
        raise NotImplementedError


def _adaptive_read(ex: exchange.ShuffleExchangeExec,
                   conf: RapidsConf) -> TpuExec:
    """Wrap a multi-partition exchange in an adaptive coalescing reader
    (AQE's coalesce-shuffle-partitions applied with exact statistics).
    Works under cluster mode too: statistics come from the exchange's
    ``map_output_sizes`` — the cluster subclass answers from the
    MapOutputTracker's MapStatus sizes instead of an in-process block
    store (GpuShuffleExchangeExec.scala:95-101 map stats future)."""
    if not conf.get(cfg.ADAPTIVE_ENABLED) or ex.num_out_partitions <= 1:
        return ex
    return adaptive_exec.AdaptiveShuffleReaderExec(
        ex, conf.get(cfg.ADVISORY_PARTITION_SIZE))


def _check_types(meta: NodeMeta, types, what: str):
    for t in types:
        if not dt.is_supported(t):
            meta.will_not_work(f"{what}: type {t} not supported")


class _ScanRule(NodeRule):
    def tag(self, meta: NodeMeta):
        _check_types(meta, meta.node.output_schema().types, "scan")
        src = meta.node.source
        from spark_rapids_tpu.io.csv import CsvSource
        from spark_rapids_tpu.io.orc import OrcSource
        from spark_rapids_tpu.io.parquet import ParquetSource

        gates = {
            ParquetSource: (cfg.PARQUET_ENABLED, cfg.PARQUET_READ_ENABLED),
            OrcSource: (cfg.ORC_ENABLED, cfg.ORC_READ_ENABLED),
            CsvSource: (cfg.CSV_ENABLED, cfg.CSV_READ_ENABLED),
        }
        for klass, (fmt_flag, read_flag) in gates.items():
            if isinstance(src, klass):
                for flag in (fmt_flag, read_flag):
                    if not meta.conf.get(flag):
                        meta.will_not_work(
                            f"{klass.__name__} scan disabled by "
                            f"{flag.key}")
        # CSV timestamp compat gate (RapidsConf.scala:482 analogue):
        # timestamp text parses only under the configured formats, so
        # scans producing TIMESTAMP columns need the explicit opt-in
        if isinstance(src, CsvSource) and \
                not meta.conf.get(cfg.CSV_TIMESTAMPS_ENABLED) and \
                any(t is dt.TIMESTAMP
                    for t in meta.node.output_schema().types):
            meta.will_not_work(
                "CSV TIMESTAMP columns disabled by "
                f"{cfg.CSV_TIMESTAMPS_ENABLED.key} (formats gated by "
                f"{cfg.CSV_TIMESTAMP_FORMATS.key})")

    def convert(self, meta, children):
        node: pn.ScanNode = meta.node
        from spark_rapids_tpu.ml.handoff import DeviceBatchesSource

        if isinstance(node.source, DeviceBatchesSource):
            # already on device: serve as-is, no host round trip
            return basic.DeviceBatchesExec(node.source,
                                           node.output_schema())
        rows = meta.conf.get(cfg.MAX_READER_BATCH_SIZE_ROWS)
        # file sources default to DEFAULT_CONF: hand them the session
        # conf so reader knobs (split packing targets, read threads)
        # follow the session, not construction-time defaults. Only
        # before splits are derived — a source already being read
        # keeps the split layout it advertised.
        src = node.source
        if hasattr(src, "conf") and \
                getattr(src, "_splits", None) is None:
            src.conf = meta.conf
        return basic.ScanExec(node.source, node.output_schema(),
                              batch_rows=rows,
                              pack=meta.conf.get(cfg.SCAN_PACK_TRANSFERS))


class _WriteRule(NodeRule):
    def tag(self, meta: NodeMeta):
        from spark_rapids_tpu.io.write import WriteFilesNode

        node: WriteFilesNode = meta.node
        _check_types(meta, node.children[0].output_schema().types, "write")
        gates = {
            "parquet": (cfg.PARQUET_ENABLED, cfg.PARQUET_WRITE_ENABLED),
            "orc": (cfg.ORC_ENABLED, cfg.ORC_WRITE_ENABLED),
        }
        for flag in gates[node.format]:
            if not meta.conf.get(flag):
                meta.will_not_work(
                    f"{node.format} write disabled by {flag.key}")

    def convert(self, meta, children):
        from spark_rapids_tpu.io.write import WriteFilesExec

        return WriteFilesExec(meta.node, children[0])


class _RangeRule(NodeRule):
    def convert(self, meta, children):
        node: pn.RangeNode = meta.node
        return basic.RangeExec(node.start, node.end, node.step,
                               node.output_schema())


class _ProjectRule(NodeRule):
    def tag(self, meta: NodeMeta):
        for e in meta.node.exprs:
            tag_expression(e, meta, meta.conf)

    def convert(self, meta, children):
        node: pn.ProjectNode = meta.node
        return basic.ProjectExec(node.exprs, children[0],
                                 node.output_schema(), meta.conf)


class _FilterRule(NodeRule):
    def tag(self, meta: NodeMeta):
        tag_expression(meta.node.condition, meta, meta.conf)

    def convert(self, meta, children):
        return basic.FilterExec(meta.node.condition, children[0], meta.conf)


_SUPPORTED_AGGS = (aggfn.Min, aggfn.Max, aggfn.Sum, aggfn.Count,
                   aggfn.Average, aggfn.First, aggfn.Last,
                   aggfn.StddevSamp, aggfn.StddevPop,
                   aggfn.VarianceSamp, aggfn.VariancePop)


class _AggregateRule(NodeRule):
    def tag(self, meta: NodeMeta):
        node: pn.AggregateNode = meta.node
        for e in node.grouping:
            tag_expression(e, meta, meta.conf)
        for call in node.aggs:
            if not isinstance(call.fn, _SUPPORTED_AGGS):
                meta.will_not_work(
                    f"aggregate {type(call.fn).__name__} not implemented")
                continue
            if call.fn.distinct:
                meta.will_not_work("distinct aggregates fall back")
            if call.fn.input is not None:
                tag_expression(call.fn.input, meta, meta.conf)

    @staticmethod
    def _fuse_filter(child: TpuExec):
        """Aggregate-over-filter fuses the keep-mask into the groupby
        sort (one fewer compaction executable per batch)."""
        if isinstance(child, basic.FilterExec) and \
                child.filter.fused and \
                child.filter.condition.deterministic:
            return child.children[0], child.filter
        return child, None

    def convert(self, meta, children):
        node: pn.AggregateNode = meta.node
        child = children[0]
        out_schema = node.output_schema()
        if node.mode != "complete":
            child, ff = self._fuse_filter(child)
            return agg_exec.HashAggregateExec(
                node.grouping, node.aggs, child, out_schema,
                mode=node.mode, conf=meta.conf, fused_filter=ff)
        mesh = _in_program_mesh(
            meta.conf, node, "groupby", keyed=bool(node.grouping),
            reason_if_unkeyed="ungrouped aggregate funnels to one "
                              "device")
        if mesh is not None:
            # mesh lowering: the partial/exchange/final pipeline becomes
            # one all_to_all + local-groupby program per chip
            from spark_rapids_tpu.parallel.execs import MeshGroupByExec

            return MeshGroupByExec(node.grouping, node.aggs, child,
                                   out_schema, meta.conf, mesh)
        if child.num_partitions == 1:
            child, ff = self._fuse_filter(child)
            return agg_exec.HashAggregateExec(
                node.grouping, node.aggs, child, out_schema,
                mode="complete", conf=meta.conf, fused_filter=ff)
        # distributed: partial -> exchange -> final (the physical split
        # Spark's planner produces, aggregate.scala partial/final modes)
        pnames = list(node.grouping_names)
        ptypes = [e.dtype for e in node.grouping]
        for a in node.aggs:
            for j, pt in enumerate(a.fn.partial_types()):
                pnames.append(f"{a.name}#p{j}")
                ptypes.append(pt)
        partial_schema = Schema(pnames, ptypes)
        child, ff = self._fuse_filter(child)
        partial = agg_exec.HashAggregateExec(
            node.grouping, node.aggs, child, partial_schema,
            mode="partial", conf=meta.conf, fused_filter=ff)
        nkeys = len(node.grouping)
        if nkeys:
            ex = _adaptive_read(exchange.ShuffleExchangeExec(
                ("hash", list(range(nkeys))),
                min(cfg.resolve_shuffle_partitions(meta.conf),
                    max(child.num_partitions, 1)),
                partial,
                task_threads=meta.conf.get(cfg.TASK_THREADS)),
                meta.conf)
        else:
            ex = exchange.ShuffleExchangeExec(
                ("single",), 1, partial,
                task_threads=meta.conf.get(cfg.TASK_THREADS))
        final_grouping = [BoundReference(i, e.dtype)
                          for i, e in enumerate(node.grouping)]
        return agg_exec.HashAggregateExec(
            final_grouping, node.aggs, ex, out_schema, mode="final",
            conf=meta.conf)


class _SortRule(NodeRule):
    def tag(self, meta: NodeMeta):
        _check_types(meta, meta.node.output_schema().types, "sort")

    def convert(self, meta, children):
        node: pn.SortNode = meta.node
        child = children[0]
        # a non-global sort has no exchange to fold — only ORDER BY
        # consults the SPMD gate (so no fallback noise for local sorts)
        mesh = _in_program_mesh(meta.conf, node, "sort") \
            if node.global_sort else None
        if mesh is not None:
            from spark_rapids_tpu.parallel.execs import MeshSortExec

            return MeshSortExec(node.specs, child,
                                node.output_schema(), meta.conf, mesh)
        if node.global_sort and child.num_partitions > 1:
            parts = min(cfg.resolve_shuffle_partitions(meta.conf),
                        child.num_partitions)
            if parts > 1:
                # distributed global sort: range-partition on sampled
                # bounds (full key tuples for multi-key sorts), then
                # sort each range-ordered partition — no
                # single-partition funnel (GpuRangePartitioning +
                # GpuSortExec, avoiding the SURVEY §5.7 cliff)
                child = exchange.ShuffleExchangeExec(
                    ("range", list(node.specs), None), parts, child,
                    task_threads=meta.conf.get(cfg.TASK_THREADS),
                    batch_bytes=meta.conf.get(cfg.BATCH_SIZE_BYTES))
            else:
                child = exchange.ShuffleExchangeExec(
                    ("single",), 1, child,
                    task_threads=meta.conf.get(cfg.TASK_THREADS))
        return sort.SortExec(
            node.specs, child, global_sort=node.global_sort,
            batch_bytes=meta.conf.get(cfg.BATCH_SIZE_BYTES))


class _LimitRule(NodeRule):
    def convert(self, meta, children):
        node: pn.LimitNode = meta.node
        child = children[0]
        limited = basic.LocalLimitExec(node.n, child)
        if node.global_limit and child.num_partitions > 1:
            ex = exchange.ShuffleExchangeExec(
                ("single",), 1, limited,
                task_threads=meta.conf.get(cfg.TASK_THREADS))
            return basic.LocalLimitExec(node.n, ex)
        return limited


class _UnionRule(NodeRule):
    def convert(self, meta, children):
        return basic.UnionExec(children, meta.node.output_schema())


class _ExpandRule(NodeRule):
    def tag(self, meta: NodeMeta):
        for p in meta.node.projections:
            for e in p:
                tag_expression(e, meta, meta.conf)

    def convert(self, meta, children):
        node: pn.ExpandNode = meta.node
        return basic.ExpandExec(node.projections, children[0],
                                node.output_schema(), meta.conf)


class _GenerateRule(NodeRule):
    """GpuGenerateExecSparkPlanMeta analogue: only explode/posexplode of a
    created array is supported (GpuGenerateExec.scala:66-82); lowering
    desugars the generator into Expand projections (one per array slot)
    so the existing ExpandExec kernel runs it."""

    def tag(self, meta: NodeMeta):
        node: pn.GenerateNode = meta.node
        for e in node.exprs:
            tag_expression(e, meta, meta.conf)
        _check_types(meta, node.output_schema().types, "generate")

    def convert(self, meta, children):
        node: pn.GenerateNode = meta.node
        return basic.ExpandExec(node.expand_projections(), children[0],
                                node.output_schema(), meta.conf)


_BNLJ_FLAG = cfg.register_op_flag(
    "exec", "BroadcastNestedLoopJoinExec",
    "Brute-force cross/conditioned join streaming the left side against a "
    "broadcast right side; the full pair grid is materialized per batch "
    "(GpuOverrides.scala:1837-1840 disables it by default for the same "
    "OOM risk)", default_enabled=False)
_CARTESIAN_FLAG = cfg.register_op_flag(
    "exec", "CartesianProductExec",
    "Brute-force cartesian product over the left x right partition grid "
    "(GpuOverrides.scala:1841-1856 disables it by default for the same "
    "OOM risk)", default_enabled=False)


class _JoinRule(NodeRule):
    def tag(self, meta: NodeMeta):
        node: pn.JoinNode = meta.node
        if node.condition is not None and node.kind not in ("inner",
                                                            "cross"):
            meta.will_not_work(
                "conditioned outer joins are post-join-filter unsafe "
                "(GpuHashJoin.scala:285-291 applies the same restriction)")
        if node.kind == "cross" and not (meta.conf.get(_BNLJ_FLAG) or
                                         meta.conf.get(_CARTESIAN_FLAG)):
            meta.will_not_work(
                "cross joins are disabled by default (OOM risk, "
                f"GpuOverrides.scala:1837-1856); set {_BNLJ_FLAG.key} or "
                f"{_CARTESIAN_FLAG.key} to true")
        if node.condition is not None:
            tag_expression(node.condition, meta, meta.conf)
        ls = node.children[0].output_schema()
        rs = node.children[1].output_schema()
        _check_types(meta, ls.types, "join left")
        _check_types(meta, rs.types, "join right")

    def convert(self, meta, children):
        node: pn.JoinNode = meta.node
        left, right = children
        out_schema = node.output_schema()
        kind = node.kind
        lk, rk = node.left_keys, node.right_keys
        cond = node.condition
        if kind == "right":
            # flip: stream the (former) right side, build the left, then
            # reorder output columns (Spark310 buildSide-flip analogue).
            # Conditioned right joins were rejected at tag time.
            inner_schema = _concat_schema(right.schema, left.schema)
            flipped = self._plan(meta, "left", right, left, rk, lk, None,
                                 inner_schema,
                                 build_node=node.children[0])
            nr = len(right.schema)
            reorder = [BoundReference(nr + i, t)
                       for i, t in enumerate(left.schema.types)] + \
                      [BoundReference(i, t)
                       for i, t in enumerate(right.schema.types)]
            reorder = [Alias(e, n)
                       for e, n in zip(reorder, out_schema.names)]
            return basic.ProjectExec(reorder, flipped, out_schema,
                                     meta.conf)
        return self._plan(meta, kind, left, right, lk, rk, cond,
                          out_schema, build_node=node.children[1])

    @staticmethod
    def _plan(meta, kind, left, right, lk, rk, cond, out_schema,
              build_node=None):
        supported = bool(lk) and kind in ("inner", "left", "left_semi",
                                          "left_anti", "full")
        mesh = _in_program_mesh(
            meta.conf, meta.node, "join", keyed=supported,
            reason_if_unkeyed=("no equi-join keys to hash-route" if not lk
                               else f"unsupported join kind '{kind}'"))
        if mesh is not None:
            # right joins arrive here already flipped to "left" (convert()
            # above); "full" composes left + null-extended anti halves with
            # a sharded union (GpuHashJoin.scala:302-318 emits FullOuter
            # from one kernel; the mesh shape is two programs + a union)
            from spark_rapids_tpu.parallel.execs import MeshShuffledJoinExec

            return MeshShuffledJoinExec(kind, left, right, lk, rk,
                                        out_schema, cond, meta.conf, mesh)
        multi = left.num_partitions > 1 or right.num_partitions > 1
        if multi and lk and kind in ("inner", "left", "left_semi",
                                     "left_anti") and \
                build_node is not None:
            # Spark's autoBroadcastJoinThreshold: a small ESTIMATED
            # build side broadcasts instead of shuffling both sides -
            # two exchange pipelines (partition + split + concat
            # dispatches per batch) collapse into one materialize
            from spark_rapids_tpu.plan.optimizer import estimate_rows

            thr = meta.conf.get(cfg.AUTO_BROADCAST_THRESHOLD)
            est = estimate_rows(build_node) if thr > 0 else None
            row_bytes = max(sum(t.byte_width
                                for t in right.schema.types), 1)
            if est is not None and est * row_bytes <= thr:
                build = exchange.BroadcastExchangeExec(right)
                return joins.BroadcastHashJoinExec(
                    kind, left, _ReplayExec(build, left.num_partitions),
                    lk, rk, out_schema, cond, meta.conf)
        if kind == "cross":
            # brute-force joins: nested-loop when the right side is already
            # a single partition (broadcast is then free) or when the
            # partition-grid cartesian isn't enabled; a multi-partition
            # right side with both flags on goes to CartesianProductExec
            # rather than funneling it whole into one device batch
            use_bnlj = meta.conf.get(_BNLJ_FLAG) and (
                right.num_partitions == 1 or
                not meta.conf.get(_CARTESIAN_FLAG))
            if use_bnlj:
                if right.num_partitions > 1:
                    right = exchange.ShuffleExchangeExec(
                        ("single",), 1, right,
                        task_threads=meta.conf.get(cfg.TASK_THREADS))
                build = exchange.BroadcastExchangeExec(right)
                return joins.BroadcastNestedLoopJoinExec(
                    left, _ReplayExec(build, left.num_partitions),
                    out_schema, cond, meta.conf)
            return joins.CartesianProductExec(left, right, out_schema,
                                              cond, meta.conf)
        if multi:
            parts = cfg.resolve_shuffle_partitions(meta.conf)
            tt = meta.conf.get(cfg.TASK_THREADS)
            lex = exchange.ShuffleExchangeExec(("hash", lk), parts, left,
                                               task_threads=tt)
            rex = exchange.ShuffleExchangeExec(("hash", rk), parts, right,
                                               task_threads=tt)
            if meta.conf.get(cfg.ADAPTIVE_ENABLED) and parts > 1:
                # defer the final join strategy to EXECUTE time: once
                # the build-side map stage has materialized, the
                # adaptive exec picks broadcast vs shuffled-hash vs
                # dense-probe from MEASURED sizes, and its paired
                # readers split skewed partitions (one shared group
                # spec keeps the sides partition-aligned; cluster mode
                # included — stats come from the tracker)
                return adaptive_exec.AdaptiveShuffledJoinExec(
                    kind, lex, rex, lk, rk, out_schema, cond, meta.conf)
            return joins.ShuffledHashJoinExec(
                kind, lex, rex, lk, rk, out_schema, cond, meta.conf)
        build = exchange.BroadcastExchangeExec(right)
        # broadcast replays its single partition to every stream partition
        return joins.BroadcastHashJoinExec(
            kind, left, _ReplayExec(build, left.num_partitions), lk, rk,
            out_schema, cond, meta.conf)


class _ReplayExec(TpuExec):
    """Presents a 1-partition child (broadcast) as n identical partitions."""

    def __init__(self, child: TpuExec, n: int):
        super().__init__([child], child.schema)
        self._n = max(n, 1)

    @property
    def num_partitions(self) -> int:
        return self._n

    @property
    def coalesce_after(self):
        return self.children[0].coalesce_after

    def execute(self, partition: int = 0):
        return self.children[0].execute(0)


def _concat_schema(a: Schema, b: Schema) -> Schema:
    return Schema(list(a.names) + list(b.names),
                  list(a.types) + list(b.types))


def _default_coercible(in_t: dt.DType, default) -> bool:
    """Can ``default`` be stored in a column of ``in_t``'s physical dtype?
    (lead/lag fill value; WindowExec materializes it with jnp.asarray)."""
    if isinstance(default, bool):
        return True  # bool coerces into every numeric physical dtype
    if in_t.is_integral or in_t in (dt.DATE, dt.TIMESTAMP):
        return isinstance(default, int)
    if in_t.is_floating:
        return isinstance(default, (int, float))
    if in_t is dt.BOOLEAN:
        return False  # non-bool default over a boolean column
    return False


class _WindowRule(NodeRule):
    def tag(self, meta: NodeMeta):
        node: pn.WindowNode = meta.node
        for c in node.calls:
            if isinstance(c.fn, aggfn.AggregateFunction):
                if not isinstance(c.fn, (aggfn.Sum, aggfn.Count,
                                         aggfn.Average, aggfn.Min,
                                         aggfn.Max, aggfn.First,
                                         aggfn.Last)):
                    meta.will_not_work(
                        f"window aggregate {type(c.fn).__name__} "
                        "not implemented")
                if isinstance(c.fn, (aggfn.First, aggfn.Last)) and \
                        c.fn.ignore_nulls:
                    meta.will_not_work(
                        "first/last(ignoreNulls) windows fall back")
                if c.frame.kind == "range":
                    self._tag_range_frame(c, node, meta)
                elif isinstance(c.fn, (aggfn.Min, aggfn.Max)) and \
                        not (c.frame.lower is None and
                             c.frame.upper in (0, None)):
                    meta.will_not_work(
                        "bounded min/max window frames fall back "
                        "(GpuWindowExpression.scala frame checks analogue)")
                if c.fn.input is not None:
                    tag_expression(c.fn.input, meta, meta.conf)
                if c.fn.input is not None and \
                        c.fn.input.dtype is dt.STRING:
                    meta.will_not_work("string window aggregates fall back")
            elif isinstance(c.fn, tuple):
                kind = c.fn[0]
                if kind not in ("lead", "lag"):
                    meta.will_not_work(f"window shift {kind!r} unknown")
                    continue
                tag_expression(c.fn[1], meta, meta.conf)
                if c.default is not None:
                    in_t = c.fn[1].dtype
                    if in_t is dt.STRING:
                        meta.will_not_work(
                            "lead/lag default over strings falls back")
                    elif not _default_coercible(in_t, c.default):
                        meta.will_not_work(
                            f"lead/lag default {c.default!r} does not "
                            f"coerce to {in_t} column")
            elif c.fn not in ("row_number", "rank", "dense_rank"):
                meta.will_not_work(f"window function {c.fn} unknown")

    @staticmethod
    def _tag_range_frame(c, node: pn.WindowNode, meta: NodeMeta):
        """Device range frames: single ascending order key of an
        orderable numeric/date/timestamp type, sum/count/avg only (the
        reference limits range frames to timestamp keys,
        GpuWindowExpression.scala:208-263 — ours are wider but min/max
        still fall back)."""
        if isinstance(c.fn, (aggfn.Min, aggfn.Max)):
            meta.will_not_work("range-framed min/max windows fall back")
            return
        if len(node.order_specs) != 1:
            meta.will_not_work(
                "range frames need exactly one order key")
            return
        spec = node.order_specs[0]
        if not spec.ascending:
            meta.will_not_work("descending range frames fall back")
        kt = node.children[0].output_schema().types[spec.ordinal]
        if not (kt.is_numeric or kt in (dt.DATE, dt.TIMESTAMP)):
            meta.will_not_work(
                f"range frame over {kt} order key falls back")

    def convert(self, meta, children):
        node: pn.WindowNode = meta.node
        child = children[0]
        mesh = _in_program_mesh(
            meta.conf, node, "window",
            keyed=bool(node.partition_ordinals),
            reason_if_unkeyed="window without PARTITION BY funnels to "
                              "one device")
        if mesh is not None:
            # partition-by windows lower onto the mesh: the hash
            # exchange + per-partition window (GpuWindowExec.scala:92)
            # fuse into one all_to_all + per-chip kernel program
            from spark_rapids_tpu.parallel.execs import MeshWindowExec

            return MeshWindowExec(node.partition_ordinals,
                                  node.order_specs, node.calls, child,
                                  node.output_schema(), meta.conf, mesh)
        if child.num_partitions > 1:
            if node.partition_ordinals:
                parts = cfg.resolve_shuffle_partitions(meta.conf)
                child = _adaptive_read(exchange.ShuffleExchangeExec(
                    ("hash", node.partition_ordinals), parts, child,
                    task_threads=meta.conf.get(cfg.TASK_THREADS)),
                    meta.conf)
            else:
                child = exchange.ShuffleExchangeExec(
                    ("single",), 1, child,
                    task_threads=meta.conf.get(cfg.TASK_THREADS))
        return window.WindowExec(node.partition_ordinals, node.order_specs,
                                 node.calls, child, node.output_schema(),
                                 meta.conf)


class _CoalescePartitionsRule(NodeRule):
    def convert(self, meta, children):
        return basic.CoalescePartitionsExec(meta.node.num_partitions,
                                            children[0])


class _ExchangeRule(NodeRule):
    def convert(self, meta, children):
        node: pn.ShuffleExchangeNode = meta.node
        return exchange.ShuffleExchangeExec(
            node.partitioning, node.num_partitions, children[0],
            task_threads=meta.conf.get(cfg.TASK_THREADS))


class _BroadcastRule(NodeRule):
    def convert(self, meta, children):
        return exchange.BroadcastExchangeExec(children[0])


class _CacheRule(NodeRule):
    def convert(self, meta, children):
        from spark_rapids_tpu.execs.cache import CachedExec

        return CachedExec(meta.node, children[0])


class _FragmentRule(NodeRule):
    def convert(self, meta, children):
        from spark_rapids_tpu.service.cache.fragments import (
            FragmentCaptureExec, FragmentServeExec)

        if children:
            return FragmentCaptureExec(meta.node, children[0])
        return FragmentServeExec(meta.node)


class _MapInPandasRule(NodeRule):
    def convert(self, meta, children):
        from spark_rapids_tpu.execs.python_exec import MapInPandasExec

        return MapInPandasExec(meta.node, children[0],
                               conf=meta.conf)


class _CoGroupedMapRule(NodeRule):
    def convert(self, meta, children):
        from spark_rapids_tpu.execs.python_exec import \
            CoGroupedMapInPandasExec

        node = meta.node
        left, right = children
        if left.num_partitions > 1 or right.num_partitions > 1:
            parts = cfg.resolve_shuffle_partitions(meta.conf)
            tt = meta.conf.get(cfg.TASK_THREADS)
            left = exchange.ShuffleExchangeExec(
                ("hash", list(node.left_ordinals)), parts, left,
                task_threads=tt)
            right = exchange.ShuffleExchangeExec(
                ("hash", list(node.right_ordinals)), parts, right,
                task_threads=tt)
        return CoGroupedMapInPandasExec(node, left, right,
                                        conf=meta.conf)


class _GroupedMapRule(NodeRule):
    def convert(self, meta, children):
        from spark_rapids_tpu.execs.python_exec import \
            GroupedMapInPandasExec

        node = meta.node
        child = children[0]
        if child.num_partitions > 1:
            parts = cfg.resolve_shuffle_partitions(meta.conf)
            child = _adaptive_read(exchange.ShuffleExchangeExec(
                ("hash", list(node.grouping_ordinals)), parts, child),
                meta.conf)
        return GroupedMapInPandasExec(node, child,
                                      conf=meta.conf)


class _ArrowEvalPythonRule(NodeRule):
    def convert(self, meta, children):
        from spark_rapids_tpu.execs.python_exec import ArrowEvalPythonExec

        return ArrowEvalPythonExec(meta.node, children[0],
                                   conf=meta.conf)


class _AggInPandasRule(NodeRule):
    def convert(self, meta, children):
        from spark_rapids_tpu.execs.python_exec import AggregateInPandasExec

        node = meta.node
        child = children[0]
        if child.num_partitions > 1:
            parts = cfg.resolve_shuffle_partitions(meta.conf)
            child = _adaptive_read(exchange.ShuffleExchangeExec(
                ("hash", list(node.grouping_ordinals)), parts, child),
                meta.conf)
        return AggregateInPandasExec(node, child,
                                     conf=meta.conf)


class _WindowInPandasRule(NodeRule):
    def convert(self, meta, children):
        from spark_rapids_tpu.execs.python_exec import WindowInPandasExec

        node = meta.node
        child = children[0]
        if child.num_partitions > 1:
            parts = cfg.resolve_shuffle_partitions(meta.conf)
            child = _adaptive_read(exchange.ShuffleExchangeExec(
                ("hash", list(node.partition_ordinals)), parts, child),
                meta.conf)
        return WindowInPandasExec(node, child, conf=meta.conf)


def _register_io_rules():
    from spark_rapids_tpu.execs.cache import CacheNode
    from spark_rapids_tpu.execs.python_exec import MapInPandasNode
    from spark_rapids_tpu.io.write import WriteFilesNode
    # cycle-safe: service/cache/fragments imports execs/memory/plan.nodes
    # only, never this module (the service layer reaches overrides
    # exclusively through function-level imports)
    from spark_rapids_tpu.service.cache.fragments import \
        CachedFragmentNode

    from spark_rapids_tpu.execs.python_exec import (
        AggregateInPandasNode, ArrowEvalPythonNode,
        CoGroupedMapInPandasNode, GroupedMapInPandasNode,
        WindowInPandasNode)

    _NODE_RULES[WriteFilesNode] = _WriteRule()
    _NODE_RULES[MapInPandasNode] = _MapInPandasRule()
    _NODE_RULES[GroupedMapInPandasNode] = _GroupedMapRule()
    _NODE_RULES[CoGroupedMapInPandasNode] = _CoGroupedMapRule()
    _NODE_RULES[WindowInPandasNode] = _WindowInPandasRule()
    _NODE_RULES[ArrowEvalPythonNode] = _ArrowEvalPythonRule()
    _NODE_RULES[AggregateInPandasNode] = _AggInPandasRule()
    _NODE_RULES[CacheNode] = _CacheRule()
    _NODE_RULES[CachedFragmentNode] = _FragmentRule()
    # mirror the reference: pandas execs are off by default because data
    # leaves the accelerator for the Python worker
    # (GpuOverrides.scala:1888-1907)
    cfg.register_op_flag(
        "exec", "MapInPandasNode",
        "Run mapInPandas around the TPU pipeline (device->pandas->device "
        "round trip per batch)", default_enabled=False)
    cfg.register_op_flag(
        "exec", "GroupedMapInPandasNode",
        "Run groupBy().applyInPandas around the TPU pipeline "
        "(co-partitioned device->pandas->device round trip)",
        default_enabled=False)
    cfg.register_op_flag(
        "exec", "CoGroupedMapInPandasNode",
        "Run cogroup().applyInPandas around the TPU pipeline",
        default_enabled=False)
    cfg.register_op_flag(
        "exec", "WindowInPandasNode",
        "Run a pandas window UDF over co-partitioned window partitions "
        "(GpuWindowInPandasExec analogue)", default_enabled=False)
    # scalar pandas UDFs stay enabled by default — the reference likewise
    # keeps GpuArrowEvalPythonExec on (it holds data on the accelerator
    # between the scan and the Python worker, GpuOverrides.scala:1888)
    cfg.register_op_flag(
        "exec", "ArrowEvalPythonNode",
        "Evaluate scalar pandas UDFs per batch and append their columns "
        "(GpuArrowEvalPythonExec analogue)")
    cfg.register_op_flag(
        "exec", "AggregateInPandasNode",
        "Run pandas aggregation UDFs over co-partitioned groups "
        "(GpuAggregateInPandasExec analogue)", default_enabled=False)


_NODE_RULES: Dict[Type[pn.PlanNode], NodeRule] = {
    pn.ScanNode: _ScanRule(),
    pn.RangeNode: _RangeRule(),
    pn.ProjectNode: _ProjectRule(),
    pn.FilterNode: _FilterRule(),
    pn.AggregateNode: _AggregateRule(),
    pn.SortNode: _SortRule(),
    pn.LimitNode: _LimitRule(),
    pn.UnionNode: _UnionRule(),
    pn.ExpandNode: _ExpandRule(),
    pn.GenerateNode: _GenerateRule(),
    pn.JoinNode: _JoinRule(),
    pn.WindowNode: _WindowRule(),
    pn.ShuffleExchangeNode: _ExchangeRule(),
    pn.CoalescePartitionsNode: _CoalescePartitionsRule(),
    pn.BroadcastExchangeNode: _BroadcastRule(),
}

_register_io_rules()


# ---------------------------------------------------------------------------
# File-filter pushdown (GpuParquetScan.scala:228-265 row-group filtering)
# ---------------------------------------------------------------------------

_PUSHDOWN_OPS = {
    predicates.EqualTo: "=",
    predicates.LessThan: "<",
    predicates.LessThanOrEqual: "<=",
    predicates.GreaterThan: ">",
    predicates.GreaterThanOrEqual: ">=",
}

_FLIP = {"=": "=", "<": ">", "<=": ">=", ">": "<", ">=": "<="}


def _split_conjuncts(e: Expression) -> List[Expression]:
    if isinstance(e, predicates.And):
        return (_split_conjuncts(e.children[0])
                + _split_conjuncts(e.children[1]))
    return [e]


def _extract_pushdown(cond: Expression, schema: Schema):
    """-> list of (column, op, value) pruning triples, one per conjunct of
    shape ``col <cmp> literal`` (either side). Literals are already in the
    engine's physical encodings, which is what io/parquet.py _stat_value
    normalizes footer statistics to."""
    out = []
    for c in _split_conjuncts(cond):
        op = _PUSHDOWN_OPS.get(type(c))
        if op is None:
            continue
        left, right = c.children
        if isinstance(left, BoundReference) and isinstance(right, Literal):
            ref, lit, o = left, right, op
        elif isinstance(right, BoundReference) and isinstance(left,
                                                             Literal):
            ref, lit, o = right, left, _FLIP[op]
        else:
            continue
        if lit.value is None:
            continue
        if ref.dtype is dt.STRING and not isinstance(lit.value, str):
            continue
        out.append((schema.names[ref.ordinal], o, lit.value))
    return out


def push_down_file_filters(plan: pn.PlanNode,
                           conf: RapidsConf) -> pn.PlanNode:
    """Rewrite Filter(Scan(file-source)) so the source also receives the
    comparison conjuncts for chunk pruning; the Filter stays (exact
    semantics on device)."""
    from spark_rapids_tpu.io.filesrc import FileSourceBase

    if not conf.get(cfg.FILTER_PUSHDOWN_ENABLED):
        return plan
    new_children = [push_down_file_filters(c, conf)
                    for c in plan.children]
    plan = plan.with_children(new_children) if plan.children else plan
    if isinstance(plan, pn.FilterNode):
        child = plan.children[0]
        if isinstance(child, pn.ScanNode) and \
                isinstance(child.source, FileSourceBase):
            filters = _extract_pushdown(plan.condition,
                                        child.output_schema())
            if filters:
                from spark_rapids_tpu.io import scanpipe

                scanpipe.record_pushdown(len(filters))
                new_scan = pn.ScanNode(child.source.with_filters(filters))
                return plan.with_children([new_scan])
    return plan


# ---------------------------------------------------------------------------
# Transition / coalesce insertion (GpuTransitionOverrides.scala)
# ---------------------------------------------------------------------------


def insert_coalesce(root: TpuExec) -> TpuExec:
    """Insert CoalesceBatchesExec where a child's output doesn't satisfy
    the parent's goal (GpuTransitionOverrides.scala:118-203)."""
    new_children = [insert_coalesce(c) for c in root.children]
    goals = root.children_coalesce_goal
    for i, (child, goal) in enumerate(zip(new_children, goals)):
        if goal is None:
            continue
        produced = child.coalesce_after
        if produced is not None and produced.satisfies(goal):
            continue
        new_children[i] = batching.CoalesceBatchesExec(child, goal)
    root.children = new_children
    return root


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


class PlanOnCpuError(AssertionError):
    """Raised in test mode when part of the plan fell back
    (GpuTransitionOverrides.scala:270-326 assertIsOnTheGpu)."""


def apply_overrides(plan: pn.PlanNode,
                    conf: Optional[RapidsConf] = None) -> TpuExec:
    conf = conf or RapidsConf()
    if conf.get(cfg.COMPILE_CACHE_DIR):
        # before any trace of this query: compiled executables then
        # land in (and come from) the persistent cache
        from spark_rapids_tpu.utils import progcache

        if not progcache.install(conf.get(cfg.COMPILE_CACHE_DIR)):
            import warnings

            warnings.warn(
                f"rapids.tpu.sql.compileCacheDir="
                f"{conf.get(cfg.COMPILE_CACHE_DIR)!r} ignored: a "
                f"different persistent cache "
                f"({progcache.installed_dir()!r}) is already active "
                f"in this process (jax holds one global cache)")
    if conf.get(cfg.UDF_COMPILER_ENABLED):
        from spark_rapids_tpu.udf import compile_udfs_in_plan

        plan = compile_udfs_in_plan(plan)
    if conf.get(cfg.OPTIMIZER_ENABLED):
        from spark_rapids_tpu.plan.optimizer import optimize

        plan = optimize(plan)
    plan = push_down_file_filters(plan, conf)
    pn.gate_split_packing(plan)
    meta = NodeMeta(plan, conf)
    meta.tag_for_tpu()
    explain_mode = conf.get(cfg.EXPLAIN).upper()
    if explain_mode in ("ALL", "NOT_ON_TPU"):
        print(meta.explain(only_not_on_tpu=explain_mode == "NOT_ON_TPU"))
    # plan-time partition-count queries must see STATIC shuffle counts:
    # without this, a rule asking an adaptive reader for num_partitions
    # materializes (executes!) the whole map stage mid-planning, before
    # fusion/coalesce have rewritten the subtree
    with adaptive_exec.planning_mode():
        exec_ = meta.convert()
        if conf.get(cfg.FUSION_ENABLED):
            from spark_rapids_tpu.execs.fused import fuse_pipelines

            exec_ = fuse_pipelines(exec_, conf)
        exec_ = insert_coalesce(exec_)
    if _cluster_mode(conf):
        from spark_rapids_tpu.runtime.cluster import (
            install_cluster_exchanges, session_cluster)

        runtime = session_cluster(conf)
        if runtime is not None:
            exec_ = install_cluster_exchanges(exec_, runtime)
    _enable_in_program_exchanges(exec_, conf)
    if conf.get(cfg.TEST_ENABLED):
        allowed = {s.strip() for s in
                   conf.get(cfg.TEST_ALLOWED_NON_TPU).split(",")
                   if s.strip()}
        _assert_on_tpu(exec_, allowed)
    # label every exec with its pipeline stage so dispatch telemetry
    # (and bench output) attributes round trips per stage
    from spark_rapids_tpu.plan.optimizer import cut_stages

    cut_stages(exec_)
    return exec_


def _enable_in_program_exchanges(exec_: TpuExec, conf) -> None:
    """SPMD whole-stage exchange: flip every eligible hash
    ShuffleExchangeExec surviving in the physical plan to the compiled
    all_to_all map side (execs/exchange._materialize_in_program). The
    mesh exec lowering already absorbs most shuffles into chained
    Mesh*Execs; this walk catches the rest — explicit repartitions,
    shuffled-join inputs, partial/final aggregate boundaries. Safe to
    flip one side of a co-partitioned pair: the in-program step
    reproduces the host partition kernel's pid exactly. Every "no" on a
    mesh-enabled session lands in parallel/spmd.py's fallback telemetry
    with a reason."""
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.execs.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.parallel import spmd

    if conf is None or not conf.get(cfg.MESH_ENABLED):
        return
    skew = spmd.adaptive_skew_spec(conf)
    seen: set = set()

    def walk(e) -> None:
        if id(e) in seen:
            return
        seen.add(id(e))
        if isinstance(e, ShuffleExchangeExec) and not e.in_program \
                and e._blocks is None \
                and e.partitioning[0] != "single":
            kind = e.partitioning[0]
            if kind != "hash":
                mesh = spmd.in_program_mesh(
                    conf, "exchange", keyed=False,
                    reason_if_unkeyed=f"{kind} partitioning routes "
                    "host-side (sampled bounds / row order)")
            elif any(t is dt.STRING for t in e.schema.types):
                mesh = spmd.in_program_mesh(
                    conf, "exchange", keyed=False,
                    reason_if_unkeyed="string columns need host-side "
                    "dictionary unification")
            else:
                mesh = spmd.in_program_mesh(conf, "exchange")
            if mesh is not None:
                e.enable_in_program(mesh, skew=skew)
        for c in e.children:
            walk(c)
        for bx in getattr(e, "builds", ()) or ():
            walk(bx)

    walk(exec_)


def _assert_on_tpu(exec_: TpuExec, allowed: set):
    if isinstance(exec_, basic.CpuFallbackExec):
        name = type(exec_.plan_node).__name__
        if name not in allowed:
            raise PlanOnCpuError(
                f"{name} fell back to CPU: {exec_.reasons}")
    for c in exec_.children:
        _assert_on_tpu(c, allowed)


def explain(plan: pn.PlanNode, conf: Optional[RapidsConf] = None) -> str:
    conf = conf or RapidsConf()
    meta = NodeMeta(plan, conf)
    meta.tag_for_tpu()
    return meta.explain()


# all module-level knobs (including every import-time op flag above)
# are registered by this point; anything added later is a per-node
# apply-time flag that docs generation can never see
cfg.snapshot_docs_registry()
