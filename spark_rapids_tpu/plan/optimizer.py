"""Plan-level optimizer rules applied before TpuOverrides.

The reference inherits Catalyst's optimized plans; standalone, this
engine needs the handful of structural rules with direct dispatch-count
impact (each collapsed node is one fewer jitted executable per batch —
at ~100 ms tunnel overhead per dispatch these rules are worth more here
than on a local GPU):

- CollapseProject: Project(Project(x)) -> one Project with the outer
  expressions rewritten over the inner ones (Catalyst's CollapseProject)
- CombineFilters: Filter(Filter(x)) -> one conjunctive Filter
- CollapseFilterProject: Filter(Project(x)) where the condition only
  references projected columns -> Project(Filter'(x)) is NOT generally
  safe (the projection may rename/compute); instead the condition is
  rewritten through the projection so the pair becomes
  Project(..) over Filter(rewritten) — pushing the filter below the
  projection lets scans prune earlier (PushDownPredicate subset for
  deterministic projections).
"""
from __future__ import annotations

from typing import List, Optional

from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Expression)
from spark_rapids_tpu.plan import nodes as pn


def _substitute(e: Expression, inner: List[Expression]) -> Expression:
    """Rewrite ``e``'s bound references as the inner projection's
    expressions (unwrapping aliases)."""
    def fn(node: Expression) -> Expression:
        if isinstance(node, BoundReference):
            repl = inner[node.ordinal]
            while isinstance(repl, Alias):
                repl = repl.children[0]
            return repl
        return node
    return e.transform(fn)


def _all_deterministic(exprs) -> bool:
    return all(e.deterministic for e in exprs)


def _reference_counts(exprs: List[Expression], width: int) -> List[int]:
    counts = [0] * width
    for e in exprs:
        for node in e.collect(lambda n: isinstance(n, BoundReference)):
            counts[node.ordinal] += 1
    return counts


def collapse_project(node: pn.PlanNode) -> pn.PlanNode:
    """Bottom-up single pass collapsing Project/Filter chains."""
    new_children = [collapse_project(c) for c in node.children]
    node = node.with_children(new_children) if node.children else node

    if isinstance(node, pn.ProjectNode) and \
            isinstance(node.children[0], pn.ProjectNode):
        inner: pn.ProjectNode = node.children[0]
        if _all_deterministic(inner.exprs):
            # avoid exploding duplicated non-trivial inner expressions:
            # collapse only when every inner expr used more than once is
            # a bare reference (Catalyst applies a similar cost guard)
            counts = _reference_counts(node.exprs, len(inner.exprs))
            cheap = all(
                c <= 1 or isinstance(
                    inner.exprs[i].children[0]
                    if isinstance(inner.exprs[i], Alias)
                    else inner.exprs[i], BoundReference)
                for i, c in enumerate(counts))
            if cheap:
                exprs = [_substitute(e, inner.exprs)
                         for e in node.exprs]
                return collapse_project(pn.ProjectNode(
                    exprs, inner.children[0], names=list(node.names)))

    if isinstance(node, pn.FilterNode) and \
            isinstance(node.children[0], pn.FilterNode):
        from spark_rapids_tpu.expressions import predicates as pr

        inner_f: pn.FilterNode = node.children[0]
        return collapse_project(pn.FilterNode(
            pr.And(inner_f.condition, node.condition),
            inner_f.children[0]))

    if isinstance(node, pn.FilterNode) and \
            isinstance(node.children[0], pn.ProjectNode):
        proj: pn.ProjectNode = node.children[0]
        if _all_deterministic(proj.exprs) and \
                _all_deterministic([node.condition]):
            pushed = _substitute(node.condition, proj.exprs)
            return collapse_project(pn.ProjectNode(
                list(proj.exprs),
                pn.FilterNode(pushed, proj.children[0]),
                names=list(proj.names)))

    return node


def rewrite_distinct_aggregates(node: pn.PlanNode) -> pn.PlanNode:
    """count/sum(DISTINCT x) -> dedup-then-aggregate: an inner group-by
    over (keys..., x) removes duplicates, then the outer aggregate runs
    the plain (non-distinct) function. This is the planner-level role of
    the reference's distinct handling (aggregate.scala:56-130).

    Mixed distinct + plain aggregates also rewrite when every plain
    aggregate is decomposable (Sum/Count/Min/Max): the inner group-by
    computes the plain aggregate per (keys, x) sub-group and the outer
    re-merges (Count -> Sum of counts; Sum/Min/Max self-merge) — the
    two-phase expand Spark plans for one distinct column. Only
    multi-distinct (different inputs) still falls back, as in the
    reference."""
    from spark_rapids_tpu.expressions import aggregates as aggfn

    new_children = [rewrite_distinct_aggregates(c)
                    for c in node.children]
    node = node.with_children(new_children) if node.children else node

    if not isinstance(node, pn.AggregateNode) or node.mode != "complete":
        return node
    dist = [a for a in node.aggs if getattr(a.fn, "distinct", False)]
    plain = [a for a in node.aggs if not getattr(a.fn, "distinct", False)]
    if not dist:
        return node
    if not all(isinstance(a.fn, (aggfn.Count, aggfn.Sum))
               for a in dist):
        return node  # (Average has no distinct form to rewrite)
    if not all(isinstance(a.fn, (aggfn.Count, aggfn.Sum, aggfn.Min,
                                 aggfn.Max, aggfn.Average))
               for a in plain):
        return node  # non-decomposable plain aggregate alongside
    # (ungrouped plain Counts merge via Sum whose empty-input default is
    # NULL, not Count's 0 — the final projection coalesces them back)
    inputs = [a.fn.children[0] if a.fn.children else None
              for a in dist]
    if any(i is None for i in inputs):
        return node
    first_key = inputs[0].tree_key()
    if first_key is None or any(i.tree_key() != first_key
                                for i in inputs[1:]):
        return node  # multi-distinct: fall back like the reference

    nkeys = len(node.grouping)
    inner_aggs = []
    inner_ords = {}  # id(plain call) -> inner agg ordinals
    for a in plain:
        fn = a.fn
        i0 = len(inner_aggs)
        if isinstance(fn, aggfn.Average):
            # avg is not avg-of-avgs decomposable: split into sum+count
            # partials, re-divided by a final projection
            inner_aggs.append(pn.AggCall(aggfn.Sum(fn.children[0]),
                                         f"_p{i0}"))
            inner_aggs.append(pn.AggCall(aggfn.Count(fn.children[0]),
                                         f"_p{i0 + 1}"))
            inner_ords[id(a)] = [i0, i0 + 1]
        else:
            clone = type(fn)(*fn.children) if fn.children else type(fn)()
            inner_aggs.append(pn.AggCall(clone, f"_p{i0}"))
            inner_ords[id(a)] = [i0]
    inner = pn.AggregateNode(
        list(node.grouping) + [inputs[0]], inner_aggs, node.children[0],
        grouping_names=list(node.grouping_names) + ["__distinct"])
    x = BoundReference(nkeys, inputs[0].dtype)
    outer_aggs = []
    out_spec = []  # per original agg: ("ref", j) | ("div", j1, j2)
    for a in node.aggs:
        if getattr(a.fn, "distinct", False):
            out_spec.append(("ref", len(outer_aggs)))
            outer_aggs.append(pn.AggCall(type(a.fn)(x), a.name))
            continue
        ords = inner_ords[id(a)]
        if isinstance(a.fn, aggfn.Average):
            j1, j2 = len(outer_aggs), len(outer_aggs) + 1
            for o in ords:
                ref = BoundReference(nkeys + 1 + o,
                                     inner_aggs[o].fn.dtype)
                outer_aggs.append(pn.AggCall(aggfn.Sum(ref),
                                             f"{a.name}_{o}"))
            out_spec.append(("div", j1, j2))
        else:
            o = ords[0]
            ref = BoundReference(nkeys + 1 + o,
                                 inner_aggs[o].fn.dtype)
            merge = aggfn.Sum if isinstance(a.fn, (aggfn.Count,
                                                   aggfn.Sum)) else \
                type(a.fn)
            kind = "coalesce0" if (not node.grouping and
                                   isinstance(a.fn, aggfn.Count)) \
                else "ref"
            out_spec.append((kind, len(outer_aggs)))
            outer_aggs.append(pn.AggCall(merge(ref), a.name))
    outer_keys = [BoundReference(i, e.dtype)
                  for i, e in enumerate(node.grouping)]
    out = pn.AggregateNode(outer_keys, outer_aggs, inner,
                           grouping_names=list(node.grouping_names))
    if all(k == "ref" for k, *_ in out_spec):
        return out
    from spark_rapids_tpu.expressions.arithmetic import Divide

    schema = out.output_schema()
    exprs = [Alias(BoundReference(i, schema.types[i]), schema.names[i])
             for i in range(nkeys)]
    names = list(schema.names[:nkeys])
    for spec, a in zip(out_spec, node.aggs):
        if spec[0] == "ref":
            j = nkeys + spec[1]
            exprs.append(Alias(BoundReference(j, schema.types[j]),
                               a.name))
        elif spec[0] == "coalesce0":
            from spark_rapids_tpu.expressions import conditional as cd_
            from spark_rapids_tpu.expressions.base import Literal
            from spark_rapids_tpu.columnar import dtypes as dt_

            j = nkeys + spec[1]
            exprs.append(Alias(cd_.Coalesce(
                [BoundReference(j, schema.types[j]),
                 Literal(0, dt_.INT64)]), a.name))
        else:
            _, j1, j2 = spec
            exprs.append(Alias(
                Divide(BoundReference(nkeys + j1,
                                      schema.types[nkeys + j1]),
                       BoundReference(nkeys + j2,
                                      schema.types[nkeys + j2])),
                a.name))
        names.append(a.name)
    return pn.ProjectNode(exprs, out, names)


def optimize(plan: pn.PlanNode) -> pn.PlanNode:
    return rewrite_distinct_aggregates(collapse_project(plan))
