"""Plan-level optimizer rules applied before TpuOverrides.

The reference inherits Catalyst's optimized plans; standalone, this
engine needs the handful of structural rules with direct dispatch-count
impact (each collapsed node is one fewer jitted executable per batch —
at ~100 ms tunnel overhead per dispatch these rules are worth more here
than on a local GPU):

- CollapseProject: Project(Project(x)) -> one Project with the outer
  expressions rewritten over the inner ones (Catalyst's CollapseProject)
- CombineFilters: Filter(Filter(x)) -> one conjunctive Filter
- CollapseFilterProject: Filter(Project(x)) where the condition only
  references projected columns -> Project(Filter'(x)) is NOT generally
  safe (the projection may rename/compute); instead the condition is
  rewritten through the projection so the pair becomes
  Project(..) over Filter(rewritten) — pushing the filter below the
  projection lets scans prune earlier (PushDownPredicate subset for
  deterministic projections).
"""
from __future__ import annotations

from typing import List, Optional

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Expression)
from spark_rapids_tpu.plan import nodes as pn


def _substitute(e: Expression, inner: List[Expression]) -> Expression:
    """Rewrite ``e``'s bound references as the inner projection's
    expressions (unwrapping aliases)."""
    def fn(node: Expression) -> Expression:
        if isinstance(node, BoundReference):
            repl = inner[node.ordinal]
            while isinstance(repl, Alias):
                repl = repl.children[0]
            return repl
        return node
    return e.transform(fn)


def _all_deterministic(exprs) -> bool:
    return all(e.deterministic for e in exprs)


def _reference_counts(exprs: List[Expression], width: int) -> List[int]:
    counts = [0] * width
    for e in exprs:
        for node in e.collect(lambda n: isinstance(n, BoundReference)):
            counts[node.ordinal] += 1
    return counts


def collapse_project(node: pn.PlanNode, _memo=None) -> pn.PlanNode:
    """Bottom-up single pass collapsing Project/Filter chains.

    ``_memo`` (id -> (node, result), the node ref pins the id) keeps
    SHARED subtrees shared: CTE references reuse one plan node, and a
    rebuild that copied it per reference would make the exec layer
    materialize the common stage once per consumer."""
    if _memo is None:
        _memo = {}
    hit = _memo.get(id(node))
    if hit is not None:
        return hit[1]
    result = _collapse_project_one(node, _memo)
    _memo[id(node)] = (node, result)
    return result


def _collapse_project_one(node: pn.PlanNode, _memo) -> pn.PlanNode:
    new_children = [collapse_project(c, _memo) for c in node.children]
    node = node.with_children(new_children) if node.children else node

    if isinstance(node, pn.ProjectNode) and \
            isinstance(node.children[0], pn.ProjectNode):
        inner: pn.ProjectNode = node.children[0]
        if _all_deterministic(inner.exprs):
            # avoid exploding duplicated non-trivial inner expressions:
            # collapse only when every inner expr used more than once is
            # a bare reference (Catalyst applies a similar cost guard)
            counts = _reference_counts(node.exprs, len(inner.exprs))
            cheap = all(
                c <= 1 or isinstance(
                    inner.exprs[i].children[0]
                    if isinstance(inner.exprs[i], Alias)
                    else inner.exprs[i], BoundReference)
                for i, c in enumerate(counts))
            if cheap:
                exprs = [_substitute(e, inner.exprs)
                         for e in node.exprs]
                return collapse_project(pn.ProjectNode(
                    exprs, inner.children[0], names=list(node.names)),
                    _memo)

    if isinstance(node, pn.FilterNode) and \
            isinstance(node.children[0], pn.FilterNode):
        from spark_rapids_tpu.expressions import predicates as pr

        inner_f: pn.FilterNode = node.children[0]
        return collapse_project(pn.FilterNode(
            pr.And(inner_f.condition, node.condition),
            inner_f.children[0]), _memo)

    if isinstance(node, pn.FilterNode) and \
            isinstance(node.children[0], pn.ProjectNode):
        proj: pn.ProjectNode = node.children[0]
        if _all_deterministic(proj.exprs) and \
                _all_deterministic([node.condition]):
            pushed = _substitute(node.condition, proj.exprs)
            return collapse_project(pn.ProjectNode(
                list(proj.exprs),
                pn.FilterNode(pushed, proj.children[0]),
                names=list(proj.names)), _memo)

    return node


def rewrite_distinct_aggregates(node: pn.PlanNode,
                                _memo=None) -> pn.PlanNode:
    """count/sum(DISTINCT x) -> dedup-then-aggregate: an inner group-by
    over (keys..., x) removes duplicates, then the outer aggregate runs
    the plain (non-distinct) function. This is the planner-level role of
    the reference's distinct handling (aggregate.scala:56-130).

    Mixed distinct + plain aggregates also rewrite when every plain
    aggregate is decomposable (Sum/Count/Min/Max): the inner group-by
    computes the plain aggregate per (keys, x) sub-group and the outer
    re-merges (Count -> Sum of counts; Sum/Min/Max self-merge) — the
    two-phase expand Spark plans for one distinct column. Only
    multi-distinct (different inputs) still falls back, as in the
    reference."""
    from spark_rapids_tpu.expressions import aggregates as aggfn

    if _memo is None:
        _memo = {}
    hit = _memo.get(id(node))
    if hit is not None:
        return hit[1]
    orig = node
    new_children = [rewrite_distinct_aggregates(c, _memo)
                    for c in node.children]
    if node.children and any(n is not o for n, o in
                             zip(new_children, node.children)):
        node = node.with_children(new_children)
    result = _rewrite_distinct_one(node)
    _memo[id(orig)] = (orig, result)
    return result


def _rewrite_distinct_one(node: pn.PlanNode) -> pn.PlanNode:
    from spark_rapids_tpu.expressions import aggregates as aggfn

    if not isinstance(node, pn.AggregateNode) or node.mode != "complete":
        return node
    dist = [a for a in node.aggs if getattr(a.fn, "distinct", False)]
    plain = [a for a in node.aggs if not getattr(a.fn, "distinct", False)]
    if not dist:
        return node
    if not all(isinstance(a.fn, (aggfn.Count, aggfn.Sum))
               for a in dist):
        return node  # (Average has no distinct form to rewrite)
    if not all(isinstance(a.fn, (aggfn.Count, aggfn.Sum, aggfn.Min,
                                 aggfn.Max, aggfn.Average))
               for a in plain):
        return node  # non-decomposable plain aggregate alongside
    # (ungrouped plain Counts merge via Sum whose empty-input default is
    # NULL, not Count's 0 — the final projection coalesces them back)
    inputs = [a.fn.children[0] if a.fn.children else None
              for a in dist]
    if any(i is None for i in inputs):
        return node
    first_key = inputs[0].tree_key()
    if first_key is None or any(i.tree_key() != first_key
                                for i in inputs[1:]):
        return node  # multi-distinct: fall back like the reference

    nkeys = len(node.grouping)
    inner_aggs = []
    inner_ords = {}  # id(plain call) -> inner agg ordinals
    for a in plain:
        fn = a.fn
        i0 = len(inner_aggs)
        if isinstance(fn, aggfn.Average):
            # avg is not avg-of-avgs decomposable: split into sum+count
            # partials, re-divided by a final projection
            inner_aggs.append(pn.AggCall(aggfn.Sum(fn.children[0]),
                                         f"_p{i0}"))
            inner_aggs.append(pn.AggCall(aggfn.Count(fn.children[0]),
                                         f"_p{i0 + 1}"))
            inner_ords[id(a)] = [i0, i0 + 1]
        else:
            clone = type(fn)(*fn.children) if fn.children else type(fn)()
            inner_aggs.append(pn.AggCall(clone, f"_p{i0}"))
            inner_ords[id(a)] = [i0]
    inner = pn.AggregateNode(
        list(node.grouping) + [inputs[0]], inner_aggs, node.children[0],
        grouping_names=list(node.grouping_names) + ["__distinct"])
    x = BoundReference(nkeys, inputs[0].dtype)
    outer_aggs = []
    out_spec = []  # per original agg: ("ref", j) | ("div", j1, j2)
    for a in node.aggs:
        if getattr(a.fn, "distinct", False):
            out_spec.append(("ref", len(outer_aggs)))
            outer_aggs.append(pn.AggCall(type(a.fn)(x), a.name))
            continue
        ords = inner_ords[id(a)]
        if isinstance(a.fn, aggfn.Average):
            j1, j2 = len(outer_aggs), len(outer_aggs) + 1
            for o in ords:
                ref = BoundReference(nkeys + 1 + o,
                                     inner_aggs[o].fn.dtype)
                outer_aggs.append(pn.AggCall(aggfn.Sum(ref),
                                             f"{a.name}_{o}"))
            out_spec.append(("div", j1, j2))
        else:
            o = ords[0]
            ref = BoundReference(nkeys + 1 + o,
                                 inner_aggs[o].fn.dtype)
            merge = aggfn.Sum if isinstance(a.fn, (aggfn.Count,
                                                   aggfn.Sum)) else \
                type(a.fn)
            kind = "coalesce0" if (not node.grouping and
                                   isinstance(a.fn, aggfn.Count)) \
                else "ref"
            out_spec.append((kind, len(outer_aggs)))
            outer_aggs.append(pn.AggCall(merge(ref), a.name))
    outer_keys = [BoundReference(i, e.dtype)
                  for i, e in enumerate(node.grouping)]
    out = pn.AggregateNode(outer_keys, outer_aggs, inner,
                           grouping_names=list(node.grouping_names))
    if all(k == "ref" for k, *_ in out_spec):
        return out
    from spark_rapids_tpu.expressions.arithmetic import Divide

    schema = out.output_schema()
    exprs = [Alias(BoundReference(i, schema.types[i]), schema.names[i])
             for i in range(nkeys)]
    names = list(schema.names[:nkeys])
    for spec, a in zip(out_spec, node.aggs):
        if spec[0] == "ref":
            j = nkeys + spec[1]
            exprs.append(Alias(BoundReference(j, schema.types[j]),
                               a.name))
        elif spec[0] == "coalesce0":
            from spark_rapids_tpu.expressions import conditional as cd_
            from spark_rapids_tpu.expressions.base import Literal
            from spark_rapids_tpu.columnar import dtypes as dt_

            j = nkeys + spec[1]
            exprs.append(Alias(cd_.Coalesce(
                [BoundReference(j, schema.types[j]),
                 Literal(0, dt_.INT64)]), a.name))
        else:
            _, j1, j2 = spec
            exprs.append(Alias(
                Divide(BoundReference(nkeys + j1,
                                      schema.types[nkeys + j1]),
                       BoundReference(nkeys + j2,
                                      schema.types[nkeys + j2])),
                a.name))
        names.append(a.name)
    return pn.ProjectNode(exprs, out, names)


# ---------------------------------------------------------------------------
# Filter pushdown through joins (PushPredicateThroughJoin subset): the
# SQL planner distributes WHERE conjuncts for the implicit-join form,
# but explicit JOIN ... ON and DataFrame .join().filter() leave the
# whole WHERE above the join — severing scan pruning, inflating join
# inputs, and breaking sharded mesh hand-off chains.
# ---------------------------------------------------------------------------


def _expr_conjuncts(e: Expression) -> List[Expression]:
    from spark_rapids_tpu.expressions.predicates import And

    if isinstance(e, And):
        return _expr_conjuncts(e.children[0]) + \
            _expr_conjuncts(e.children[1])
    return [e]


def _and_all(exprs: List[Expression]) -> Expression:
    from spark_rapids_tpu.expressions.predicates import And

    out = exprs[0]
    for e in exprs[1:]:
        out = And(out, e)
    return out


def _shift_refs(e: Expression, delta: int) -> Expression:
    def fn(n):
        if isinstance(n, BoundReference):
            return BoundReference(n.ordinal + delta, n.dtype)
        return n
    return e.transform(fn)


def push_filters_below_joins(node: pn.PlanNode,
                             _memo=None) -> pn.PlanNode:
    if _memo is None:
        _memo = {}
    hit = _memo.get(id(node))
    if hit is not None:
        return hit[1]
    orig = node
    result = _push_filters_one(node, _memo)
    _memo[id(orig)] = (orig, result)
    return result


def _push_filters_one(node: pn.PlanNode, _memo) -> pn.PlanNode:
    if node.children:
        new_children = [push_filters_below_joins(c, _memo)
                        for c in node.children]
        if any(n is not o for n, o in zip(new_children, node.children)):
            node = node.with_children(new_children)
    if not (isinstance(node, pn.FilterNode) and
            isinstance(node.children[0], pn.JoinNode)):
        return node
    join: pn.JoinNode = node.children[0]
    kind = join.kind
    lw = len(join.children[0].output_schema())
    # which sides may see a pre-join filter without changing results:
    # a LEFT join's right side must NOT pre-filter (a filtered-out
    # match becomes a null-extended row instead of a dropped one);
    # FULL pushes nothing; semi/anti output only left columns
    push_left = kind in ("inner", "cross", "left", "left_semi",
                         "left_anti")
    push_right = kind in ("inner", "cross", "right")
    keep: List[Expression] = []
    lpush: List[Expression] = []
    rpush: List[Expression] = []
    for c in _expr_conjuncts(node.condition):
        ords = [r.ordinal for r in
                c.collect(lambda n: isinstance(n, BoundReference))]
        if not c.deterministic or not ords:
            keep.append(c)
        elif max(ords) < lw and push_left:
            lpush.append(c)
        elif min(ords) >= lw and push_right:
            rpush.append(_shift_refs(c, -lw))
        else:
            keep.append(c)
    if not lpush and not rpush:
        return node
    left, right = join.children
    if lpush:
        left = push_filters_below_joins(
            pn.FilterNode(_and_all(lpush), left), _memo)
    if rpush:
        right = push_filters_below_joins(
            pn.FilterNode(_and_all(rpush), right), _memo)
    out: pn.PlanNode = pn.JoinNode(kind, left, right, join.left_keys,
                                   join.right_keys,
                                   condition=join.condition)
    if keep:
        out = pn.FilterNode(_and_all(keep), out)
    return out


# ---------------------------------------------------------------------------
# Greedy join reordering (r3 verdict #6). The reference inherits join
# order from Spark's cost-based optimizer upstream; standalone, this
# planner owns the job. Scan-statistics row counts (parquet footer
# metadata / host array lengths) drive a classic greedy heuristic:
# start from the LARGEST relation (the fact table stays the stream
# side) and repeatedly join the smallest connected relation — small
# dimensions become early, cheap build sides and intermediate results
# shrink as early as possible (q64's 17-table chain no longer depends
# on the hand-written query order).
# ---------------------------------------------------------------------------

_FILTER_SELECTIVITY = 0.3


def estimate_key_ndv(node: pn.PlanNode, ordinal: int) -> Optional[int]:
    """Distinct-value estimate for a join key column, derived from file
    footer statistics where the column traces back to a scan: an
    integral key with host-known (lo, hi) bounds has NDV <= hi-lo+1,
    capped by the relation's row estimate. Replaces part of the fixed
    heuristic cardinality model (round-4 weak #5) with data-driven
    numbers when footers provide them."""
    if isinstance(node, pn.FilterNode):
        return estimate_key_ndv(node.children[0], ordinal)
    if isinstance(node, pn.ProjectNode):
        e = node.exprs[ordinal]
        while isinstance(e, Alias):
            e = e.children[0]
        if isinstance(e, BoundReference):
            return estimate_key_ndv(node.children[0], e.ordinal)
        return None
    if isinstance(node, pn.ScanNode):
        src = node.source
        try:
            schema = src.schema()
            t = schema.types[ordinal]
            if not (t.is_integral or t in (dt.DATE, dt.TIMESTAMP)):
                return None
            name = schema.names[ordinal]
            splits = getattr(src, "splits", None)
            if splits is None:
                return None
            lo = hi = None
            for i in range(len(splits())):
                s = src.split_stats(i)
                if not s or name not in s:
                    return None
                slo, shi = s[name]
                lo = slo if lo is None else min(lo, slo)
                hi = shi if hi is None else max(hi, shi)
            if lo is None:
                return None
            span = int(hi) - int(lo) + 1
            rows = src.estimated_row_count()
            return max(min(span, rows) if rows is not None else span, 1)
        except Exception:
            return None
    return None


def estimate_rows(node: pn.PlanNode) -> Optional[int]:
    """Plan-time cardinality estimate; None = unknown (no reordering)."""
    est_fn = getattr(node, "plan_row_estimate", None)
    if est_fn is not None:
        # nodes that carry their own estimate (a cached-fragment leaf
        # knows the cardinality of the subtree it replaced) — without
        # this, a grafted serve leaf would charge default_rows against
        # admission for data that is already materialized
        return est_fn()
    if isinstance(node, pn.ScanNode):
        est = node.source.estimated_row_count()
        if est is not None and isinstance(node.source, pn.DataSource) \
                and getattr(node.source, "filters", None):
            est = max(int(est * _FILTER_SELECTIVITY), 1)
        return est
    if isinstance(node, pn.FilterNode):
        c = estimate_rows(node.children[0])
        return None if c is None else max(int(c * _FILTER_SELECTIVITY), 1)
    if isinstance(node, pn.JoinNode):
        le = estimate_rows(node.children[0])
        if node.kind in ("left_semi", "left_anti"):
            return le
        re = estimate_rows(node.children[1])
        if le is None or re is None:
            return None
        if node.kind == "inner":
            # |A join B| = |A|*|B| / ndv(k), FLOORED at max(le, re):
            # span-based NDV is only an upper bound on true NDV (sparse
            # key domains like lineitem.l_orderkey can make span ~ rows
            # while true NDV is rows/4), so an unfloored estimate would
            # systematically UNDER-estimate and mislead the broadcast
            # threshold. With the floor, the refinement can only detect
            # many-to-many EXPANSION (est above both sides) — the
            # direction span stats CAN bound soundly.
            if node.left_keys:
                cands = []
                for side, ord_ in (
                        (node.children[0], node.left_keys[0]),
                        (node.children[1], node.right_keys[0])):
                    ndv = estimate_key_ndv(side, ord_)
                    if ndv is not None:
                        cands.append(ndv)
                if cands:
                    est = (le * re) // max(max(cands), 1)
                    return max(min(est, le * re), max(le, re), 1)
            return max(le, re)  # FK->PK: output tracks the fact side
        return le if node.kind == "left" else le + re
    if isinstance(node, pn.AggregateNode):
        c = estimate_rows(node.children[0])
        # grouped outputs shrink; keep a conservative fraction
        return None if c is None else max(c // 3, 1)
    if isinstance(node, pn.UnionNode):
        parts = [estimate_rows(c) for c in node.children]
        return None if any(p is None for p in parts) else sum(parts)
    if isinstance(node, pn.LimitNode):
        c = estimate_rows(node.children[0])
        return node.n if c is None else min(node.n, c)
    if len(node.children) == 1:  # project/sort/window/exchange/...
        return estimate_rows(node.children[0])
    return None


def _flatten_inner_joins(node: pn.PlanNode):
    """Maximal chain of condition-free inner equi-joins.
    Returns (rels, colmap, edges): base relations, a map from this
    subtree's output ordinal to (rel_index, rel_ordinal), and key
    equalities as ((ri, ci), (rj, cj)) pairs."""
    if isinstance(node, pn.JoinNode) and node.kind == "inner" and \
            node.condition is None and node.left_keys:
        lrels, lmap, ledges = _flatten_inner_joins(node.children[0])
        rrels, rmap, redges = _flatten_inner_joins(node.children[1])
        off = len(lrels)
        rmap = [(ri + off, ci) for ri, ci in rmap]
        redges = [((a + off, b), (c + off, d))
                  for (a, b), (c, d) in redges]
        edges = ledges + redges
        for lk, rk in zip(node.left_keys, node.right_keys):
            edges.append((lmap[lk], rmap[rk]))
        return lrels + rrels, lmap + rmap, edges
    width = len(node.output_schema())
    return [node], [(0, i) for i in range(width)], []


def _greedy_order(n: int, edges, est) -> Optional[List[int]]:
    adj = {i: set() for i in range(n)}
    for (ri, _), (rj, _) in edges:
        adj[ri].add(rj)
        adj[rj].add(ri)
    start = max(range(n), key=lambda i: est[i])
    order, placed = [start], {start}
    while len(order) < n:
        cand = [i for i in range(n)
                if i not in placed and adj[i] & placed]
        if not cand:
            return None  # disconnected graph: keep the written order
        nxt = min(cand, key=lambda i: est[i])
        order.append(nxt)
        placed.add(nxt)
    return order


def reorder_joins(node: pn.PlanNode, _memo=None) -> pn.PlanNode:
    if _memo is None:
        _memo = {}
    hit = _memo.get(id(node))
    if hit is not None:
        return hit[1]
    orig = node
    result = _reorder_joins_one(node, _memo)
    _memo[id(orig)] = (orig, result)
    return result


def _reorder_joins_one(node: pn.PlanNode, _memo) -> pn.PlanNode:
    # TOP-DOWN: the chain must flatten before any sub-chain wraps
    # itself in a restore-projection (which would hide it)
    if not (isinstance(node, pn.JoinNode) and node.kind == "inner" and
            node.condition is None and node.left_keys):
        if node.children:
            new_children = [reorder_joins(c, _memo)
                            for c in node.children]
            if any(n is not o for n, o in
                   zip(new_children, node.children)):
                return node.with_children(new_children)
        return node

    def keep_written_order():
        new_children = [reorder_joins(c, _memo)
                        for c in node.children]
        if any(n is not o for n, o in zip(new_children, node.children)):
            return node.with_children(new_children)
        return node

    rels, colmap, edges = _flatten_inner_joins(node)
    if len(rels) < 3:
        return keep_written_order()
    est = [estimate_rows(r) for r in rels]
    if any(e is None for e in est):
        return keep_written_order()
    order = _greedy_order(len(rels), edges, est)
    if order is None or order == list(range(len(rels))):
        return keep_written_order()
    rels = [reorder_joins(r, _memo) for r in rels]  # recurse below
    # rebuild left-deep in greedy order; when a relation joins, every
    # key equality linking it to already-placed relations applies (so
    # no edge constraint is ever dropped — an edge activates when its
    # later-placed endpoint arrives)
    offsets = {order[0]: 0}
    cur = rels[order[0]]
    width = len(cur.output_schema())
    placed = {order[0]}
    for idx in order[1:]:
        r = rels[idx]
        pairs = []
        for (ri, ci), (rj, cj) in edges:
            if ri in placed and rj == idx:
                pairs.append((offsets[ri] + ci, cj))
            elif rj in placed and ri == idx:
                pairs.append((offsets[rj] + cj, ci))
        pairs = list(dict.fromkeys(pairs))
        cur = pn.JoinNode("inner", cur, r,
                          [p[0] for p in pairs], [p[1] for p in pairs])
        offsets[idx] = width
        width += len(r.output_schema())
        placed.add(idx)
    # a projection restores the original column order on top
    out_schema = node.output_schema()
    exprs: List[Expression] = []
    for ri, rel in enumerate(rels):
        rtypes = rel.output_schema().types
        for ci in range(len(rtypes)):
            exprs.append(Alias(
                BoundReference(offsets[ri] + ci, rtypes[ci]),
                out_schema.names[len(exprs)]))
    return pn.ProjectNode(exprs, cur, names=list(out_schema.names))


def optimize(plan: pn.PlanNode) -> pn.PlanNode:
    plan = collapse_project(plan)
    # collapse first (filters drop through projections), then push
    # through joins, then collapse again (a pushed filter may meet
    # another filter/projection), then push the combined form once more
    plan = push_filters_below_joins(plan)
    plan = collapse_project(plan)
    plan = push_filters_below_joins(plan)
    plan = reorder_joins(plan)
    # the reorder's restore-projection may now collapse with outer ones
    plan = collapse_project(plan)
    return rewrite_distinct_aggregates(plan)


# ---------------------------------------------------------------------------
# Peak-footprint model (round-6, service admission): a static estimate
# of how many device bytes a query may pin at once, from the same
# footer-stat cardinalities the join reorder uses. The admission
# controller charges this against the HBM budget before letting a query
# onto the device (GpuSemaphore bounds WHO may enter; this bounds HOW
# MUCH the admitted set is expected to ask for).
# ---------------------------------------------------------------------------


def _row_width(node: pn.PlanNode) -> int:
    """Estimated device bytes per row of a node's output (kernel lane
    width + validity byte; strings are dictionary codes on device)."""
    schema = node.output_schema()
    return sum(t.byte_width + 1 for t in schema.types) or 1


def estimate_footprint_bytes(plan: pn.PlanNode,
                             default_rows: int = 1 << 20,
                             runtime_rows=None) -> int:
    """Estimated peak device bytes of executing ``plan``: the widest
    single operator's working set (its output plus every input it holds
    live) plus the broadcast/build sides and materialized exchanges that
    stay resident across the pipeline. Nodes without a cardinality
    estimate assume ``default_rows``. Deliberately coarse and
    conservative — admission needs an upper-bound-shaped number, not a
    point estimate; the spill catalog is the real enforcement.

    ``runtime_rows`` (AQE replan rule 3b: node -> rows | None) answers
    for nodes the STATIC estimator cannot — measured cardinalities from
    earlier runs of the same plan shape (execs.adaptive's registry) —
    so admission tightens as the workload repeats."""
    from spark_rapids_tpu.ops.buckets import bucket_capacity

    resident = 0  # exchange/aggregate materializations live across stages

    def bytes_of(node: pn.PlanNode) -> int:
        rows = estimate_rows(node)
        if rows is None and runtime_rows is not None:
            rows = runtime_rows(node)
        rows = max(rows if rows is not None else default_rows, 1)
        # BUCKETED, not raw: device columns are padded to the capacity
        # ladder (ops/buckets), so the bytes a node actually pins are
        # the bucket's, not the row count's — an estimate off by up to
        # a full growth factor would under-admit against real HBM
        return bucket_capacity(rows) * _row_width(node)

    def walk(node: pn.PlanNode, seen) -> int:
        """Peak transient bytes of the subtree rooted at node."""
        nonlocal resident
        if id(node) in seen:  # shared CTE subtree: one materialization
            return 0
        seen.add(id(node))
        own = bytes_of(node)
        if isinstance(node, (pn.JoinNode, pn.AggregateNode, pn.SortNode,
                             pn.ShuffleExchangeNode)):
            # materialization points hold their input batches staged
            # (spillable, but device-first) while producing output
            resident += own
        child_peaks = [walk(c, seen) for c in node.children]
        return own + max(child_peaks, default=0)

    peak = walk(plan, set())
    return peak + resident


# ---------------------------------------------------------------------------
# Plan-cost model (round-5): a static dispatch-count estimate over the
# PHYSICAL tree, so tests can assert optimizer decisions (join reorder,
# broadcast selection) never make a plan costlier than the written
# order — the plan-quality guard the semantics fuzz can't provide.
# Weights are the measured per-exec dispatch shapes from BASELINE.md's
# telemetry, not wall-clock claims.
# ---------------------------------------------------------------------------


def plan_cost(exec_) -> int:
    """Estimated dispatch count of a physical exec tree. Runs under
    planning_mode so adaptive/range partition-count queries never
    materialize anything."""
    from spark_rapids_tpu.execs import adaptive as adaptive_exec

    with adaptive_exec.planning_mode():
        return _cost(exec_)


def _own_cost(e) -> int:
    """Estimated dispatch count of ONE exec (excluding children)."""
    from spark_rapids_tpu.execs import basic, joins
    from spark_rapids_tpu.execs.adaptive import AdaptiveShuffleReaderExec
    from spark_rapids_tpu.execs.aggregate import HashAggregateExec
    from spark_rapids_tpu.execs.batching import CoalesceBatchesExec
    from spark_rapids_tpu.execs.exchange import (BroadcastExchangeExec,
                                                 ShuffleExchangeExec)
    from spark_rapids_tpu.execs.fused import (FusedAggregateExec,
                                              FusedChainExec)
    from spark_rapids_tpu.execs.sort import SortExec

    parts = max(getattr(e, "num_partitions", 1), 1)
    if type(e).__name__.startswith("Mesh"):
        # whole-stage SPMD exec: one compiled shard_map launch plus a
        # staging/gather hop, independent of partition count — the
        # point of folding the shuffle into the program
        return 2
    if isinstance(e, FusedAggregateExec):
        # chain + single-pass groupby per partition; the build prep is
        # inlined into the chain's first launch (in-program build), so
        # builds no longer add their own dispatches
        own = 2 * parts
    elif isinstance(e, FusedChainExec):
        own = 1 * parts
    elif isinstance(e, HashAggregateExec):
        own = 3 * parts
    elif isinstance(e, joins.HashJoinExec):
        own = 6 * parts  # probe/expand/emit chain + count sync
    elif isinstance(e, (joins.BroadcastNestedLoopJoinExec,
                        joins.CartesianProductExec)):
        # full pair-grid materialization: the guard must never score a
        # hash->nested-loop degradation as an improvement
        own = 50 * parts
    elif isinstance(e, AdaptiveShuffleReaderExec):
        own = 0  # a view over its exchange; the exchange carries cost
    elif isinstance(e, ShuffleExchangeExec):
        if getattr(e, "in_program", False):
            # staging gather + ONE all_to_all program + result gather,
            # regardless of batch or partition count
            own = 3
        else:
            own = 2 * max(e.children[0].num_partitions, 1) + parts
    elif isinstance(e, BroadcastExchangeExec):
        own = 2
    elif isinstance(e, basic.FilterExec):
        own = 2 * parts
    elif isinstance(e, (basic.ProjectExec, CoalesceBatchesExec)):
        own = 1 * parts
    elif isinstance(e, SortExec):
        own = 2 * parts
    elif isinstance(e, basic.ScanExec):
        own = 1 * parts
    else:
        own = 2 * parts  # unknown execs are not free
    return own


def _cost(e) -> int:
    return _own_cost(e) + sum(_cost(c) for c in e.children)


# ---------------------------------------------------------------------------
# Stage cutting (round-6): partition the PHYSICAL tree into pipeline
# stages — maximal regions whose per-batch dispatches the fusion pass
# coalesces toward one program — and label every exec with its stage so
# dispatch telemetry attributes round trips per stage. Stage breakers
# are the materialization points: exchanges (a broadcast/shuffle build
# runs to completion before its consumer), aggregates (the merge loop
# drains its input), and sorts (a global sort stages everything).
# ---------------------------------------------------------------------------


def _is_stage_breaker(e) -> bool:
    from spark_rapids_tpu.execs.aggregate import HashAggregateExec
    from spark_rapids_tpu.execs.exchange import (BroadcastExchangeExec,
                                                 ShuffleExchangeExec)
    from spark_rapids_tpu.execs.sort import SortExec

    if isinstance(e, ShuffleExchangeExec) and \
            getattr(e, "in_program", False):
        # the shuffle is a collective inside the enclosing stage's
        # program, not a materialization boundary: child and consumer
        # share one stage (whole-stage SPMD execution)
        return False
    return isinstance(e, (HashAggregateExec, ShuffleExchangeExec,
                          BroadcastExchangeExec, SortExec))


def cut_stages(root) -> List[dict]:
    """Assign ``_stage_label`` to every exec and return the stage list:
    [{stage, ops, est_dispatches, mesh_internal}] in discovery
    (top-down) order. ``mesh_internal`` marks stages whose shuffle is
    an in-program mesh collective rather than a host exchange. A
    stage starts at the root, below every breaker, and at every
    broadcast build subtree (reached via ``.builds`` on fused execs —
    those exchanges are not ``children``). ``est_dispatches`` is the
    static per-stage dispatch estimate from the plan-cost model, so
    bench output can show where a query's round-trip budget sits
    BEFORE running it."""
    from spark_rapids_tpu.execs import adaptive as adaptive_exec

    stages: List[dict] = []
    seen: set = set()

    def new_stage() -> dict:
        s = {"stage": f"stage{len(stages)}", "ops": [],
             "est_dispatches": 0, "mesh_internal": False}
        stages.append(s)
        return s

    def walk(node, stage) -> None:
        if id(node) in seen:
            return
        seen.add(id(node))
        if stage is None:
            stage = new_stage()
        node._stage_label = stage["stage"]
        stage["ops"].append(node.name)
        stage["est_dispatches"] += _own_cost(node)
        if node.name.startswith("Mesh") or \
                getattr(node, "in_program", False):
            # this stage's shuffle rides an in-program collective over
            # the mesh (no host exchange at its boundary)
            stage["mesh_internal"] = True
        breaker = _is_stage_breaker(node)
        for c in node.children:
            walk(c, None if breaker else stage)
        for bx in getattr(node, "builds", ()) or ():
            walk(bx, None)

    with adaptive_exec.planning_mode():
        walk(root, None)
    return stages
