"""Planning layer: declarative plan nodes, the meta-wrapper override tree
(tagging with reasons, per-op config gates, explain), and transition/coalesce
insertion — the TPU-native analogue of the reference's L5
(GpuOverrides.scala, RapidsMeta.scala, GpuTransitionOverrides.scala)."""
from spark_rapids_tpu.plan import nodes  # noqa: F401
