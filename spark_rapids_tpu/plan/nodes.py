"""Engine-neutral physical plan nodes.

The reference rewrites Spark's physical plans (SparkPlan). This framework is
standalone, so it defines its own plan-node vocabulary, which two engines
consume:

- the CPU engine (``spark_rapids_tpu.cpu.engine``) interprets nodes with
  pandas/numpy — it is both the fallback path for unsupported nodes and the
  golden-comparison oracle (the role vanilla Spark plays in the reference's
  test strategy, SparkQueryCompareTestSuite.scala:153-161),
- the TPU exec layer (``spark_rapids_tpu.execs``) — the accelerated path the
  planner (plan/overrides.py) converts replaceable subtrees into, exactly the
  GpuOverrides convertIfNeeded flow (RapidsMeta.scala:600-615).

Expressions inside nodes are **bound**: ``BoundReference`` ordinals into the
child's output schema (the reference binds with GpuBindReferences,
GpuBoundAttribute.scala:97). Output column names live in each node's
``output_schema``.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.expressions.aggregates import AggregateFunction
from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Expression, Literal)
from spark_rapids_tpu.ops.sortkeys import SortKeySpec

JOIN_TYPES = ("inner", "left", "right", "full", "left_semi", "left_anti",
              "cross")


def expr_name(e: Expression, i: int) -> str:
    if isinstance(e, Alias):
        return e.alias
    return f"col{i}"


class PlanNode:
    """Base physical plan node. Immutable tree; children in ``children``."""

    def __init__(self, children: Sequence["PlanNode"]):
        self.children = list(children)

    def output_schema(self) -> Schema:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__

    def with_children(self, children: List["PlanNode"]) -> "PlanNode":
        import copy

        c = copy.copy(self)
        c.children = list(children)
        return c

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.describe()]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def describe(self) -> str:
        return self.name

    def __repr__(self) -> str:  # pragma: no cover
        return self.tree_string()


# --------------------------------------------------------------------------
# Sources


class DataSource:
    """Leaf data provider. ``read_host()`` returns host-side columns —
    the CPU engine consumes them directly; the TPU scan exec uploads them
    (the reference's host read + device decode split,
    GpuParquetScan.scala:228-265)."""

    def schema(self) -> Schema:
        raise NotImplementedError

    def read_host(self):
        """-> (data: dict name->ndarray, validity: dict name->bool ndarray).
        String columns are object arrays (None = null)."""
        raise NotImplementedError

    # -- splits: file sources map splits onto scan partitions (the
    # reference's FilePartition model; GpuParquetScan.scala partition
    # readers). Default: one split backed by read_host().

    def num_splits(self) -> int:
        return 1

    def read_host_split(self, split: int):
        assert split == 0, split
        return self.read_host()

    def read_host_chunks(self, split: int):
        """Stream one split as (data, validity) host chunks for the
        scan pipeline (io/scanpipe). Default: the whole split as one
        chunk; file sources override with decode-granular streams."""
        yield self.read_host_split(split)

    def split_nbytes(self, split: int) -> int:
        """On-disk bytes reading this split touches (bytes_read
        telemetry); 0 for non-file sources."""
        return 0

    def split_origin(self, split: int):
        """(file_path, block_start, block_length) for file-backed splits
        (input_file_name support); None for non-file sources."""
        return None

    def split_stats(self, split: int):
        """{column: (min, max)} from file footer statistics for this
        split, or None. Sources with footer stats feed Column.stats for
        free (the packed-key groupby path) instead of an upload-time
        host min/max pass."""
        return None

    def estimated_row_count(self):
        """Plan-time row-count estimate (file footer metadata / host
        array length), or None when unknown. Feeds the optimizer's
        greedy join reordering — never correctness."""
        return None


class InMemorySource(DataSource):
    """Host-resident columns (dict name -> numpy array / list), the analogue
    of a cached relation. ``validity`` maps name -> bool mask."""

    def __init__(self, data: dict, schema: Optional[Schema] = None,
                 validity: Optional[dict] = None):
        self.data = data
        self.validity = validity or {}
        self._schema = schema or _infer_schema(data)

    def schema(self) -> Schema:
        return self._schema

    def read_host(self):
        return self.data, self.validity

    def estimated_row_count(self):
        for v in self.data.values():
            return len(v)
        return 0


def _infer_schema(data: dict) -> Schema:
    import numpy as np

    from spark_rapids_tpu.columnar.column import _infer_dtype

    names, types = [], []
    for k, v in data.items():
        arr = np.asarray(v)
        names.append(k)
        if arr.dtype == object or arr.dtype.kind in "US":
            types.append(dt.STRING)
        elif arr.dtype.kind == "M":
            unit = np.datetime_data(arr.dtype)[0]
            types.append(dt.DATE if unit == "D" else dt.TIMESTAMP)
        else:
            types.append(_infer_dtype(arr.dtype))
    return Schema(names, types)


class ScanNode(PlanNode):
    """Leaf scan over a DataSource (file sources live in io/ and subclass
    DataSource; the reference's GpuFileSourceScanExec / GpuBatchScanExec)."""

    def __init__(self, source: DataSource):
        super().__init__([])
        self.source = source

    def output_schema(self) -> Schema:
        return self.source.schema()

    def describe(self) -> str:
        return f"Scan[{type(self.source).__name__}]"


class RangeNode(PlanNode):
    """spark.range() analogue (GpuRangeExec, basicPhysicalOperators.scala)."""

    def __init__(self, start: int, end: int, step: int = 1,
                 name: str = "id"):
        super().__init__([])
        assert step != 0
        self.start, self.end, self.step = start, end, step
        self.col_name = name

    def output_schema(self) -> Schema:
        return Schema([self.col_name], [dt.INT64])

    def describe(self) -> str:
        return f"Range({self.start}, {self.end}, {self.step})"


# --------------------------------------------------------------------------
# Row-level ops


class ProjectNode(PlanNode):
    def __init__(self, exprs: List[Expression], child: PlanNode,
                 names: Optional[List[str]] = None):
        super().__init__([child])
        self.exprs = list(exprs)
        self.names = names or [expr_name(e, i) for i, e in enumerate(exprs)]

    def output_schema(self) -> Schema:
        return Schema(self.names, [e.dtype for e in self.exprs])

    def describe(self) -> str:
        return f"Project[{', '.join(self.names)}]"


class FilterNode(PlanNode):
    def __init__(self, condition: Expression, child: PlanNode):
        super().__init__([child])
        self.condition = condition

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def describe(self) -> str:
        return f"Filter[{self.condition!r}]"


# --------------------------------------------------------------------------
# Aggregation


@dataclasses.dataclass
class AggCall:
    """One named aggregate output: function over bound input expression(s)."""

    fn: AggregateFunction
    name: str


class AggregateNode(PlanNode):
    """Group-by aggregate. ``grouping`` are bound expressions (usually plain
    references) into the child; output schema = grouping names then agg
    names. ``mode`` follows the reference's partial/final split
    (aggregate.scala:298):

    - "complete": raw input -> final results (single-stage)
    - "partial":  raw input -> partial columns (update halves)
    - "final":    partial columns -> final results (merge halves + evaluate)
    """

    def __init__(self, grouping: List[Expression],
                 aggs: List[AggCall], child: PlanNode,
                 mode: str = "complete",
                 grouping_names: Optional[List[str]] = None):
        super().__init__([child])
        assert mode in ("complete", "partial", "final")
        self.grouping = list(grouping)
        self.aggs = list(aggs)
        self.mode = mode
        self.grouping_names = grouping_names or [
            expr_name(e, i) for i, e in enumerate(grouping)]

    def output_schema(self) -> Schema:
        names = list(self.grouping_names)
        types = [e.dtype for e in self.grouping]
        if self.mode == "partial":
            for a in self.aggs:
                for j, pt in enumerate(a.fn.partial_types()):
                    names.append(f"{a.name}#p{j}")
                    types.append(pt)
        else:
            for a in self.aggs:
                names.append(a.name)
                types.append(a.fn.dtype)
        return Schema(names, types)

    def describe(self) -> str:
        return (f"Aggregate[{self.mode}, keys={self.grouping_names}, "
                f"aggs={[a.name for a in self.aggs]}]")


# --------------------------------------------------------------------------
# Sort / limit / set ops


class SortNode(PlanNode):
    """``specs`` reference child ordinals. ``global_sort`` requires a total
    order across all partitions (the reference's RequireSingleBatch cliff,
    GpuSortExec.scala:50 — our exec chunks instead, SURVEY.md §5.7)."""

    def __init__(self, specs: List[SortKeySpec], child: PlanNode,
                 global_sort: bool = True):
        super().__init__([child])
        self.specs = list(specs)
        self.global_sort = global_sort

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def describe(self) -> str:
        return f"Sort[{self.specs}, global={self.global_sort}]"


class LimitNode(PlanNode):
    def __init__(self, n: int, child: PlanNode, global_limit: bool = True):
        super().__init__([child])
        self.n = n
        self.global_limit = global_limit

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def describe(self) -> str:
        return f"Limit[{self.n}]"


class UnionNode(PlanNode):
    """UNION ALL: children must be schema-compatible."""

    def __init__(self, children: List[PlanNode]):
        super().__init__(children)
        s0 = children[0].output_schema()
        for c in children[1:]:
            assert [t for t in c.output_schema().types] == list(s0.types), \
                "union children must share types"

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()


class ExpandNode(PlanNode):
    """Emits one output row per (input row, projection) — GROUPING SETS /
    rollup support (GpuExpandExec.scala)."""

    def __init__(self, projections: List[List[Expression]],
                 child: PlanNode, names: List[str]):
        super().__init__([child])
        assert projections
        self.projections = [list(p) for p in projections]
        self.names = names

    def output_schema(self) -> Schema:
        return Schema(self.names, [e.dtype for e in self.projections[0]])

    def describe(self) -> str:
        return f"Expand[{len(self.projections)} projections]"


class GenerateNode(PlanNode):
    """explode/posexplode of a per-row created array of expressions
    (GpuGenerateExec.scala: the reference supports exactly
    Explode/PosExplode(CreateArray(exprs)) since v0.3 has no array type).
    Each input row emits len(exprs) rows: the required child columns
    repeated, an optional position column, and the k-th expression's
    value. Lowering desugars this into Expand projections — one per array
    slot — instead of a dedicated kernel."""

    def __init__(self, exprs: List[Expression], child: PlanNode,
                 required_ordinals: List[int], value_name: str = "col",
                 include_pos: bool = False, pos_name: str = "pos"):
        super().__init__([child])
        assert exprs, "explode of an empty array produces no columns"
        assert len({e.dtype for e in exprs}) == 1, \
            "array slots must share one type (CreateArray type coercion " \
            "happens before planning)"
        self.exprs = list(exprs)
        self.required_ordinals = list(required_ordinals)
        self.value_name = value_name
        self.include_pos = include_pos
        self.pos_name = pos_name

    def output_schema(self) -> Schema:
        s = self.children[0].output_schema()
        names = [s.names[o] for o in self.required_ordinals]
        types = [s.types[o] for o in self.required_ordinals]
        if self.include_pos:
            names.append(self.pos_name)
            types.append(dt.INT32)
        names.append(self.value_name)
        types.append(self.exprs[0].dtype)
        return Schema(names, types)

    def expand_projections(self) -> List[List[Expression]]:
        """The Expand-projection desugaring (one projection per array
        slot) shared by the planner rule and the CPU oracle."""
        child_schema = self.children[0].output_schema()
        projections = []
        for k, e in enumerate(self.exprs):
            p: List[Expression] = [
                BoundReference(o, child_schema.types[o])
                for o in self.required_ordinals]
            if self.include_pos:
                p.append(Literal(k, dt.INT32))
            p.append(e)
            projections.append(p)
        return projections

    def describe(self) -> str:
        gen = "posexplode" if self.include_pos else "explode"
        return f"Generate[{gen}, {len(self.exprs)} slots]"


# --------------------------------------------------------------------------
# Joins


class JoinNode(PlanNode):
    """Equi-join on key ordinals plus optional residual condition evaluated
    over the joined row (left columns then right columns — the reference
    applies conditions as a post-join filter, GpuHashJoin.scala:285-291)."""

    def __init__(self, kind: str, left: PlanNode, right: PlanNode,
                 left_keys: List[int], right_keys: List[int],
                 condition: Optional[Expression] = None):
        super().__init__([left, right])
        assert kind in JOIN_TYPES, kind
        assert len(left_keys) == len(right_keys)
        if kind != "cross":
            assert left_keys, "equi-join requires keys; use kind='cross'"
        self.kind = kind
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.condition = condition

    def output_schema(self) -> Schema:
        ls, rs = (c.output_schema() for c in self.children)
        if self.kind in ("left_semi", "left_anti"):
            return ls
        names = list(ls.names) + list(rs.names)
        ltypes = list(ls.types)
        rtypes = list(rs.types)
        return Schema(names, ltypes + rtypes)

    def describe(self) -> str:
        return (f"Join[{self.kind}, l={self.left_keys}, r={self.right_keys}"
                + (", cond" if self.condition is not None else "") + "]")


# --------------------------------------------------------------------------
# Window


@dataclasses.dataclass
class WindowFrame:
    """Window frame. ``kind`` is "rows" (bounds are row offsets) or
    "range" (bounds are VALUE deltas against the single order key —
    RANGE BETWEEN x PRECEDING AND y FOLLOWING = keys in
    [k - x, k + y]); None = unbounded. Spark default for aggregates with
    an order spec is range (None, 0) but rows (None, 0) is equivalent
    for our run-aggregates, so "rows" stays the default here
    (GpuWindowExpression.scala:208-263 frame validation; the reference
    limits range frames to timestamp order keys — ours allow any
    numeric/date/timestamp ascending key)."""

    lower: Optional[int] = None
    upper: Optional[int] = 0
    kind: str = "rows"

    def __post_init__(self):
        assert self.kind in ("rows", "range"), self.kind


@dataclasses.dataclass
class WindowCall:
    """One window-function output column.

    ``fn`` is 'row_number' | 'rank' | 'dense_rank', a tuple
    ('lead'|'lag', input_expression), or an AggregateFunction instance
    (sum/min/max/count/avg evaluated over ``frame``)."""

    fn: object
    name: str
    frame: WindowFrame = dataclasses.field(default_factory=WindowFrame)
    offset: int = 1          # lead/lag
    default: object = None   # lead/lag fill


class WindowNode(PlanNode):
    """Appends window-function columns. Partitions by ordinals, orders
    within partitions by specs (GpuWindowExec.scala:92)."""

    def __init__(self, partition_ordinals: List[int],
                 order_specs: List[SortKeySpec],
                 calls: List[WindowCall], child: PlanNode):
        super().__init__([child])
        self.partition_ordinals = list(partition_ordinals)
        self.order_specs = list(order_specs)
        self.calls = list(calls)

    def output_schema(self) -> Schema:
        s = self.children[0].output_schema()
        names = list(s.names)
        types = list(s.types)
        for c in self.calls:
            names.append(c.name)
            if isinstance(c.fn, AggregateFunction):
                types.append(c.fn.dtype)
            elif c.fn in ("row_number", "rank", "dense_rank"):
                types.append(dt.INT32)
            elif isinstance(c.fn, tuple) and c.fn[0] in ("lead", "lag"):
                types.append(c.fn[1].dtype)
            else:
                raise ValueError(f"unknown window function {c.fn}")
        return Schema(names, types)

    def describe(self) -> str:
        return (f"Window[part={self.partition_ordinals}, "
                f"calls={[c.name for c in self.calls]}]")


# --------------------------------------------------------------------------
# Exchange markers (planner-inserted; single-process engines treat these as
# repartition points, the distributed runtime maps them onto ICI all_to_all)


class ShuffleExchangeNode(PlanNode):
    """partitioning: ('hash', ordinals) | ('range', specs) |
    ('round_robin',) | ('single',) — GpuShuffleExchangeExec.scala:146-248."""

    def __init__(self, partitioning: Tuple, num_partitions: int,
                 child: PlanNode):
        super().__init__([child])
        self.partitioning = partitioning
        self.num_partitions = num_partitions

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def describe(self) -> str:
        return (f"ShuffleExchange[{self.partitioning[0]}, "
                f"n={self.num_partitions}]")


class CoalescePartitionsNode(PlanNode):
    """df.coalesce(n): shrink partition count WITHOUT a shuffle by
    reading contiguous groups of input partitions (GpuCoalesceExec,
    GpuOverrides.scala:1777-1833 coalesce registration)."""

    def __init__(self, num_partitions: int, child: PlanNode):
        super().__init__([child])
        assert num_partitions >= 1
        self.num_partitions = num_partitions

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def describe(self) -> str:
        return f"CoalescePartitions[{self.num_partitions}]"


class BroadcastExchangeNode(PlanNode):
    """Marks the build side of a broadcast join
    (GpuBroadcastExchangeExec.scala:237)."""

    def __init__(self, child: PlanNode):
        super().__init__([child])

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()


# --------------------------------------------------------------------------
# Helpers


def walk(node: PlanNode):
    yield node
    for c in node.children:
        yield from walk(c)


_INPUT_FILE_EXPRS = ("InputFileName", "InputFileBlockStart",
                     "InputFileBlockLength")


def gate_split_packing(plan: PlanNode) -> None:
    """input_file_name/block exprs need per-file batch identity, which a
    packed multi-file scan partition cannot provide — disable packing on
    every file source when the plan reads them (the reference likewise
    gates its small-file optimization off under these expressions,
    GpuFileSourceScanExec's canUseSmallFileOpt). Engine-neutral (both
    the CPU oracle and the TPU planner call it), so detection is by
    class name, not import."""

    def expr_has(e) -> bool:
        if type(e).__name__ in _INPUT_FILE_EXPRS:
            return True
        return any(expr_has(c) for c in getattr(e, "children", ()))

    def node_has(n) -> bool:
        for v in vars(n).values():
            items = v if isinstance(v, (list, tuple)) else [v]
            for x in items:
                if hasattr(x, "children") and hasattr(x, "dtype") and \
                        expr_has(x):
                    return True
                fn = getattr(x, "fn", None)  # AggCall
                if fn is not None and getattr(fn, "input", None) \
                        is not None and expr_has(fn.input):
                    return True
        return any(node_has(c) for c in n.children)

    if not node_has(plan):
        return
    for n in walk(plan):
        src = getattr(n, "source", None)
        if src is not None and getattr(src, "pack_splits", False):
            # the source may be shared with a concurrently executing
            # scan — mutate split state only under its own lock so a
            # reader never sees pack_splits flipped mid-read
            lock = getattr(src, "_lock", None)
            if lock is not None:
                with lock:
                    src.pack_splits = False
                    src._splits = None  # re-derive unpacked
            else:
                src.pack_splits = False
                src._splits = None
