"""Canonical plan fingerprinting for the semantic cache (service/cache).

Mirrors ``Expression.tree_key()`` one level up: a hashable structural
key over a logical plan tree, with every leaf DataSource keyed by its
``(identity, snapshot version)`` pair from service/cache/snapshots. Two
plans with equal fingerprints read provably identical data and compute
provably identical results — the key the result cache and fragment
cache both hang entries on.

Conservative by construction: any payload this module cannot key —
an unkeyable expression, an opaque source (InMemorySource), a node
carrying runtime state (execs.cache.CacheNode's holder) — makes the
whole fingerprint None and the plan bypasses caching. A false "miss"
costs a recompute; a false "hit" would be a wrong answer.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from spark_rapids_tpu.expressions.base import Expression
from spark_rapids_tpu.plan import nodes as pn

#: sentinel distinct from a legitimate ``None`` attribute value
_UNKEYABLE = object()


@dataclasses.dataclass(frozen=True)
class PlanFingerprint:
    """``key`` is the hashable structural fingerprint; ``reads`` lists
    every ``(source identity, snapshot version)`` pair the plan reads —
    already folded into ``key``, kept separately for cache-entry
    observability (stats can say WHAT an entry depends on)."""

    key: tuple
    reads: tuple


def plan_fingerprint(plan: pn.PlanNode) -> Optional[PlanFingerprint]:
    """Fingerprint a plan subtree, or None when it cannot be keyed.
    Snapshot versions are resolved AS OF NOW: calling this twice around
    a table mutation yields different keys — which is exactly how
    publish-time revalidation detects a mid-run version bump."""
    reads: List[tuple] = []
    memo: dict = {}
    key = _node_key(plan, reads, memo)
    if key is None:
        return None
    return PlanFingerprint(key=key, reads=tuple(reads))


def _node_key(node: pn.PlanNode, reads, memo):
    cached = memo.get(id(node))
    if cached is not None:  # shared CTE subtree: key (and stat) once
        return cached
    params = []
    for k in sorted(vars(node)):
        if k == "children":
            continue
        vk = _val_key(vars(node)[k], reads)
        if vk is _UNKEYABLE:
            if k.startswith("_"):
                continue  # private unkeyable attrs are caches, not params
            return None
        params.append((k, vk))
    kids = []
    for c in node.children:
        ck = _node_key(c, reads, memo)
        if ck is None:
            return None
        kids.append(ck)
    out = (type(node).__module__, type(node).__qualname__,
           tuple(params), tuple(kids))
    memo[id(node)] = out
    return out


def _source_key(source, reads):
    from spark_rapids_tpu.service.cache import snapshots

    ident = snapshots.source_identity(source)
    if ident is None:
        return _UNKEYABLE
    version = snapshots.source_version(source)
    if version is None:
        return _UNKEYABLE
    reads.append((ident, version))
    return ("#src", ident, version)


def _val_key(v, reads):
    # float by repr: NaN would never dict-hit and -0.0 == 0.0 would
    # alias two semantically different constants (same rationale as
    # Expression.tree_key)
    if isinstance(v, (float, np.floating)):
        return ("#f", repr(float(v)))
    if isinstance(v, (bool, int, str, bytes, type(None))):
        return v
    if isinstance(v, np.integer):
        return ("#np", int(v))
    if isinstance(v, np.bool_):
        return ("#np", bool(v))
    if isinstance(v, Expression):
        tk = v.tree_key()
        return _UNKEYABLE if tk is None else ("#expr", tk)
    if isinstance(v, pn.DataSource):
        return _source_key(v, reads)
    if hasattr(v, "name") and hasattr(v, "kernel_dtype"):
        return ("#dtype", v.name)
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        # AggCall / SortKeySpec / WindowFrame / WindowCall payloads
        fields = []
        for f in dataclasses.fields(v):
            fk = _val_key(getattr(v, f.name), reads)
            if fk is _UNKEYABLE:
                return _UNKEYABLE
            fields.append((f.name, fk))
        return ("#dc", type(v).__qualname__, tuple(fields))
    if isinstance(v, (list, tuple)):
        out = []
        for x in v:
            xk = _val_key(x, reads)
            if xk is _UNKEYABLE:
                return _UNKEYABLE
            out.append(xk)
        return ("#seq",) + tuple(out)
    return _UNKEYABLE
