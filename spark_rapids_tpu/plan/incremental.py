"""Incremental-query plan analysis for standing queries.

A standing query (service/streaming) folds arriving micro-batches into
long-lived partial-aggregate state instead of recomputing from scratch.
That is only sound for plans of the shape

    Aggregate[complete](delta-reachable subtree over ONE streaming scan)

because the aggregate update/merge split (execs/aggregate.py) is the
incremental-combine operator: partials over disjoint row sets re-merge
to the partials of their union, so per-delta update partials fold into
the running state with one merge launch. Everything BELOW the aggregate
(filters, projections, joins against non-streaming dimension tables) is
row-local in the streaming input — running it over just the delta rows
produces exactly the delta's contribution.

This module validates that shape and builds the delta subplan: the
aggregate's child with the streaming scan swapped for a mutable
per-fold delta source. It deliberately knows nothing about the service
layer — sources are recognized by the ``is_streaming`` marker attribute
(service/streaming/source.py sets it), keeping plan/ free of service
imports.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from spark_rapids_tpu.plan import nodes as pn


class IncrementalUnsupported(ValueError):
    """The plan cannot be maintained incrementally — submit it as a
    normal batch query instead."""


@dataclasses.dataclass
class IncrementalInfo:
    """The validated decomposition register_standing folds over."""

    #: the root complete-mode aggregate (grouping/aggs bound to child)
    aggregate: pn.AggregateNode
    #: the aggregate's child subtree (delta subplan template)
    child: pn.PlanNode
    #: the single streaming DataSource the child reads
    stream_source: pn.DataSource
    #: rename-only projection above the aggregate (SQL aliases GROUP BY
    #: outputs this way): (output name, ordinal into aggregate output);
    #: None when the aggregate is the literal root
    projection: Optional[List[Tuple[str, int]]] = None

    def output_names(self) -> List[str]:
        if self.projection is not None:
            return [n for n, _ in self.projection]
        return list(self.aggregate.output_schema().names)


def is_streaming_source(source) -> bool:
    return bool(getattr(source, "is_streaming", False))


def streaming_sources(plan: pn.PlanNode) -> List[pn.DataSource]:
    """Every distinct streaming source read anywhere under ``plan``."""
    out: List[pn.DataSource] = []
    for node in pn.walk(plan):
        src = getattr(node, "source", None)
        if src is not None and is_streaming_source(src) and \
                not any(s is src for s in out):
            out.append(src)
    return out


def _rename_only(node: pn.ProjectNode
                 ) -> Optional[List[Tuple[str, int]]]:
    """(name, child ordinal) per output if ``node`` only renames /
    reorders its input columns; None if any expression computes."""
    from spark_rapids_tpu.expressions.base import Alias, BoundReference

    out: List[Tuple[str, int]] = []
    for name, e in zip(node.names, node.exprs):
        while isinstance(e, Alias):
            e = e.children[0]
        if not isinstance(e, BoundReference):
            return None
        out.append((name, e.ordinal))
    return out


def analyze(plan) -> IncrementalInfo:
    """Validate ``plan`` (a PlanNode or DataFrame-like with ``_plan``)
    for incremental maintenance; raises IncrementalUnsupported with the
    reason otherwise."""
    node = getattr(plan, "_plan", plan)
    # the SQL planner tops GROUP BY statements with a rename-only
    # projection (SELECT aliases); peel those — the renaming applies to
    # the EMITTED frame, it never touches what the fold maintains
    projection: Optional[List[Tuple[str, int]]] = None
    while isinstance(node, pn.ProjectNode):
        mapping = _rename_only(node)
        if mapping is None:
            raise IncrementalUnsupported(
                "the projection above the aggregate computes new "
                "columns — a standing query supports only rename/"
                "reorder above its aggregate; compute inside the "
                "aggregation or in the consumer")
        projection = mapping if projection is None else \
            [(name, mapping[ordinal][1]) for name, ordinal in projection]
        node = node.children[0]
    if not isinstance(node, pn.AggregateNode):
        raise IncrementalUnsupported(
            "a standing query must be a top-level aggregation "
            f"(got {type(node).__name__}) — the update/merge split is "
            "the incremental operator, so the aggregate must be the "
            "outermost node")
    if node.mode != "complete":
        raise IncrementalUnsupported(
            f"standing queries fold complete-mode aggregates, not "
            f"{node.mode!r} (partial/final splits belong to the batch "
            f"planner)")
    for call in node.aggs:
        if getattr(call.fn, "distinct", False):
            raise IncrementalUnsupported(
                f"aggregate {call.name!r} is DISTINCT: its update "
                "partials are not mergeable across micro-batches")
    child = node.children[0]
    streams = streaming_sources(child)
    if not streams:
        raise IncrementalUnsupported(
            "the plan reads no streaming table (create one with "
            "Session.create_streaming_table) — nothing would ever "
            "arrive to fold")
    if len(streams) > 1:
        raise IncrementalUnsupported(
            f"the plan reads {len(streams)} streaming tables; "
            "incremental folding supports exactly one streaming fact "
            "side (dimension sides must be non-streaming)")
    for n in pn.walk(child):
        # a runtime-state holder (df.cache()) under the delta subtree
        # would replay its FIRST materialization on every fold
        if type(n).__name__ == "CacheNode":
            raise IncrementalUnsupported(
                "the delta subtree contains a CacheNode: its one-shot "
                "materialization cannot observe per-fold deltas")
    return IncrementalInfo(aggregate=node, child=child,
                           stream_source=streams[0],
                           projection=projection)


def substitute_source(node: pn.PlanNode, old: pn.DataSource,
                      new: pn.DataSource) -> pn.PlanNode:
    """The delta subplan: ``node`` with every scan of ``old`` replaced
    by a scan of ``new``. Untouched subtrees (the dimension sides) are
    SHARED, not copied — their exec-side materializations (broadcast
    builds) survive across folds by identity."""
    if isinstance(node, pn.ScanNode) and node.source is old:
        return pn.ScanNode(new)
    kids = [substitute_source(c, old, new) for c in node.children]
    if all(k is c for k, c in zip(kids, node.children)):
        return node
    return node.with_children(kids)
