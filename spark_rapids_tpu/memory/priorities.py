"""Spill priorities: lower value spills first.

Mirrors the reference's ordering contract (SpillPriorities.scala:32-60):
shuffle output buffers spill first (they are re-fetchable / persisted), then
shuffle input being read, then batches being coalesced, and active per-task
working batches spill last.
"""

# Shuffle output awaiting fetch: cheapest to lose from device (= 0 in the
# reference, SpillPriorities.scala:35).
OUTPUT_FOR_SHUFFLE_PRIORITY = 0

# Buffers received from a remote shuffle, not yet handed to a task.
INPUT_FROM_SHUFFLE_PRIORITY = 1 << 20

# Scan-cache landings (io/scanpipe): scan results parked as spillable
# batches keyed on per-file (mtime_ns, size). Re-reading the source file
# is cheaper than recomputing a cached fragment's plan, so these spill
# before CACHED_FRAGMENT, but they save real filesystem+decode work, so
# they outlast shuffle residue.
SCAN_CACHE_PRIORITY = 1 << 25

# Materialized semantic-cache fragments (service/cache): re-creatable
# from their source plan, so they spill before any query's working
# batches, but they serve many future queries, so they outlast shuffle
# residue awaiting a single consumer.
CACHED_FRAGMENT_PRIORITY = 1 << 30

# Standing-query partial-aggregate state (service/streaming): outlives
# any single fold by design and is NOT re-creatable without replaying
# every ingested micro-batch, so it outranks cached fragments (which
# recompute from their source plan) — but it is idle between folds, so
# it spills before anything a task is actively computing on.
STREAMING_STATE_PRIORITY = 1 << 35

# Batches buffered by the coalesce iterator while accumulating to its goal.
COALESCE_PRIORITY = 1 << 40

# A task's on-deck / actively-processed batch: spill only as a last resort
# (Long.MaxValue - 1000 in the reference, SpillPriorities.scala:52-59).
ACTIVE_ON_DECK_PRIORITY = (1 << 62) - 1000
ACTIVE_BATCHING_PRIORITY = (1 << 62) - 2000
