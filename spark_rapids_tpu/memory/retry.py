"""Split-and-retry: the OOM-resilience framework.

The reference survives device memory pressure in two layers: the RMM
alloc-failure handler spills the buffer store and retries the
allocation (DeviceMemoryEventHandler.scala:42-69), and the retry
iterator generalizes that so ANY operator can halve its input and keep
going instead of dying (RmmRapidsRetryIterator: withRetry /
withRetryNoSplit / splitAndRetry semantics). XLA exposes no alloc
callback, so control inverts: device computations run inside
``with_retry`` and on RESOURCE_EXHAUSTED the framework climbs a ladder

    spill to half the tracked bytes  ->  spill everything  ->
    split the offending input and process the halves (bounded depth)
    ->  give up (SplitAndRetryOOM, chained to the original error)

Call sites that can consume multiple outputs (aggregate update batches,
join probe batches) pass a ``split`` function and genuinely halve;
sites whose contract is one output (concat-to-single-batch, a sort
bucket) use ``with_retry_no_split`` and stop at the spill rungs.

Every rung is accounted per call-site tag and per catalog buffer-owner
(the query service's owner tag), so retries/splits/bytes-spilled/time
blocked surface in ServiceStats and the benchmark-runner JSON. The
fault injector (memory/fault_injection.py) hooks the guarded-call
bracket, so the whole ladder is exercised deterministically on CPU CI.
"""
from __future__ import annotations

import logging
import re
import threading
from spark_rapids_tpu.utils import lockorder
import time
from typing import Callable, List, Optional, TypeVar

from spark_rapids_tpu.memory.catalog import (BufferCatalog,
                                             current_buffer_owner,
                                             get_catalog)
from spark_rapids_tpu.memory.fault_injection import InjectedOOM, get_injector

log = logging.getLogger(__name__)

T = TypeVar("T")
U = TypeVar("U")


class SplitAndRetryOOM(RuntimeError):
    """The whole ladder — spill-to-budget, spill-all, splits to the
    depth bound — failed to make the computation fit. Raised ``from``
    the original device error so the trace keeps both contexts."""


# -- OOM classification ------------------------------------------------------
# Type-gated + anchored-marker matching. The old bare substring scan
# (`"OOM" in str(exc)`) classified a ValueError mentioning "OOM" in
# user data as a device OOM and silently spill-retried it; now only
# runtime-level errors whose message carries an allocation-failure
# marker in marker position qualify.

_OOM_PATTERNS = (
    re.compile(r"(?:^|[:\s(])RESOURCE[_ ]EXHAUSTED(?:$|[:\s)])"),
    re.compile(r"(?:^|: )Out of memory(?:$|[ :])"),
    re.compile(r"(?:^|: )Resource exhausted(?:$|[ :])"),
    re.compile(r"\bOut of memory allocating\b"),
)


def is_oom_error(exc: BaseException) -> bool:
    if isinstance(exc, (InjectedOOM, MemoryError)):
        return True
    # jaxlib raises XlaRuntimeError (a RuntimeError subclass); user
    # errors like ValueError/KeyError never count however their
    # message reads
    if not isinstance(exc, RuntimeError):
        return False
    msg = str(exc)
    return any(p.search(msg) for p in _OOM_PATTERNS)


# -- retry policy (config-wired) --------------------------------------------

DEFAULT_MAX_SPILL_RETRIES = 2
DEFAULT_MAX_SPLIT_DEPTH = 8

_policy_lock = lockorder.make_lock("memory.retry.policy")
_max_spill_retries = DEFAULT_MAX_SPILL_RETRIES
_max_split_depth = DEFAULT_MAX_SPLIT_DEPTH


def configure(max_spill_retries: Optional[int] = None,
              max_split_depth: Optional[int] = None) -> None:
    global _max_spill_retries, _max_split_depth
    with _policy_lock:
        if max_spill_retries is not None:
            _max_spill_retries = max(int(max_spill_retries), 0)
        if max_split_depth is not None:
            _max_split_depth = max(int(max_split_depth), 0)


def configure_from_conf(conf) -> None:
    from spark_rapids_tpu import config as cfg

    configure(max_spill_retries=conf.get(cfg.RETRY_MAX_SPILL_RETRIES),
              max_split_depth=conf.get(cfg.RETRY_MAX_SPLIT_DEPTH))


def reset_config() -> None:
    configure(DEFAULT_MAX_SPILL_RETRIES, DEFAULT_MAX_SPLIT_DEPTH)


# -- accounting --------------------------------------------------------------

_STAT_KEYS = ("oom_retries", "oom_splits", "spilled_bytes", "blocked_s",
              "gave_ups")

_stats_lock = lockorder.make_lock("memory.retry.stats")
_totals = {k: 0 for k in _STAT_KEYS}
_per_site: dict = {}
_per_owner: dict = {}


def _record(site: str, owner, retries: int = 0, splits: int = 0,
            spilled: int = 0, blocked_s: float = 0.0,
            gave_up: int = 0) -> None:
    delta = {"oom_retries": retries, "oom_splits": splits,
             "spilled_bytes": spilled, "blocked_s": blocked_s,
             "gave_ups": gave_up}
    with _stats_lock:
        for k, v in delta.items():
            _totals[k] += v
        site_d = _per_site.setdefault(site, {k: 0 for k in _STAT_KEYS})
        for k, v in delta.items():
            site_d[k] += v
        if owner is not None:
            own = _per_owner.setdefault(owner,
                                        {k: 0 for k in _STAT_KEYS})
            for k, v in delta.items():
                own[k] += v


def snapshot() -> dict:
    """Totals so far (for before/after deltas in the runner)."""
    with _stats_lock:
        return dict(_totals)


def delta(before: dict) -> dict:
    now = snapshot()
    return {k: round(now[k] - before.get(k, 0), 6)
            for k in _STAT_KEYS}


def stats() -> dict:
    """{"totals": ..., "per_site": ...} — the observability snapshot
    the runner JSON and chaos fence embed."""
    with _stats_lock:
        return {"totals": dict(_totals),
                "per_site": {s: dict(d) for s, d in _per_site.items()}}


def site_delta(before_per_site: dict) -> dict:
    """Per-site deltas against a prior ``stats()["per_site"]`` snapshot
    (sites with no activity since dropped) — so a report covering one
    run never mixes in another run's counters."""
    with _stats_lock:
        now = {s: dict(d) for s, d in _per_site.items()}
    out = {}
    for site, d in now.items():
        prev = before_per_site.get(site, {})
        dd = {k: round(d[k] - prev.get(k, 0), 6) for k in _STAT_KEYS}
        if any(dd.values()):
            out[site] = dd
    return out


def owner_stats(owner) -> dict:
    """Accumulated retry accounting of one buffer-owner tag (the query
    service's per-query view)."""
    with _stats_lock:
        d = _per_owner.get(owner)
        return dict(d) if d else {k: 0 for k in _STAT_KEYS}


def pop_owner_stats(owner) -> dict:
    """Final per-owner accounting, removed from the live map — a
    long-lived service must not keep an entry per query ever run."""
    with _stats_lock:
        d = _per_owner.pop(owner, None)
        return dict(d) if d else {k: 0 for k in _STAT_KEYS}


def reset_stats() -> None:
    with _stats_lock:
        for k in _STAT_KEYS:
            _totals[k] = 0
        _per_site.clear()
        _per_owner.clear()


# -- splitters ---------------------------------------------------------------


def halve_batch(batch) -> Optional[list]:
    """Split a ColumnarBatch into two row-range halves; None when it
    cannot shrink further (the ladder then gives up)."""
    n = batch.realized_num_rows()
    if n <= 1:
        return None
    h = n // 2
    return [batch.slice(0, h), batch.slice(h, n - h)]


# -- the ladder --------------------------------------------------------------


def _spill_rung(cat: BufferCatalog, attempt: int) -> int:
    """Rung ``attempt`` of the spill escalation: first to half the
    tracked device bytes, then everything (DeviceMemoryEventHandler's
    store-exhausted escalation)."""
    if attempt == 0:
        target = cat.device_bytes // 2
        log.warning("device OOM: spilling to %d tracked bytes and "
                    "retrying", target)
        return cat.synchronous_spill(target)
    log.warning("device OOM persists: spilling all tracked device "
                "buffers")
    return cat.spill_all_device()


def with_retry(item: U, fn: Callable[[U], T], *,
               split: Optional[Callable[[U], Optional[list]]] = None,
               catalog: Optional[BufferCatalog] = None,
               tag: str = "<untagged>",
               max_spill_retries: Optional[int] = None,
               max_split_depth: Optional[int] = None) -> List[T]:
    """Run ``fn(item)`` under the OOM ladder; returns the result list —
    one element normally, several when the input had to split.

    ``split(item)`` must return >= 2 sub-items that together cover the
    input (or None when it cannot shrink), and ``fn`` over the parts
    must compose: callers merge the returned parts themselves (partial
    aggregates re-merge, join probe outputs just concatenate).
    """
    cat = catalog if catalog is not None else get_catalog()
    spill_rungs = _max_spill_retries if max_spill_retries is None \
        else max_spill_retries
    depth_bound = _max_split_depth if max_split_depth is None \
        else max_split_depth
    injector = get_injector()
    out: List[T] = []
    work = [(item, 0)]  # LIFO would reorder halves; treat as FIFO
    while work:
        cur, depth = work.pop(0)
        attempt = 0
        while True:
            try:
                injector.maybe_inject(tag)
                out.append(fn(cur))
                break
            except Exception as exc:
                if not is_oom_error(exc):
                    raise
                owner = current_buffer_owner()
                if attempt < spill_rungs:
                    t0 = time.perf_counter()
                    spilled = _spill_rung(cat, attempt)
                    _record(tag, owner, retries=1, spilled=spilled,
                            blocked_s=time.perf_counter() - t0)
                    attempt += 1
                    continue
                halves = None
                if split is not None and depth < depth_bound:
                    halves = split(cur)
                if halves:
                    log.warning(
                        "device OOM survived %d spill retries at %s: "
                        "splitting input (depth %d)", attempt, tag,
                        depth + 1)
                    _record(tag, owner, splits=1)
                    work[:0] = [(h, depth + 1) for h in halves]
                    break
                _record(tag, owner, gave_up=1)
                raise SplitAndRetryOOM(
                    f"device OOM at {tag!r} persisted through "
                    f"{attempt} spill retries and split depth {depth} "
                    f"(splittable={split is not None})") from exc
    return out


def with_retry_no_split(fn: Callable[[], T], *,
                        catalog: Optional[BufferCatalog] = None,
                        tag: str = "<untagged>",
                        max_spill_retries: Optional[int] = None) -> T:
    """Single-output form: spill rungs only, no splitting — for call
    sites whose contract is exactly one result (concat-to-one, a sort
    bucket). The reference's withRetryNoSplit."""
    return with_retry(None, lambda _none: fn(), catalog=catalog,
                      tag=tag, max_spill_retries=max_spill_retries,
                      max_split_depth=0)[0]
