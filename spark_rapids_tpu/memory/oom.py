"""OOM detection + spill-and-retry.

The reference installs an RMM event handler whose alloc-failure callback
spills the device store and asks RMM to retry
(DeviceMemoryEventHandler.onAllocFailure, DeviceMemoryEventHandler.scala:
42-69). XLA exposes no alloc callback, so the TPU design inverts control:
wrap device computations in ``with_oom_retry`` — on RESOURCE_EXHAUSTED we
synchronously spill catalog-managed buffers and re-run, escalating from
"spill to budget" to "spill everything" before giving up.
"""
from __future__ import annotations

import logging
from typing import Callable, Optional, TypeVar

from spark_rapids_tpu.memory.catalog import BufferCatalog, get_catalog

log = logging.getLogger(__name__)

T = TypeVar("T")

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "OOM",
                "Resource exhausted")


def is_oom_error(exc: BaseException) -> bool:
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


def with_oom_retry(fn: Callable[[], T],
                   catalog: Optional[BufferCatalog] = None,
                   max_retries: int = 2) -> T:
    """Run ``fn``; on device OOM spill and retry (escalating), then re-raise.

    Retry ladder mirrors DeviceMemoryEventHandler's store-exhausted logic:
    first spill down to half the tracked bytes, then spill everything.
    """
    cat = catalog if catalog is not None else get_catalog()
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as exc:  # jaxlib raises XlaRuntimeError(RuntimeError)
            if not is_oom_error(exc) or attempt >= max_retries:
                raise
            if attempt == 0:
                target = cat.device_bytes // 2
                log.warning("device OOM: spilling to %d tracked bytes and "
                            "retrying", target)
                cat.synchronous_spill(target)
            else:
                log.warning("device OOM persists: spilling all tracked "
                            "device buffers")
                cat.spill_all_device()
            attempt += 1
