"""OOM detection + spill-and-retry (compatibility surface).

The real machinery moved to :mod:`spark_rapids_tpu.memory.retry`, which
generalizes the original spill-and-rerun ladder into the reference's
split-and-retry shape (RmmRapidsRetryIterator) with per-site accounting
and deterministic fault injection. This module keeps the historical
names importable:

- ``is_oom_error`` — now type-gated with anchored markers (a ValueError
  whose user data mentions "OOM" is no longer treated as a device OOM),
- ``with_oom_retry`` — the spill-only ladder; on give-up the terminal
  ``SplitAndRetryOOM`` chains ``from`` the original device error
  instead of discarding the retry context.
"""
from __future__ import annotations

from typing import Callable, Optional, TypeVar

from spark_rapids_tpu.memory.catalog import BufferCatalog
from spark_rapids_tpu.memory.retry import (  # noqa: F401
    SplitAndRetryOOM,
    is_oom_error,
    with_retry_no_split,
)

T = TypeVar("T")


def with_oom_retry(fn: Callable[[], T],
                   catalog: Optional[BufferCatalog] = None,
                   max_retries: int = 2,
                   tag: str = "oom.retry") -> T:
    """Run ``fn``; on device OOM spill and retry (escalating: half the
    tracked bytes, then everything), then raise SplitAndRetryOOM from
    the original error."""
    return with_retry_no_split(fn, catalog=catalog, tag=tag,
                               max_spill_retries=max_retries)
