"""Tiered memory management: catalog, spill stores, admission control.

TPU-native re-design of the reference's device/host/disk spill framework
(SURVEY.md §2.3): RapidsBufferCatalog (RapidsBufferCatalog.scala:109),
RapidsBufferStore chain (RapidsBufferStore.scala:39), SpillPriorities
(SpillPriorities.scala:32-60), SpillableColumnarBatch
(SpillableColumnarBatch.scala), GpuSemaphore (GpuSemaphore.scala:27) and the
RMM OOM event handler (DeviceMemoryEventHandler.scala:42-69).

XLA owns the actual HBM allocator, so unlike RMM there is no alloc callback
to intercept; instead the catalog enforces a *logical* device budget over all
registered (spillable) buffers and the OOM hook catches XLA
RESOURCE_EXHAUSTED errors, spills, and retries the computation.
"""
from spark_rapids_tpu.memory.priorities import (  # noqa: F401
    ACTIVE_BATCHING_PRIORITY,
    ACTIVE_ON_DECK_PRIORITY,
    COALESCE_PRIORITY,
    INPUT_FROM_SHUFFLE_PRIORITY,
    OUTPUT_FOR_SHUFFLE_PRIORITY,
)
from spark_rapids_tpu.memory.catalog import (  # noqa: F401
    BufferCatalog,
    SpillCorruptionError,
    StorageTier,
    get_catalog,
    reset_catalog,
)
from spark_rapids_tpu.memory.spillable import SpillableBatch  # noqa: F401
from spark_rapids_tpu.memory.semaphore import TpuSemaphore  # noqa: F401
from spark_rapids_tpu.memory.fault_injection import (  # noqa: F401
    FaultInjector,
    InjectedOOM,
    get_injector,
)
from spark_rapids_tpu.memory.retry import (  # noqa: F401
    SplitAndRetryOOM,
    halve_batch,
    is_oom_error,
    with_retry,
    with_retry_no_split,
)
from spark_rapids_tpu.memory.oom import with_oom_retry  # noqa: F401
