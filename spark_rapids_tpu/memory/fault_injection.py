"""Deterministic device-OOM fault injection.

The reference validates its spill-and-retry machinery with
RmmSpark.forceRetryOOM / forceSplitAndRetryOOM — test hooks that make
the Nth allocation on a task thread fail so the retry ladder is
exercised without real memory pressure (spark-rapids-jni RmmSpark API).
XLA gives us no allocation hook, but the retry framework
(memory/retry.py) brackets every guarded device computation with
``maybe_inject(site)`` — so the injector fires synthetic
RESOURCE_EXHAUSTED errors at exact, reproducible points:

- ``at_call=N``: the Nth eligible guarded call fails,
- ``sites``: restrict eligibility to call-site tags (e.g.
  ``aggregate.update``; prefix match, so ``join`` hits every join site),
- ``probability`` + ``seed``: seeded random firing for chaos sweeps,
- ``consecutive=K``: each firing point fails K guarded calls in a row,
  which is what pushes the ladder past spill-and-retry into
  split-and-retry (K > maxSpillRetries forces a split),
- ``max_injections``: total cap, so a chaos run terminates.

Armed from config (``rapids.tpu.memory.faultInjection.*``) by
``runtime.initialize`` or directly by tests/scripts. Everything runs on
CPU CI: the injected error takes the identical except-path a real XLA
RESOURCE_EXHAUSTED takes.
"""
from __future__ import annotations

import random
import threading
from spark_rapids_tpu.utils import lockorder
from typing import Optional, Sequence, Tuple


class InjectedOOM(RuntimeError):
    """Synthetic device OOM. The message carries the canonical
    RESOURCE_EXHAUSTED marker so ``is_oom_error`` classifies it exactly
    like a real XLA allocation failure."""

    def __init__(self, site: str, call_no: int):
        self.site = site
        self.call_no = call_no
        super().__init__(
            f"RESOURCE_EXHAUSTED: injected device OOM at guarded call "
            f"{call_no} (site {site!r})")


class FaultInjector:
    """Thread-safe injection point shared by every guarded call."""

    def __init__(self):
        self._lock = lockorder.make_lock("memory.faultInjection")
        self.disarm()

    def disarm(self) -> None:
        with getattr(self, "_lock", threading.Lock()):
            self._armed = False
            self._at_call = 0
            self._sites: Tuple[str, ...] = ()
            self._probability = 0.0
            self._rng: Optional[random.Random] = None
            self._consecutive = 1
            self._max_injections = 0
            self._burst_left = 0
            self._calls = 0
            self._eligible_calls = 0
            self._injections = 0

    def arm(self, at_call: int = 0, sites: Sequence[str] = (),
            probability: float = 0.0, seed: int = 0,
            consecutive: int = 1, max_injections: int = 0) -> None:
        """Arm (resetting all counters). ``at_call`` counts ELIGIBLE
        (site-matching) guarded calls from 1; 0 disables the
        deterministic trigger (probability may still fire)."""
        with self._lock:
            self._armed = True
            self._at_call = max(int(at_call), 0)
            self._sites = tuple(s for s in sites if s)
            self._probability = float(probability)
            self._rng = random.Random(seed) if probability > 0 else None
            self._consecutive = max(int(consecutive), 1)
            self._max_injections = max(int(max_injections), 0)
            self._burst_left = 0
            self._calls = 0
            self._eligible_calls = 0
            self._injections = 0

    @property
    def armed(self) -> bool:
        return self._armed

    def _site_matches(self, site: str) -> bool:
        if not self._sites:
            return True
        return any(site.startswith(s) for s in self._sites)

    def maybe_inject(self, site: str) -> None:
        """Called by the retry framework before every guarded device
        computation; raises InjectedOOM when the armed config says this
        call fails. Near-zero cost when disarmed."""
        if not self._armed:
            return
        with self._lock:
            self._calls += 1
            if not self._site_matches(site):
                return
            self._eligible_calls += 1
            if self._max_injections and \
                    self._injections >= self._max_injections:
                return
            fire = False
            if self._burst_left > 0:
                self._burst_left -= 1
                fire = True
            elif self._at_call and self._eligible_calls == self._at_call:
                fire = True
                self._burst_left = self._consecutive - 1
            elif self._rng is not None and \
                    self._rng.random() < self._probability:
                fire = True
                self._burst_left = self._consecutive - 1
            if not fire:
                return
            self._injections += 1
            call_no = self._eligible_calls
        raise InjectedOOM(site, call_no)

    def stats(self) -> dict:
        with self._lock:
            return {"armed": self._armed, "calls": self._calls,
                    "eligible_calls": self._eligible_calls,
                    "injections": self._injections}


_injector = FaultInjector()


def get_injector() -> FaultInjector:
    return _injector


def arm_from_conf(conf) -> bool:
    """Arm/disarm the global injector from ``rapids.tpu.memory.
    faultInjection.*``; returns True when armed."""
    from spark_rapids_tpu import config as cfg

    if not conf.get(cfg.FAULT_INJECTION_ENABLED):
        _injector.disarm()
        return False
    sites = [s.strip() for s in
             str(conf.get(cfg.FAULT_INJECTION_SITES)).split(",")
             if s.strip()]
    _injector.arm(
        at_call=conf.get(cfg.FAULT_INJECTION_AT_CALL),
        sites=sites,
        probability=conf.get(cfg.FAULT_INJECTION_PROBABILITY),
        seed=conf.get(cfg.FAULT_INJECTION_SEED),
        consecutive=conf.get(cfg.FAULT_INJECTION_CONSECUTIVE),
        max_injections=conf.get(cfg.FAULT_INJECTION_MAX))
    return True
