"""Buffer catalog + tiered device→host→disk spill stores.

Re-design of RapidsBufferCatalog (RapidsBufferCatalog.scala:109: global
id→buffer map with acquire/ref-count), the RapidsBufferStore chain
(RapidsBufferStore.scala:39-88: per-store priority-ordered spill to the next
tier, wired device→host→disk at RapidsBufferCatalog.scala:132-137), the
bounded host store (RapidsHostMemoryStore.scala;
rapids.tpu.memory.host.spillStorageSize) and the disk store
(RapidsDiskStore.scala).

TPU adaptations:
- Buffers are whole ``ColumnarBatch``es (JAX arrays); XLA owns physical HBM,
  so the device "store" tracks logical bytes against a configurable budget
  rather than owning allocations.
- Device→host spill is ``jax.device_get`` into a ``HostBatch``; host→disk
  writes the serde wire format (serde.py) — the same bytes shuffle and
  broadcast use, like the reference reuses TableMeta/JCudfSerialization.
- Unspill on acquire copies back up the chain (RapidsBufferStore.scala's
  ``getColumnarBatch`` from a spilled tier).

Thread-safe: one lock guards the maps (the reference uses a ConcurrentHashMap
plus per-store synchronization; our operations are coarse enough for one
lock — spill IO happens outside it only for disk writes).
"""
from __future__ import annotations

import enum
import itertools
import logging
import os
import queue
import tempfile
import threading
from spark_rapids_tpu.utils import lockorder
from typing import Dict, Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar import serde
from spark_rapids_tpu.memory.hashed_pq import HashedPriorityQueue

log = logging.getLogger(__name__)


class SpillCorruptionError(RuntimeError):
    """A disk-tier spill file failed to decode (truncation, checksum
    mismatch, bad envelope). Raised instead of handing a kernel garbage
    data; chains the underlying decode error."""


class StorageTier(enum.IntEnum):
    """Where a buffer currently lives (StorageTier analogue)."""

    DEVICE = 0
    HOST = 1
    DISK = 2


class _Entry:
    __slots__ = ("buffer_id", "priority", "tier", "device_batch",
                 "host_batch", "disk_path", "size", "refcount", "seq",
                 "pending_remove", "owner", "bias")

    def __init__(self, buffer_id: int, priority: int, batch: ColumnarBatch,
                 size: int, seq: int, owner=None):
        self.buffer_id = buffer_id
        self.priority = priority
        self.tier = StorageTier.DEVICE
        self.device_batch: Optional[ColumnarBatch] = batch
        self.host_batch: Optional[serde.HostBatch] = None
        self.disk_path: Optional[str] = None
        self.size = size
        self.refcount = 0
        self.seq = seq
        self.pending_remove = False
        # owner tag (query id) + spill-priority bias: the query service
        # demotes buffers of queued/stalled queries so pressure evicts
        # the tenant that is NOT running (SpillPriorities aging analogue)
        self.owner = owner
        self.bias = 0

    def spill_key(self):
        return (self.priority + self.bias, self.seq)


# Thread-local buffer-ownership tag: the stage scheduler brackets each
# query slice with set_buffer_owner(query_id) so every batch the slice
# registers is attributable to its query — demotable while the query is
# stalled, removable wholesale on cancel/deadline.
_owner_tls = threading.local()


def set_buffer_owner(owner) -> object:
    """Set this thread's registration owner tag; returns the previous
    tag for restore (None = untagged)."""
    prev = getattr(_owner_tls, "owner", None)
    _owner_tls.owner = owner
    return prev


def current_buffer_owner():
    return getattr(_owner_tls, "owner", None)


class AsyncBatchWriter:
    """Bounded-queue single-thread async commit template (the PR 6
    double-buffered spill writer, generalized): the caller keeps
    computing while one writer thread processes submitted items. The
    bounded queue (depth 2 by default) is the double buffer — one item
    in flight, one staged — and doubles as backpressure: a storm of
    submissions blocks the submitter instead of queueing unbounded
    host memory. Subclasses implement ``_process`` (writer-thread
    body) and may override ``_on_error`` (must not raise); the
    host->disk spill path and the streaming checkpoint writer
    (service/streaming/durability.py) are the two instantiations."""

    _STOP = object()

    def __init__(self, cv: "threading.Condition", thread_name: str,
                 depth: int = 2):
        # the subclass makes the condition with a LITERAL lockorder
        # name at its own site, so the hierarchy stays statically
        # checkable (tpulint TPU303)
        self._q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._pending = 0
        self._cv = cv
        self._thread: Optional[threading.Thread] = None
        self._thread_name = thread_name

    def _process(self, item) -> None:
        raise NotImplementedError

    def _on_error(self, item, exc: BaseException) -> None:
        log.exception("async writer %s failed processing %r",
                      self._thread_name, item)

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name=self._thread_name, daemon=True)
            self._thread.start()

    def submit(self, item) -> None:
        with self._cv:
            self._pending += 1
            self._ensure_thread()
        self._q.put(item)  # blocks at depth: the backpressure point

    def pending(self) -> int:
        with self._cv:
            return self._pending

    def _loop(self) -> None:
        while True:
            e = self._q.get()
            if e is self._STOP:
                return
            try:
                self._process(e)
            except Exception as exc:  # noqa: BLE001 - must not kill the writer
                self._on_error(e, exc)
            finally:
                with self._cv:
                    self._pending -= 1
                    self._cv.notify_all()

    def drain(self) -> None:
        """Block until every submitted item committed (or aborted)."""
        with self._cv:
            while self._pending:
                self._cv.wait()

    def stop(self) -> None:
        """Drain, then end the writer thread — without this the parked
        queue.get() would pin the thread (and whatever the subclass
        references) for the life of the process."""
        self.drain()
        with self._cv:
            t = self._thread
        if t is None or not t.is_alive():
            return
        self._q.put(self._STOP)
        t.join(timeout=5.0)


class _AsyncSpillWriter(AsyncBatchWriter):
    """Double-buffered host->disk eviction (mirrors PR 1's upload
    pipeline, inverted): victims are catalog entries; processing is
    the same serialize+compress+commit as the inline spill path."""

    def __init__(self, catalog: "BufferCatalog", depth: int = 2):
        super().__init__(
            lockorder.make_condition("memory.catalog.spillWriter"),
            "srt-spill-writer", depth)
        self._catalog = catalog

    def _process(self, entry: "_Entry") -> None:
        self._catalog._finish_async_spill(entry)

    def _on_error(self, entry: "_Entry", exc: BaseException) -> None:
        log.exception("async host->disk spill of buffer %d failed; "
                      "entry stays on the host tier", entry.buffer_id)


class BufferCatalog:
    """id→buffer map + spill orchestration across the three tiers."""

    def __init__(self, device_budget: Optional[int] = None,
                 host_budget: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 disk_codec: str = "lz4",
                 async_spill: bool = False):
        self.disk_codec = disk_codec
        # host->disk eviction path: async (double-buffered writer
        # thread, compute overlaps the compressed write) or inline.
        # Default inline: unit tests and short-lived catalogs want
        # deterministic tier transitions; runtime.initialize flips it
        # on from rapids.tpu.memory.spill.asyncWrite.enabled.
        self.async_spill = async_spill
        self._writer: Optional[_AsyncSpillWriter] = None
        self._spilling_bytes = 0  # submitted to the writer, uncommitted
        self._lock = lockorder.make_rlock("memory.catalog.state")
        self._entries: Dict[int, _Entry] = {}
        self._ids = itertools.count(1)
        self._seq = itertools.count()
        self.device_budget = device_budget
        self.host_budget = host_budget
        self._spill_dir = spill_dir
        self._device_bytes = 0
        self._host_bytes = 0
        # per-tier spill-victim queues keyed by (priority, seq): O(log n)
        # victim selection instead of full scans (HashedPriorityQueue.java
        # analogue). Entries are queued only while refcount == 0.
        self._queues = {t: HashedPriorityQueue() for t in StorageTier}
        # owner tag -> live entries: the query service biases/removes a
        # query's buffers once per stage slice, which must not scan the
        # whole catalog
        self._owners: Dict[object, set] = {}
        # sticky per-owner bias: set_owner_bias applies to entries the
        # owner registers LATER too (an out-of-core query keeps its
        # eager-spill bias for its whole life, not just for buffers
        # that existed when the scheduler set it)
        self._owner_bias: Dict[object, int] = {}
        self.spilled_device_bytes = 0  # task-metric accounting
        self.spilled_host_bytes = 0

    # -- registration / lifecycle ----------------------------------------

    def register(self, batch: ColumnarBatch, priority: int) -> int:
        """Add a device batch under catalog management; returns its id.
        (RapidsDeviceMemoryStore.addTable analogue.)"""
        size = batch.device_memory_size()
        with self._lock:
            bid = next(self._ids)
            e = _Entry(bid, priority, batch, size, next(self._seq),
                       owner=current_buffer_owner())
            self._entries[bid] = e
            if e.owner is not None:
                self._owners.setdefault(e.owner, set()).add(e)
                e.bias = self._owner_bias.get(e.owner, 0)
            self._device_bytes += size
            self._queues[StorageTier.DEVICE].push(e, e.spill_key())
        self._maybe_spill_async()
        return bid

    def acquire(self, buffer_id: int) -> ColumnarBatch:
        """Ref-count acquire; unspills to device if needed
        (RapidsBufferCatalog.acquireBuffer, RapidsBufferCatalog.scala:44-55).
        The buffer cannot spill while refcount > 0."""
        with self._lock:
            e = self._entries.get(buffer_id)
            if e is None:
                raise KeyError(f"buffer {buffer_id} not in catalog")
            e.refcount += 1
            if e.refcount == 1:
                self._queues[e.tier].remove(e)  # pinned: not a victim
        try:
            return self._ensure_device(e)
        except BaseException:
            with self._lock:
                e.refcount -= 1
                if e.refcount == 0 and buffer_id in self._entries:
                    self._requeue(e)
            raise

    def release(self, buffer_id: int) -> None:
        path = None
        with self._lock:
            e = self._entries.get(buffer_id)
            if e is None:
                return
            e.refcount -= 1
            assert e.refcount >= 0
            if e.pending_remove and e.refcount == 0:
                self._entries.pop(buffer_id, None)
                self._drop_owner_index(e)
                self._drop_tier_bytes(e)
                path = e.disk_path
            elif e.refcount == 0:
                self._requeue(e)
        if path and os.path.exists(path):
            os.unlink(path)

    def remove(self, buffer_id: int) -> None:
        """Drop the buffer from all tiers (RapidsBufferCatalog.removeBuffer).
        If the buffer is currently acquired (e.g. mid-unspill), removal is
        deferred until the last release so concurrent acquirers don't lose
        the backing file under them."""
        with self._lock:
            e = self._entries.get(buffer_id)
            if e is None:
                return
            if e.refcount > 0:
                e.pending_remove = True
                return
            self._entries.pop(buffer_id, None)
            self._drop_owner_index(e)
            self._queues[e.tier].remove(e)
            self._drop_tier_bytes(e)
            path = e.disk_path
        if path and os.path.exists(path):
            os.unlink(path)

    def update_priority(self, buffer_id: int, priority: int) -> None:
        with self._lock:
            e = self._entries.get(buffer_id)
            if e is not None:
                e.priority = priority
                if e in self._queues[e.tier]:
                    self._queues[e.tier].update(e, e.spill_key())

    # -- per-owner control (query service hooks) --------------------------

    def _drop_owner_index(self, e: "_Entry") -> None:
        """Called under lock when an entry leaves ``_entries``."""
        if e.owner is not None:
            peers = self._owners.get(e.owner)
            if peers is not None:
                peers.discard(e)
                if not peers:
                    self._owners.pop(e.owner, None)

    def set_owner_bias(self, owner, bias: int) -> int:
        """Re-bias the spill priority of every buffer registered under
        ``owner`` (negative bias -> spills earlier). The stage scheduler
        demotes stalled queries' batches with this so memory pressure
        evicts the tenant that is NOT on the device. Returns the number
        of entries touched."""
        n = 0
        with self._lock:
            if bias:
                self._owner_bias[owner] = bias
            else:
                self._owner_bias.pop(owner, None)
            for e in self._owners.get(owner, ()):
                if e.bias == bias:
                    continue
                e.bias = bias
                if e in self._queues[e.tier]:
                    self._queues[e.tier].update(e, e.spill_key())
                n += 1
        return n

    def owner_refcounts(self, owner) -> Dict[int, int]:
        """{buffer_id: refcount} of live entries registered under
        ``owner`` — the leak probe cancel/deadline tests assert on."""
        with self._lock:
            return {e.buffer_id: e.refcount
                    for e in self._owners.get(owner, ())}

    def owner_bytes(self, owner) -> int:
        with self._lock:
            return sum(e.size for e in self._owners.get(owner, ()))

    def remove_owner(self, owner) -> int:
        """Drop every buffer registered under ``owner`` from all tiers
        (deferred for entries currently acquired, like remove()). The
        query service's cancel/deadline cleanup: an abandoned exec tree
        must not leak its staged shuffle/broadcast batches."""
        with self._lock:
            ids = [e.buffer_id for e in self._owners.get(owner, ())]
            self._owner_bias.pop(owner, None)
        for bid in ids:
            self.remove(bid)
        return len(ids)

    # -- introspection ----------------------------------------------------

    def tier_of(self, buffer_id: int) -> StorageTier:
        with self._lock:
            return self._entries[buffer_id].tier

    def size_of(self, buffer_id: int) -> int:
        with self._lock:
            return self._entries[buffer_id].size

    @property
    def device_bytes(self) -> int:
        return self._device_bytes

    @property
    def host_bytes(self) -> int:
        return self._host_bytes

    def __contains__(self, buffer_id: int) -> bool:
        with self._lock:
            return buffer_id in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- spill machinery --------------------------------------------------

    def synchronous_spill(self, target_device_bytes: int) -> int:
        """Spill device buffers (lowest priority first, FIFO within equal
        priority) until tracked device bytes <= target. Returns bytes
        spilled. (RapidsBufferStore.synchronousSpill analogue.)"""
        spilled = 0
        while True:
            with self._lock:
                if self._device_bytes <= target_device_bytes:
                    return spilled
                victim = self._pick_spill_victim(StorageTier.DEVICE)
                if victim is None:
                    return spilled  # everything pinned
            spilled += self._spill_device_entry(victim)

    def spill_host_to_disk(self, target_host_bytes: int) -> int:
        if self.async_spill:
            return self._spill_host_to_disk_async(target_host_bytes)
        spilled = 0
        while True:
            with self._lock:
                if self._host_bytes <= target_host_bytes:
                    return spilled
                victim = self._pick_spill_victim(StorageTier.HOST)
                if victim is None:
                    return spilled
            spilled += self._spill_host_entry(victim)

    def _spill_host_to_disk_async(self, target_host_bytes: int) -> int:
        """Hand victims to the writer thread until host bytes MINUS the
        in-flight submissions reach the target, then return — the
        compressed writes land while the caller computes. Returns bytes
        submitted (an upper bound on bytes that will commit; a raced
        acquire can still rescue a victim)."""
        submitted = 0
        while True:
            with self._lock:
                if self._host_bytes - self._spilling_bytes \
                        <= target_host_bytes:
                    return submitted
                victim = self._pick_spill_victim(StorageTier.HOST)
                if victim is None:
                    return submitted
                self._spilling_bytes += victim.size
                if self._writer is None:
                    self._writer = _AsyncSpillWriter(self)
                writer = self._writer
            writer.submit(victim)
            submitted += victim.size

    def _finish_async_spill(self, e: "_Entry") -> None:
        """Writer-thread body: the same serialize+compress+commit as
        the inline path, then retire the in-flight accounting. A lost
        race (acquire/remove rescued the entry) leaves it at its
        current tier; if it is still an unpinned host victim it gets
        requeued by the release path as usual."""
        try:
            self._spill_host_entry(e)
        finally:
            with self._lock:
                self._spilling_bytes -= e.size

    def flush_spills(self) -> None:
        """Barrier for the async eviction pipeline: returns when every
        submitted host->disk write committed. Tests and shutdown paths
        use it; the hot path never waits here."""
        with self._lock:
            writer = self._writer
        if writer is not None:
            writer.drain()

    def close(self) -> None:
        """Quiesce the catalog's background machinery: drain pending
        disk writes and END the writer thread. A catalog being retired
        (runtime shutdown, test teardown) must not leave a parked
        daemon thread pinning it in memory; the catalog stays usable —
        a later spill lazily restarts the writer."""
        with self._lock:
            writer, self._writer = self._writer, None
        if writer is not None:
            writer.stop()

    def spill_all_device(self) -> int:
        return self.synchronous_spill(0)

    def _pick_spill_victim(self, tier: StorageTier) -> Optional[_Entry]:
        """Called under lock. Min (priority, seq) unpinned entry in
        tier — POPPED from its queue; the spill paths (or the release
        path after a raced acquire) requeue it at its landing tier."""
        return self._queues[tier].pop()

    def _requeue(self, e: _Entry) -> None:
        """Called under lock with refcount == 0: (re-)expose the entry
        as a spill victim at its current tier."""
        q = self._queues[e.tier]
        if e not in q:
            q.push(e, e.spill_key())

    def _spill_device_entry(self, e: _Entry) -> int:
        batch = e.device_batch
        if batch is None:
            return 0
        hb = serde.to_host_batch(batch)  # D2H outside lock
        with self._lock:
            if e.buffer_id not in self._entries or \
                    e.tier is not StorageTier.DEVICE or e.refcount > 0:
                return 0  # raced with remove/acquire
            e.host_batch = hb
            e.device_batch = None
            e.tier = StorageTier.HOST
            self._device_bytes -= e.size
            self._host_bytes += e.size
            self.spilled_device_bytes += e.size
            self._requeue(e)  # now a host-tier victim
        # host store may itself now exceed budget → cascade to disk
        if self.host_budget is not None:
            self.spill_host_to_disk(self.host_budget)
        return e.size

    def _spill_host_entry(self, e: _Entry) -> int:
        with self._lock:
            hb = e.host_batch
            if e.buffer_id not in self._entries or \
                    e.tier is not StorageTier.HOST or hb is None or \
                    e.refcount > 0:
                return 0
        from spark_rapids_tpu.columnar import compression

        data = compression.wrap(serde.serialize_host_batch(hb),
                                self.disk_codec)
        path = os.path.join(self._ensure_spill_dir(),
                            f"spill-{e.buffer_id}.srt")
        with open(path, "wb") as f:
            f.write(data)
        with self._lock:
            if e.buffer_id not in self._entries or \
                    e.tier is not StorageTier.HOST or e.refcount > 0:
                # lost the race; never unlink a path another spill committed
                if e.disk_path != path:
                    os.unlink(path)
                return 0
            e.disk_path = path
            e.host_batch = None
            e.tier = StorageTier.DISK
            self._host_bytes -= e.size
            self.spilled_host_bytes += e.size
            self._requeue(e)  # disk entries stay tracked (removal)
        return e.size

    def _ensure_device(self, e: _Entry) -> ColumnarBatch:
        """Unspill up the chain if needed; caller holds a refcount."""
        with self._lock:
            if e.tier is StorageTier.DEVICE:
                return e.device_batch
            hb = e.host_batch
            path = e.disk_path
            tier = e.tier
        if tier is StorageTier.DISK:
            from spark_rapids_tpu.columnar import compression

            try:
                with open(path, "rb") as f:
                    hb = serde.deserialize_host_batch(
                        compression.unwrap(f.read()))
            except Exception as exc:
                # a truncated/bit-flipped spill file must fail loudly
                # here, not surface as garbage rows in a kernel
                raise SpillCorruptionError(
                    f"disk spill for buffer {e.buffer_id} at {path} "
                    f"is unreadable: {exc}") from exc
        batch = serde.to_device_batch(hb)
        with self._lock:
            if e.buffer_id not in self._entries:
                return batch  # removed mid-unspill: hand back untracked
            if e.tier is not StorageTier.DEVICE:
                if e.tier is StorageTier.HOST:
                    self._host_bytes -= e.size
                e.device_batch = batch
                e.host_batch = None
                e.tier = StorageTier.DEVICE
                self._device_bytes += e.size
            return e.device_batch

    def _drop_tier_bytes(self, e: _Entry) -> None:
        if e.tier is StorageTier.DEVICE:
            self._device_bytes -= e.size
        elif e.tier is StorageTier.HOST:
            self._host_bytes -= e.size

    def _maybe_spill_async(self) -> None:
        """Budget enforcement on register: spill synchronously if over.
        (The reference spills from the RMM alloc-failed callback; we spill
        eagerly at the logical budget since XLA gives no callback.)"""
        if self.device_budget is not None and \
                self._device_bytes > self.device_budget:
            self.synchronous_spill(self.device_budget)

    def _ensure_spill_dir(self) -> str:
        if self._spill_dir is None:
            self._spill_dir = tempfile.mkdtemp(prefix="srt-spill-")
        else:
            os.makedirs(self._spill_dir, exist_ok=True)
        return self._spill_dir


_global_catalog: Optional[BufferCatalog] = None
_global_lock = lockorder.make_lock("memory.catalog.global")


def get_catalog() -> BufferCatalog:
    """Singleton catalog (RapidsBufferCatalog.init semantics,
    RapidsBufferCatalog.scala:128-142); configured lazily from RapidsConf
    at first use by the engine session."""
    global _global_catalog
    with _global_lock:
        if _global_catalog is None:
            _global_catalog = BufferCatalog()
        return _global_catalog


def reset_catalog(catalog: Optional[BufferCatalog] = None) -> BufferCatalog:
    global _global_catalog
    with _global_lock:
        _global_catalog = catalog if catalog is not None else BufferCatalog()
        return _global_catalog
