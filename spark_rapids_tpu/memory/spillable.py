"""SpillableBatch: hold a batch logically while letting it spill physically.

Analogue of SpillableColumnarBatch (SpillableColumnarBatch.scala:165): an
operator registers a batch it is not actively computing on, keeps a handle,
and re-acquires (possibly unspilling) when needed. Used by the coalesce
iterator's accumulation list, join build sides, and the shuffle write cache.
"""
from __future__ import annotations

from typing import Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.memory.catalog import BufferCatalog, get_catalog


class SpillableBatch:
    """Context-manager-friendly handle over a catalog-registered batch."""

    def __init__(self, batch: ColumnarBatch, priority: int,
                 catalog: Optional[BufferCatalog] = None,
                 defer_count: bool = False):
        # explicit None-check: BufferCatalog defines __len__, so an EMPTY
        # catalog is falsy and `catalog or get_catalog()` would silently
        # route buffers to the global catalog
        self._catalog = catalog if catalog is not None else get_catalog()
        # row count: realized up front by default (host metadata must
        # survive tier changes — the reference stores it in TableMeta).
        # ``defer_count`` keeps only the 0-d device scalar instead: no
        # host sync on the register path; consumers that truly need the
        # int pay it via the property (and a device->host spill realizes
        # it anyway inside its own sync, serde.batch_to_host)
        if defer_count:
            nr = batch.num_rows
            self._rows: Optional[int] = nr if isinstance(nr, int) \
                else None
            self._rows_dev = None if isinstance(nr, int) else nr
        else:
            self._rows = batch.realized_num_rows()
            self._rows_dev = None
        self._size = batch.device_memory_size()
        self._id = self._catalog.register(batch, priority)
        self._closed = False

    @property
    def num_rows(self) -> int:
        if self._rows is None:
            import jax

            self._rows = int(jax.device_get(self._rows_dev))
            self._rows_dev = None
        return self._rows

    @staticmethod
    def realize_counts(handles: "list[SpillableBatch]") -> None:
        """Realize MANY deferred counts in ONE device_get (each lazy
        ``num_rows`` access would otherwise pay a full round trip)."""
        import jax

        lazy = [sb for sb in handles if sb._rows is None]
        if not lazy:
            return
        vals = jax.device_get([sb._rows_dev for sb in lazy])
        for sb, v in zip(lazy, vals):
            sb._rows = int(v)
            sb._rows_dev = None

    @property
    def buffer_id(self) -> int:
        return self._id

    def device_memory_size(self) -> int:
        return self._size

    def get_batch(self) -> ColumnarBatch:
        """Acquire the batch on device. Caller must call ``release()`` (or
        use ``with spillable.acquired() as b:``) when done computing."""
        return self._catalog.acquire(self._id)

    def release(self) -> None:
        self._catalog.release(self._id)

    def acquired(self):
        return _Acquired(self)

    def update_priority(self, priority: int) -> None:
        self._catalog.update_priority(self._id, priority)

    def close(self) -> None:
        if not self._closed:
            self._catalog.remove(self._id)
            self._closed = True

    def __enter__(self) -> "SpillableBatch":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _Acquired:
    __slots__ = ("_sb", "_batch")

    def __init__(self, sb: SpillableBatch):
        self._sb = sb

    def __enter__(self) -> ColumnarBatch:
        self._batch = self._sb.get_batch()
        return self._batch

    def __exit__(self, *exc) -> None:
        self._sb.release()
