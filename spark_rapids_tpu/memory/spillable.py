"""SpillableBatch: hold a batch logically while letting it spill physically.

Analogue of SpillableColumnarBatch (SpillableColumnarBatch.scala:165): an
operator registers a batch it is not actively computing on, keeps a handle,
and re-acquires (possibly unspilling) when needed. Used by the coalesce
iterator's accumulation list, join build sides, and the shuffle write cache.
"""
from __future__ import annotations

from typing import Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.memory.catalog import BufferCatalog, get_catalog


class SpillableBatch:
    """Context-manager-friendly handle over a catalog-registered batch."""

    def __init__(self, batch: ColumnarBatch, priority: int,
                 catalog: Optional[BufferCatalog] = None):
        # explicit None-check: BufferCatalog defines __len__, so an EMPTY
        # catalog is falsy and `catalog or get_catalog()` would silently
        # route buffers to the global catalog
        self._catalog = catalog if catalog is not None else get_catalog()
        # realize the row count before the batch can spill: host metadata
        # must survive tier changes (the reference stores it in TableMeta)
        self.num_rows = batch.realized_num_rows()
        self._size = batch.device_memory_size()
        self._id = self._catalog.register(batch, priority)
        self._closed = False

    @property
    def buffer_id(self) -> int:
        return self._id

    def device_memory_size(self) -> int:
        return self._size

    def get_batch(self) -> ColumnarBatch:
        """Acquire the batch on device. Caller must call ``release()`` (or
        use ``with spillable.acquired() as b:``) when done computing."""
        return self._catalog.acquire(self._id)

    def release(self) -> None:
        self._catalog.release(self._id)

    def acquired(self):
        return _Acquired(self)

    def update_priority(self, priority: int) -> None:
        self._catalog.update_priority(self._id, priority)

    def close(self) -> None:
        if not self._closed:
            self._catalog.remove(self._id)
            self._closed = True

    def __enter__(self) -> "SpillableBatch":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _Acquired:
    __slots__ = ("_sb", "_batch")

    def __init__(self, sb: SpillableBatch):
        self._sb = sb

    def __enter__(self) -> ColumnarBatch:
        self._batch = self._sb.get_batch()
        return self._batch

    def __exit__(self, *exc) -> None:
        self._sb.release()
