"""Hashed priority queue: O(log n) push/pop, O(1) membership, O(log n)
arbitrary removal and priority update (the reference ships a dedicated
HashedPriorityQueue.java for exactly this — spill victim selection must
not degrade to linear scans as buffer counts grow).

Min-heap over (priority, seq) with an index map entry -> heap slot,
maintained through sift operations."""
from __future__ import annotations

from typing import Dict, Generic, List, Optional, Tuple, TypeVar

T = TypeVar("T")


class HashedPriorityQueue(Generic[T]):
    def __init__(self):
        self._heap: List[Tuple[Tuple, T]] = []
        self._pos: Dict[T, int] = {}

    def __len__(self) -> int:
        return len(self._heap)

    def __contains__(self, item: T) -> bool:
        return item in self._pos

    def push(self, item: T, key: Tuple) -> None:
        assert item not in self._pos, f"{item} already queued"
        self._heap.append((key, item))
        self._pos[item] = len(self._heap) - 1
        self._sift_up(len(self._heap) - 1)

    def peek(self) -> Optional[T]:
        return self._heap[0][1] if self._heap else None

    def pop(self) -> Optional[T]:
        if not self._heap:
            return None
        item = self._heap[0][1]
        self._remove_at(0)
        return item

    def remove(self, item: T) -> bool:
        i = self._pos.get(item)
        if i is None:
            return False
        self._remove_at(i)
        return True

    def update(self, item: T, key: Tuple) -> None:
        i = self._pos.get(item)
        if i is None:
            self.push(item, key)
            return
        old = self._heap[i][0]
        self._heap[i] = (key, item)
        if key < old:
            self._sift_up(i)
        else:
            self._sift_down(i)

    # -- internals --------------------------------------------------------

    def _remove_at(self, i: int) -> None:
        last = len(self._heap) - 1
        item = self._heap[i][1]
        if i != last:
            self._swap(i, last)
        self._heap.pop()
        del self._pos[item]
        if i <= last - 1 and i < len(self._heap):
            self._sift_up(i)
            self._sift_down(i)

    def _swap(self, a: int, b: int) -> None:
        self._heap[a], self._heap[b] = self._heap[b], self._heap[a]
        self._pos[self._heap[a][1]] = a
        self._pos[self._heap[b][1]] = b

    def _sift_up(self, i: int) -> None:
        while i > 0:
            parent = (i - 1) // 2
            if self._heap[i][0] < self._heap[parent][0]:
                self._swap(i, parent)
                i = parent
            else:
                return

    def _sift_down(self, i: int) -> None:
        n = len(self._heap)
        while True:
            best = i
            for c in (2 * i + 1, 2 * i + 2):
                if c < n and self._heap[c][0] < self._heap[best][0]:
                    best = c
            if best == i:
                return
            self._swap(i, best)
            i = best
