"""TpuSemaphore: per-chip task admission control.

Analogue of GpuSemaphore (GpuSemaphore.scala:27-161): a counting semaphore
bounding how many concurrent tasks may hold device memory on one chip
(rapids.tpu.sql.concurrentTpuTasks; the reference defaults to 2 to
oversubscribe and hide host I/O, RapidsConf.scala:340-347). Reentrant per
task: a task that already holds a permit doesn't double-acquire
(GpuSemaphore.scala:106-130), and completion releases it.
"""
from __future__ import annotations

import threading
from spark_rapids_tpu.utils import lockorder
from typing import Optional, Set


class TpuSemaphore:
    def __init__(self, max_concurrent: int = 2):
        if max_concurrent <= 0:
            raise ValueError("max_concurrent must be positive")
        self._max = max_concurrent
        self._permits = max_concurrent
        # membership check and permit decrement happen atomically under one
        # condition variable, so racing threads of the same task consume one
        # permit total (the reference keeps per-task TaskInfo for the same
        # reason, GpuSemaphore.scala:106-130)
        self._holders: Set[int] = set()
        self._cv = lockorder.make_condition("memory.semaphore")
        self._tls = threading.local()

    def acquire_if_necessary(self, task_id: Optional[int] = None) -> bool:
        """Blocking acquire unless this task already holds a permit
        (GpuSemaphore.acquireIfNecessary). Returns True iff THIS call took
        the permit (the caller that gets True owns the matching release)."""
        tid = task_id if task_id is not None else threading.get_ident()
        with self._cv:
            while True:
                if tid in self._holders:
                    return False
                if self._permits > 0:
                    self._permits -= 1
                    self._holders.add(tid)
                    return True
                self._cv.wait()

    def release_if_necessary(self, task_id: Optional[int] = None) -> None:
        tid = task_id if task_id is not None else threading.get_ident()
        with self._cv:
            if tid in self._holders:
                self._holders.discard(tid)
                self._permits += 1
                self._cv.notify_all()

    def available(self) -> int:
        """Permits not currently held (query-service admission consults
        this; it never reserves — the blocking acquire at device entry
        is the true bound, so the read being racy is harmless)."""
        with self._cv:
            return self._permits

    @property
    def max_permits(self) -> int:
        return self._max

    def holds(self, task_id: Optional[int] = None) -> bool:
        tid = task_id if task_id is not None else threading.get_ident()
        with self._cv:
            return tid in self._holders

    def __enter__(self) -> "TpuSemaphore":
        # nested `with sem:` on the same task must not release the permit
        # the outer scope still relies on — remember per-thread whether this
        # particular enter acquired
        if not hasattr(self._tls, "stack"):
            self._tls.stack = []
        self._tls.stack.append(self.acquire_if_necessary())
        return self

    def __exit__(self, *exc) -> None:
        acquired = self._tls.stack.pop() if getattr(self._tls, "stack", None) \
            else True
        if acquired:
            self.release_if_necessary()


_instance: Optional[TpuSemaphore] = None
_instance_lock = lockorder.make_lock("memory.semaphore.instance")


def initialize(max_concurrent: int) -> TpuSemaphore:
    """Executor-init-time setup (Plugin.scala:138)."""
    global _instance
    with _instance_lock:
        _instance = TpuSemaphore(max_concurrent)
        return _instance


def get() -> TpuSemaphore:
    global _instance
    with _instance_lock:
        if _instance is None:
            _instance = TpuSemaphore()
        return _instance
