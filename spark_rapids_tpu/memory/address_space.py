"""Address-space sub-allocator (AddressSpaceAllocator.scala analogue):
carves variable-length blocks out of ONE registered root buffer — the
reference uses it to hand out bounce buffers from a single
RDMA-registered allocation. First-fit with free-block coalescing."""
from __future__ import annotations

import threading
from spark_rapids_tpu.utils import lockorder
from typing import Dict, List, Optional, Tuple


class AddressSpaceAllocator:
    def __init__(self, size: int):
        assert size > 0
        self.size = size
        self._lock = lockorder.make_lock("memory.addressSpace")
        self._free: List[Tuple[int, int]] = [(0, size)]  # (offset, len)
        self._allocated: Dict[int, int] = {}             # offset -> len

    def allocate(self, length: int) -> Optional[int]:
        """Returns the block's offset, or None when fragmented/full."""
        if length <= 0:
            raise ValueError("length must be positive")
        with self._lock:
            for i, (off, flen) in enumerate(self._free):
                if flen >= length:
                    if flen == length:
                        self._free.pop(i)
                    else:
                        self._free[i] = (off + length, flen - length)
                    self._allocated[off] = length
                    return off
            return None

    def free(self, offset: int) -> None:
        with self._lock:
            length = self._allocated.pop(offset, None)
            if length is None:
                raise KeyError(f"offset {offset} not allocated")
            self._free.append((offset, length))
            self._free.sort()
            # coalesce adjacent free blocks
            merged: List[Tuple[int, int]] = []
            for off, flen in self._free:
                if merged and merged[-1][0] + merged[-1][1] == off:
                    merged[-1] = (merged[-1][0], merged[-1][1] + flen)
                else:
                    merged.append((off, flen))
            self._free = merged

    @property
    def allocated_bytes(self) -> int:
        with self._lock:
            return sum(self._allocated.values())

    @property
    def available_bytes(self) -> int:
        with self._lock:
            return sum(flen for _, flen in self._free)

    @property
    def largest_free_block(self) -> int:
        with self._lock:
            return max((flen for _, flen in self._free), default=0)
