"""File I/O: TPU-accelerated scans and writes (SURVEY.md §2.7).

The reference splits file work: CPU parses footers / filters row groups /
assembles host buffers, then cuDF decodes on device (GpuParquetScan.scala:
228-265). TPUs have no device-side decoders, so the TPU-native split is:
host decode (pyarrow, multi-threaded across files — the MultiFileParquet
PartitionReader analogue) -> columnar host buffers -> device upload, with
the same row-group pruning / predicate pushdown / column projection on the
metadata path.
"""
from spark_rapids_tpu.io import scanpipe
from spark_rapids_tpu.io.csv import CsvSource
from spark_rapids_tpu.io.orc import OrcSource
from spark_rapids_tpu.io.parquet import ParquetSource
from spark_rapids_tpu.io.write import WriteFilesNode

__all__ = ["ParquetSource", "OrcSource", "CsvSource", "WriteFilesNode",
           "scanpipe"]
