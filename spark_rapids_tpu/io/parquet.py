"""Parquet scan: footer metadata pruning + threaded host decode + upload.

Reference flow (GpuParquetScan.scala): CPU parses the footer, filters row
groups by predicate/statistics (:228-265), assembles the needed column
chunks, then decodes on device; many small files are read by a thread pool
and stitched into one batch (MultiFileParquetPartitionReader, :700-839).
TPU-native flow: identical metadata path (pyarrow footer statistics), host
decode, device upload in the scan exec. Splits are row-group ranges packed
to the reader byte target, so scan partitions parallelize over row groups.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.io import arrow_conv
from spark_rapids_tpu.io.filesrc import (FileSourceBase, Filter,
                                         filter_may_match)


@dataclasses.dataclass(frozen=True)
class _RgSplit:
    path: str
    row_groups: tuple  # row-group ordinals within the file
    # ((col, lo, hi), ...) aggregated over this split's row groups for
    # columns where EVERY row group published min/max — free Column.stats
    # for the packed-key groupby path (no upload-time host pass)
    stats: tuple = ()
    # on-disk (compressed, projected-columns) bytes this split reads —
    # the bytes_read side of pruning telemetry
    nbytes: int = 0


def _stat_value(typ: dt.DType, v):
    """Normalize a parquet footer statistic to the engine's physical
    encoding so it compares against pushdown literals."""
    if v is None:
        return None
    if typ is dt.DATE:
        import datetime

        if isinstance(v, datetime.date):
            return (v - datetime.date(1970, 1, 1)).days
        return v
    if typ is dt.TIMESTAMP:
        import datetime

        if isinstance(v, datetime.datetime):
            if v.tzinfo is None:
                v = v.replace(tzinfo=datetime.timezone.utc)
            return int(v.timestamp() * 1_000_000)
        return v
    return v


def _merge_rg_stats(per_rg: List[dict], types) -> tuple:
    """Aggregate per-row-group (min, max) into split-level stats; a
    column qualifies only when EVERY row group in the split published
    min/max for it. Integral/date/timestamp columns only (the packed-key
    consumers)."""
    if not per_rg:
        return ()
    out = []
    for cname, typ in types.items():
        if not (typ.is_integral or typ in (dt.DATE, dt.TIMESTAMP)):
            continue
        vals = [rg.get(cname) for rg in per_rg]
        if any(v is None or v[0] is None or v[1] is None for v in vals):
            continue
        out.append((cname, int(min(v[0] for v in vals)),
                    int(max(v[1] for v in vals))))
    return tuple(out)


_FOOTER_ROWS: dict = {}


def _footer_row_count(path: str) -> int:
    """num_rows from the footer, cached by (path, mtime, size)."""
    import os

    import pyarrow.parquet as pq

    st = os.stat(path)
    key = (path, st.st_mtime_ns, st.st_size)
    n = _FOOTER_ROWS.get(key)
    if n is None:
        if len(_FOOTER_ROWS) >= 4096:
            _FOOTER_ROWS.clear()
        n = pq.read_metadata(path).num_rows
        _FOOTER_ROWS[key] = n
    return n


class ParquetSource(FileSourceBase):
    """Columnar parquet reader with row-group statistics pruning."""

    def __init__(self, paths, columns: Optional[List[str]] = None,
                 filters: Optional[Sequence[Filter]] = None,
                 conf: Optional[cfg.RapidsConf] = None):
        super().__init__(paths, columns, filters, conf)

    def _file_schema(self) -> Schema:
        import pyarrow.parquet as pq

        return arrow_conv.schema_from_arrow(
            pq.read_schema(self.paths[0]), self.columns)

    def estimated_row_count(self):
        """Footer num_rows across files (pre-pruning): the plan-time
        size signal for greedy join reordering — footer metadata only,
        no data read (the reference gets this from Spark's relation
        statistics upstream). Counts cache per path PROCESS-wide:
        every fresh plan over the same files (the benchmark loop's
        plan-per-iteration) must not re-open every footer."""
        if self._est_rows is None:
            try:
                self._est_rows = sum(_footer_row_count(p)
                                     for p in self.paths)
            except Exception:  # pragma: no cover - corrupt footer
                self._est_rows = -1
        return None if self._est_rows < 0 else self._est_rows

    def _build_splits(self) -> list:
        import pyarrow.parquet as pq

        from spark_rapids_tpu.io import scanpipe

        schema = self.schema()
        types = dict(zip(schema.names, schema.types))
        # dual split targets: the reader batch target bounds the
        # UNCOMPRESSED bytes one host read materializes; maxPartitionBytes
        # bounds the ON-DISK bytes one partition covers, so a single
        # file bigger than it still splits on row-group boundaries and
        # parallelizes like many small files
        target = self.conf.get(cfg.MAX_READER_BATCH_SIZE_BYTES)
        disk_target = self.conf.get(cfg.SCAN_MAX_PARTITION_BYTES)
        prune = self._pruning_enabled()
        splits: List[_RgSplit] = []
        for path in self.paths:
            meta = pq.ParquetFile(path).metadata
            name_to_col = {meta.schema.column(i).name: i
                           for i in range(meta.num_columns)}
            proj_cols = [name_to_col[c] for c in types
                         if c in name_to_col]
            kept: List[int] = []
            kept_stats: List[dict] = []
            kept_bytes = 0
            kept_disk = 0

            def emit(kept, kept_stats, kept_disk):
                splits.append(_RgSplit(
                    path, tuple(kept),
                    _merge_rg_stats(kept_stats, types),
                    int(kept_disk)))

            for rg in range(meta.num_row_groups):
                self.chunks_total += 1
                rgmeta = meta.row_group(rg)
                # on-disk cost of this row group = compressed extent of
                # the PROJECTED columns only (pyarrow reads only those)
                rg_disk = sum(rgmeta.column(ci).total_compressed_size
                              for ci in proj_cols)
                stats = {}
                for cname, typ in types.items():
                    ci = name_to_col.get(cname)
                    if ci is None:
                        continue
                    st = rgmeta.column(ci).statistics
                    if st is None or not st.has_min_max:
                        continue
                    stats[cname] = (_stat_value(typ, st.min),
                                    _stat_value(typ, st.max),
                                    bool(st.null_count))
                if prune and not filter_may_match(self.filters, stats):
                    self.chunks_pruned += 1
                    scanpipe.record_pruned("parquet", 1, rg_disk)
                    continue
                rg_bytes = rgmeta.total_byte_size
                if kept and (kept_bytes + rg_bytes > target or
                             kept_disk + rg_disk > disk_target):
                    emit(kept, kept_stats, kept_disk)
                    kept, kept_stats = [], []
                    kept_bytes = kept_disk = 0
                kept.append(rg)
                kept_stats.append(stats)
                kept_bytes += rg_bytes
                kept_disk += rg_disk
            if kept:
                emit(kept, kept_stats, kept_disk)
        return splits

    # split_stats: FileSourceBase merges per-desc stats, incl. packed
    # multi-file partitions

    def _read_split(self, desc: _RgSplit):
        import pyarrow.parquet as pq

        self._maybe_debug_dump(desc.path)
        f = pq.ParquetFile(desc.path)
        schema = self.schema()
        return f.read_row_groups(list(desc.row_groups),
                                 columns=list(schema.names),
                                 use_threads=False)

    def _desc_chunks(self, desc: _RgSplit):
        """Row-group-granular streaming read: the scan pipeline gets
        its first chunk after ONE row group's decode latency instead of
        the whole split's, and never holds more than a chunk + the
        accumulator remainder on the host."""
        import pyarrow.parquet as pq

        self._maybe_debug_dump(desc.path)
        f = pq.ParquetFile(desc.path)
        schema = self.schema()
        names = list(schema.names)
        for rg in desc.row_groups:
            table = f.read_row_groups([rg], columns=names,
                                      use_threads=False)
            yield arrow_conv.table_to_host(table, schema)

    def _desc_nbytes(self, desc: _RgSplit) -> int:
        if desc.nbytes:
            return desc.nbytes
        return super()._desc_nbytes(desc)

    def split_origin(self, split: int):
        """(path, block_start, block_length) from the split's actual
        row-group byte extent — Spark's InputFileBlockStart/Length report
        the block, not the whole file (GpuInputFileBlock.scala)."""
        descs = self.splits()
        if not descs:
            return None
        desc: _RgSplit = descs[split]
        import pyarrow.parquet as pq

        try:
            meta = pq.ParquetFile(desc.path).metadata
            starts, lengths = [], 0
            for rg in desc.row_groups:
                rgm = meta.row_group(rg)
                offs = []
                comp = 0
                for c in range(rgm.num_columns):
                    cm = rgm.column(c)
                    # file_offset is 0 from many writers; the first page
                    # offset (dictionary page if present) is the start
                    off = cm.dictionary_page_offset
                    if off is None or off <= 0:
                        off = cm.data_page_offset
                    offs.append(off)
                    # on-disk (compressed) extent — Spark's block
                    # semantics (the row-group meta only carries the
                    # uncompressed total)
                    comp += cm.total_compressed_size
                starts.append(min(offs))
                lengths += comp
            return (desc.path, int(min(starts)), int(lengths))
        except Exception:  # pragma: no cover - odd footers
            return super().split_origin(split)

    _dump_prefix_conf = cfg.PARQUET_DEBUG_DUMP_PREFIX
