"""pyarrow <-> host-columnar conversion shared by all file sources.

Host representation (what DataSource.read_host returns): numpy arrays in the
engine's physical encodings — int32 days for DATE, int64 UTC microseconds for
TIMESTAMP, object arrays (None = null) for STRING — plus bool validity masks.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema


def schema_from_arrow(arrow_schema, columns: Optional[List[str]] = None
                      ) -> Schema:
    names, types = [], []
    for field in arrow_schema:
        if columns is not None and field.name not in columns:
            continue
        names.append(field.name)
        types.append(dt.from_arrow(field.type))
    if columns is not None:
        order = {n: i for i, n in enumerate(names)}
        missing = [c for c in columns if c not in order]
        if missing:
            raise KeyError(f"columns not in file schema: {missing}")
        names = list(columns)
        types = [types[order[c]] for c in columns]
    return Schema(names, types)


def column_to_host(col, typ: dt.DType) -> Tuple[np.ndarray, np.ndarray]:
    """One arrow ChunkedArray/Array -> (data ndarray, validity ndarray)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    valid = pc.is_valid(col)
    valid = valid.to_numpy(zero_copy_only=False).astype(bool)
    if typ is dt.STRING:
        data = np.array(col.to_pylist(), dtype=object)
        return data, valid
    if typ is dt.DATE:
        ints = pc.fill_null(col.cast(pa.int32()), 0)
        return ints.to_numpy(zero_copy_only=False).astype(np.int32), valid
    if typ is dt.TIMESTAMP:
        # normalize to UTC microseconds (the engine is UTC-only, like the
        # reference: GpuOverrides.scala:341)
        ts = col
        if isinstance(ts, pa.ChunkedArray):
            ts = ts.combine_chunks()
        ts = ts.cast(pa.timestamp("us", tz="UTC")) \
            if ts.type.tz is not None else ts.cast(pa.timestamp("us"))
        ints = pc.fill_null(ts.cast(pa.int64()), 0)
        return ints.to_numpy(zero_copy_only=False).astype(np.int64), valid
    if typ is dt.BOOLEAN:
        filled = pc.fill_null(col, False)
        return (filled.to_numpy(zero_copy_only=False).astype(bool), valid)
    sentinel = 0
    filled = pc.fill_null(col, sentinel)
    arr = filled.to_numpy(zero_copy_only=False).astype(typ.np_dtype)
    return arr, valid


def table_to_host(table, schema: Schema
                  ) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
    data: Dict[str, np.ndarray] = {}
    validity: Dict[str, np.ndarray] = {}
    for name, typ in zip(schema.names, schema.types):
        col = table.column(name)
        if str(col.type).startswith("dictionary"):
            col = col.cast("string")
        data[name], validity[name] = column_to_host(col, typ)
    return data, validity


def empty_host(schema: Schema):
    data, validity = {}, {}
    for name, typ in zip(schema.names, schema.types):
        data[name] = np.array(
            [], dtype=object if typ is dt.STRING else typ.np_dtype)
        validity[name] = np.array([], dtype=bool)
    return data, validity


def concat_host(parts, schema: Schema):
    """Concatenate per-split host dicts in order."""
    parts = [p for p in parts if p is not None]
    if not parts:
        return empty_host(schema)
    data, validity = {}, {}
    for name in schema.names:
        data[name] = np.concatenate([p[0][name] for p in parts])
        validity[name] = np.concatenate([p[1][name] for p in parts])
    return data, validity


def batch_to_arrow(batch, schema: Schema):
    """Device ColumnarBatch -> pyarrow Table (the write path's device ->
    host handoff; ColumnarOutputWriter analogue)."""
    import pyarrow as pa

    n = batch.realized_num_rows()
    arrays = []
    for c, typ in zip(batch.columns, schema.types):
        data, valid = c.to_numpy(n)
        mask = None if valid is None else ~np.asarray(valid, dtype=bool)
        if typ is dt.STRING:
            vals = list(data)
            if mask is not None:
                vals = [None if m else v for v, m in zip(vals, mask)]
            arrays.append(pa.array(vals, type=pa.string()))
        elif typ is dt.DATE:
            arrays.append(pa.array(np.asarray(data, dtype=np.int32),
                                   mask=mask).cast(pa.date32()))
        elif typ is dt.TIMESTAMP:
            arrays.append(pa.array(np.asarray(data, dtype=np.int64),
                                   mask=mask).cast(
                pa.timestamp("us", tz="UTC")))
        else:
            arrays.append(pa.array(np.asarray(data, dtype=typ.np_dtype),
                                   mask=mask, type=dt.to_arrow(typ)))
    return pa.Table.from_arrays(arrays, names=list(schema.names))
