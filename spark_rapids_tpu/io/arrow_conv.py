"""pyarrow <-> host-columnar conversion shared by all file sources.

Host representation (what DataSource.read_host returns): numpy arrays in the
engine's physical encodings — int32 days for DATE, int64 UTC microseconds for
TIMESTAMP, object arrays (None = null) for STRING — plus bool validity masks.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.io.hoststrings import HostStrings


def schema_from_arrow(arrow_schema, columns: Optional[List[str]] = None
                      ) -> Schema:
    names, types = [], []
    for field in arrow_schema:
        if columns is not None and field.name not in columns:
            continue
        names.append(field.name)
        types.append(dt.from_arrow(field.type))
    if columns is not None:
        order = {n: i for i, n in enumerate(names)}
        missing = [c for c in columns if c not in order]
        if missing:
            raise KeyError(f"columns not in file schema: {missing}")
        names = list(columns)
        types = [types[order[c]] for c in columns]
    return Schema(names, types)


def column_to_host(col, typ: dt.DType) -> Tuple[np.ndarray, np.ndarray]:
    """One arrow ChunkedArray/Array -> (data ndarray, validity ndarray
    or None when the column has no nulls — skipping the is_valid pass,
    the fill_null pass, AND the validity upload)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    if col.null_count == 0:
        valid = None
    else:
        valid = pc.is_valid(col).to_numpy(
            zero_copy_only=False).astype(bool)
    if typ is dt.STRING:
        # stay dictionary-encoded end to end: arrow's C++ encode gives
        # codes + unique values; sort the (small) dictionary and remap
        # so code order == string order (StringColumn's invariant).
        # Only the dictionary ever becomes Python objects.
        if isinstance(col, pa.ChunkedArray):
            col = col.combine_chunks()
        if not pa.types.is_dictionary(col.type):
            col = pc.dictionary_encode(col)
        if col.dictionary.null_count > 0:
            # a null INSIDE the dictionary is legal arrow (never
            # produced by parquet dictionary pages); is_valid/null_count
            # above only see index-level nulls, so rows referencing the
            # null slot would otherwise surface as the literal string
            # 'None'. Fold them into the validity mask and repoint
            # their codes at slot 0.
            dict_valid = pc.is_valid(col.dictionary).to_numpy(
                zero_copy_only=False).astype(bool)
            idx0 = pc.fill_null(col.indices, 0).to_numpy(
                zero_copy_only=False).astype(np.int64, copy=False)
            row_hits_null = ~dict_valid[idx0]
            if valid is None:
                valid = np.ones(len(col), dtype=bool)
            valid = valid & ~row_hits_null
            col = pa.DictionaryArray.from_arrays(
                pa.array(np.where(row_hits_null, 0, idx0),
                         type=col.indices.type),
                pc.fill_null(col.dictionary, ""))
        idx = col.indices if valid is None else pc.fill_null(col.indices, 0)
        codes = idx.to_numpy(zero_copy_only=False).astype(
            np.int32, copy=False)
        dvals = col.dictionary.to_numpy(zero_copy_only=False)
        if len(dvals):
            ds = dvals.astype(str)
            order = np.argsort(ds, kind="stable")
            rank = np.empty(len(order), dtype=np.int32)
            rank[order] = np.arange(len(order), dtype=np.int32)
            codes = rank[codes]
            dictionary = np.asarray(ds[order], dtype=object)
        else:
            dictionary = np.array([], dtype=object)
        return HostStrings(codes, dictionary), valid
    if typ is dt.DATE:
        ints = col.cast(pa.int32())
        if valid is not None:
            ints = pc.fill_null(ints, 0)
        return ints.to_numpy(zero_copy_only=False).astype(
            np.int32, copy=False), valid
    if typ is dt.TIMESTAMP:
        # normalize to UTC microseconds (the engine is UTC-only, like the
        # reference: GpuOverrides.scala:341)
        ts = col
        if isinstance(ts, pa.ChunkedArray):
            ts = ts.combine_chunks()
        ts = ts.cast(pa.timestamp("us", tz="UTC")) \
            if ts.type.tz is not None else ts.cast(pa.timestamp("us"))
        ints = ts.cast(pa.int64())
        if valid is not None:
            ints = pc.fill_null(ints, 0)
        return ints.to_numpy(zero_copy_only=False).astype(
            np.int64, copy=False), valid
    if typ is dt.BOOLEAN:
        filled = col if valid is None else pc.fill_null(col, False)
        return (filled.to_numpy(zero_copy_only=False).astype(
            bool, copy=False), valid)
    filled = col if valid is None else pc.fill_null(col, 0)
    arr = filled.to_numpy(zero_copy_only=False).astype(
        typ.np_dtype, copy=False)
    return arr, valid


def table_to_host(table, schema: Schema
                  ) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
    data: Dict[str, np.ndarray] = {}
    validity: Dict[str, np.ndarray] = {}
    for name, typ in zip(schema.names, schema.types):
        col = table.column(name)
        if str(col.type).startswith("dictionary"):
            col = col.cast("string")
        data[name], validity[name] = column_to_host(col, typ)
    return data, validity


def empty_host(schema: Schema):
    data, validity = {}, {}
    for name, typ in zip(schema.names, schema.types):
        data[name] = np.array(
            [], dtype=object if typ is dt.STRING else typ.np_dtype)
        validity[name] = np.array([], dtype=bool)
    return data, validity


def concat_host(parts, schema: Schema):
    """Concatenate per-split host dicts in order."""
    parts = [p for p in parts if p is not None]
    if not parts:
        return empty_host(schema)
    data, validity = {}, {}
    for name in schema.names:
        vals = [p[0][name] for p in parts]
        if any(isinstance(v, HostStrings) for v in vals):
            data[name] = HostStrings.concat(
                [v if isinstance(v, HostStrings)
                 else HostStrings.from_objects(v) for v in vals])
        else:
            data[name] = np.concatenate(vals)
        vparts = [p[1][name] for p in parts]
        if all(v is None for v in vparts):
            validity[name] = None
        else:
            validity[name] = np.concatenate(
                [v if v is not None else np.ones(len(d), dtype=bool)
                 for v, d in zip(vparts, vals)])
    return data, validity


def batch_to_arrow(batch, schema: Schema):
    """Device ColumnarBatch -> pyarrow Table (the write path's device ->
    host handoff; ColumnarOutputWriter analogue)."""
    import pyarrow as pa

    n = batch.realized_num_rows()
    arrays = []
    for c, typ in zip(batch.columns, schema.types):
        data, valid = c.to_numpy(n)
        mask = None if valid is None else ~np.asarray(valid, dtype=bool)
        if typ is dt.STRING:
            vals = list(data)
            if mask is not None:
                vals = [None if m else v for v, m in zip(vals, mask)]
            arrays.append(pa.array(vals, type=pa.string()))
        elif typ is dt.DATE:
            arrays.append(pa.array(np.asarray(data, dtype=np.int32),
                                   mask=mask).cast(pa.date32()))
        elif typ is dt.TIMESTAMP:
            arrays.append(pa.array(np.asarray(data, dtype=np.int64),
                                   mask=mask).cast(
                pa.timestamp("us", tz="UTC")))
        else:
            arrays.append(pa.array(np.asarray(data, dtype=typ.np_dtype),
                                   mask=mask, type=dt.to_arrow(typ)))
    return pa.Table.from_arrays(arrays, names=list(schema.names))
