"""Out-of-core ingest: the bounded-depth async scan pipeline.

ScanExec's per-split read becomes a prefetching producer/consumer chain
(the multi-threaded reader architecture of GpuMultiFileReader.scala, run
per split instead of per file):

- **pruning decides before any byte moves**: the split layout the
  sources advertise (io/parquet.py row groups, io/orc.py stripes) is
  already pruned by footer statistics, and this module only accounts
  the on-disk bytes that survived vs. the bytes pruning skipped;
- **an IO thread pool** streams a split's chunks (row groups / stripes)
  off the filesystem and packs them into :class:`~.interop.PackedHost`
  parts — pure host work, off the task thread;
- **double-buffered upload**: the consumer issues slice ``k+1``'s
  ``device_put`` before yielding slice ``k`` (the PR 6/PR 19
  ``AsyncBatchWriter`` template run in reverse), so the 20-45 MB/s
  tunnel transfer hides behind the current batch's compute;
- **backpressure**: queued packed slices are bounded by
  ``rapids.tpu.io.scan.prefetch.depth`` and their host bytes charge the
  service admission budget (``admission_bytes``), so prefetch cannot
  silently overcommit memory the admission ledger thinks is free;
- **spillable landing** (``rapids.tpu.io.scan.landing.spillable``):
  scan results register as snapshot-versioned ``SpillableBatch``es in a
  scan cache keyed on the split identity + per-file ``(mtime_ns,
  size)`` — a re-scan of unchanged files hits warm device/host/disk
  tiers instead of the filesystem.

Slice boundaries are computed by a re-slicing accumulator and are
therefore IDENTICAL regardless of chunk granularity or prefetch depth —
``prefetch.depth=0`` (fully synchronous, no threads) is the
byte-identity reference path the ingest fence compares against, and
float aggregation order downstream never shifts with the pipeline
configuration.
"""
from __future__ import annotations

import os
import queue as _queue
import threading
import time
from typing import Optional

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.utils import lockorder
from spark_rapids_tpu.utils.tracing import TraceRange

# ---------------------------------------------------------------------------
# telemetry: the io.scan block (bytes read/pruned, decode/h2d seconds,
# overlap fraction) — same snapshot/delta idiom as utils/dispatch
# ---------------------------------------------------------------------------

_stats_lock = lockorder.make_lock("io.scanpipe.stats")

_counters = {
    "bytes_read": 0,          # on-disk bytes of chunks actually read
    "bytes_pruned": 0,        # on-disk bytes pruning skipped pre-read
    "chunks_read": 0,         # row groups / stripes / files read
    "chunks_pruned": 0,
    "splits_read": 0,
    "slices_uploaded": 0,
    "decode_s": 0.0,          # host read + pack seconds (both paths)
    "h2d_s": 0.0,             # device_put issue seconds
    "prefetch_busy_s": 0.0,   # producer-thread busy seconds (async only)
    "prefetch_wait_s": 0.0,   # consumer blocked on the queue (async only)
    "pushdown_filters": 0,    # conjuncts the planner planted on sources
    "cache_hits": 0,
    "cache_misses": 0,
}
#: {(format, reason): [chunks, bytes]} — sources that cannot prune
#: (CSV has no footer stats, ORC files may lack stripe statistics)
#: record WHY, so bytes-read accounting stays honest across formats.
_unprunable: dict = {}
_inflight_bytes = 0


def record_pruned(fmt: str, chunks: int, nbytes: int) -> None:
    """A source pruned ``chunks`` chunks (``nbytes`` on disk) by footer
    statistics before any read."""
    with _stats_lock:
        _counters["chunks_pruned"] += int(chunks)
        _counters["bytes_pruned"] += int(nbytes)


def record_unprunable(fmt: str, reason: str, chunks: int,
                      nbytes: int) -> None:
    """A source had pushed-down filters but no statistics to prune with
    — the explicit complement of ``record_pruned``."""
    with _stats_lock:
        ent = _unprunable.setdefault((fmt, reason), [0, 0])
        ent[0] += int(chunks)
        ent[1] += int(nbytes)


def record_pushdown(n: int) -> None:
    """The planner planted ``n`` pruning conjuncts on a file source."""
    with _stats_lock:
        _counters["pushdown_filters"] += int(n)


def _bump(**kw) -> None:
    with _stats_lock:
        for k, v in kw.items():
            _counters[k] += v


def _add_inflight(nbytes: int) -> None:
    global _inflight_bytes
    with _stats_lock:
        _inflight_bytes = max(_inflight_bytes + int(nbytes), 0)


def inflight_bytes() -> int:
    """Host bytes of packed slices queued but not yet uploaded."""
    with _stats_lock:
        return _inflight_bytes


def admission_bytes() -> int:
    """Bytes this subsystem holds that the admission ledger must see:
    queued prefetch slices (host) + device-resident scan-cache
    landings. The query service adds this to its ``extra_bytes_fn``."""
    return inflight_bytes() + cache_device_bytes()


def snapshot() -> dict:
    with _stats_lock:
        out = dict(_counters)
        out["unprunable"] = {f"{fmt}:{reason}": (c, b)
                             for (fmt, reason), (c, b)
                             in _unprunable.items()}
        return out


def delta(before: dict) -> dict:
    """The ``io.scan`` telemetry block accumulated since ``before`` (a
    ``snapshot()``): byte/chunk counts, decode vs h2d seconds, and the
    measured scan–compute overlap fraction — the share of producer
    (read+pack) seconds hidden behind consumer compute, ``None`` when
    no async scan ran in the window."""
    now = snapshot()
    d = {k: round(now[k] - before.get(k, 0), 6)
         if isinstance(now[k], float) else now[k] - before.get(k, 0)
         for k in _counters}
    unp = {}
    for k, (c, b) in now["unprunable"].items():
        pc, pb = before.get("unprunable", {}).get(k, (0, 0))
        if c - pc or b - pb:
            unp[k] = {"chunks": c - pc, "bytes": b - pb}
    d["unprunable"] = unp
    busy = d["prefetch_busy_s"]
    wait = d["prefetch_wait_s"]
    d["overlap_fraction"] = (
        round(max(0.0, min(1.0, (busy - wait) / busy)), 4)
        if busy > 1e-9 else None)
    return d


def reset_stats() -> None:
    """Zero every counter (tests)."""
    global _inflight_bytes
    with _stats_lock:
        for k in _counters:
            _counters[k] = 0.0 if isinstance(_counters[k], float) else 0
        _unprunable.clear()
        _inflight_bytes = 0


# ---------------------------------------------------------------------------
# scan cache: snapshot-versioned spillable landing
# ---------------------------------------------------------------------------

_cache_lock = lockorder.make_lock("io.scanpipe.cache")
_cache: "dict[tuple, _CacheEntry]" = {}
_CACHE_MAX_ENTRIES = 256


class _CacheEntry:
    __slots__ = ("versions", "spillables", "catalog", "pins", "dead")

    def __init__(self, versions, spillables, catalog):
        self.versions = versions
        self.spillables = list(spillables)
        self.catalog = catalog
        self.pins = 0       # readers currently serving from this entry
        self.dead = False   # superseded/invalidated while pinned


def _close_entry(entry: "_CacheEntry") -> None:
    for sb in entry.spillables:
        try:
            sb.close()
        except Exception:  # catalog reset/closed under us: nothing to free
            pass


def _canon_desc(desc) -> tuple:
    """Hashable identity of one split descriptor, independent of how
    splits were packed into partitions."""
    from spark_rapids_tpu.io.filesrc import PackedSplit

    if isinstance(desc, PackedSplit):
        return ("#packed",) + tuple(_canon_desc(m) for m in desc.members)
    if isinstance(desc, str):
        return ("#file", desc)
    path = getattr(desc, "path", None)
    sub = getattr(desc, "row_groups", None)
    if sub is None:
        sub = getattr(desc, "stripes", None)
    return ("#chunks", path, tuple(sub or ()))


def _desc_paths(desc) -> list:
    from spark_rapids_tpu.io.filesrc import PackedSplit

    if isinstance(desc, PackedSplit):
        out = []
        for m in desc.members:
            out.extend(_desc_paths(m))
        return out
    if isinstance(desc, str):
        return [desc]
    p = getattr(desc, "path", None)
    return [p] if p else []


def _cache_key(exec_, partition: int):
    """(key, file-version vector) for one scan partition, or (None,
    None) when the source is unkeyable or a file vanished — then
    nothing lands (staleness must never be a guess)."""
    from spark_rapids_tpu.service.cache import snapshots

    source = exec_.source
    ident = snapshots.source_identity(source)
    if ident is None:
        return None, None
    descs = source.splits()
    if not descs:
        return None, None
    desc = descs[partition]
    paths = sorted(set(_desc_paths(desc)))
    versions = snapshots.file_versions(paths)
    if versions is None:
        return None, None
    key = (ident, int(getattr(source, "_snap_version", 0)),
           _canon_desc(desc), int(exec_.batch_rows), bool(exec_.pack))
    return key, (tuple(paths), versions)


def _cache_lookup(key, versions) -> Optional["_CacheEntry"]:
    """Pin and return a live, version-matching entry; invalidate and
    miss otherwise."""
    from spark_rapids_tpu.memory.catalog import get_catalog

    with _cache_lock:
        entry = _cache.get(key)
        if entry is None:
            _bump(cache_misses=1)
            return None
        stale = entry.versions != versions
        if entry.catalog is not get_catalog():
            # the catalog was reset under us: its buffers are gone, do
            # not try to close through the dead handle
            _cache.pop(key, None)
            _bump(cache_misses=1)
            return None
        if stale:
            _cache.pop(key, None)
            if entry.pins == 0:
                _close_entry(entry)
            else:
                entry.dead = True
            _bump(cache_misses=1)
            return None
        entry.pins += 1
        _bump(cache_hits=1)
        return entry


def _unpin(entry: "_CacheEntry") -> None:
    with _cache_lock:
        entry.pins -= 1
        if entry.dead and entry.pins == 0:
            _close_entry(entry)


def _cache_publish(key, versions, spillables, catalog) -> None:
    entry = _CacheEntry(versions, spillables, catalog)
    with _cache_lock:
        old = _cache.pop(key, None)
        if old is not None:
            if old.pins == 0:
                _close_entry(old)
            else:
                old.dead = True
        _cache[key] = entry
        while len(_cache) > _CACHE_MAX_ENTRIES:
            victim_key = next((k for k, e in _cache.items()
                               if e.pins == 0), None)
            if victim_key is None:
                break
            _close_entry(_cache.pop(victim_key))


def cache_device_bytes() -> int:
    """Device-tier bytes currently held by scan-cache landings."""
    from spark_rapids_tpu.memory.catalog import StorageTier

    with _cache_lock:
        entries = [(e.catalog, sb) for e in _cache.values()
                   for sb in e.spillables]
    total = 0
    for catalog, sb in entries:
        try:
            if catalog.tier_of(sb.buffer_id) == StorageTier.DEVICE:
                total += sb.device_memory_size()
        except Exception:
            continue
    return total


def cache_len() -> int:
    with _cache_lock:
        return len(_cache)


def clear_cache() -> None:
    """Drop every landed entry, closing catalog registrations (tests,
    and the explicit invalidation hook)."""
    with _cache_lock:
        entries = list(_cache.values())
        _cache.clear()
        for e in entries:
            if e.pins == 0:
                _close_entry(e)
            else:
                e.dead = True


# ---------------------------------------------------------------------------
# the re-slicing accumulator: chunk stream -> exact batch_rows slices
# ---------------------------------------------------------------------------


def _host_rows(data, schema) -> int:
    if not len(schema):
        return 0
    return len(data[schema.names[0]])


def _slice_host(data, validity, schema, start, end):
    d, v = {}, {}
    for name in schema.names:
        d[name] = data[name][start:end]
        vv = validity.get(name)
        v[name] = None if vv is None else vv[start:end]
    return d, v


class _SliceAccum:
    """Accumulates host chunks and emits slices of EXACTLY
    ``batch_rows`` rows (remainder only at end-of-split): batch
    boundaries match the read-everything-then-slice layout bit for bit,
    whatever the chunk granularity underneath."""

    def __init__(self, schema, batch_rows: int):
        self.schema = schema
        self.batch_rows = batch_rows
        self._parts: list = []
        self._rows = 0
        self.total = 0

    def add(self, part) -> None:
        n = _host_rows(part[0], self.schema)
        if n == 0:
            return
        self._parts.append(part)
        self._rows += n
        self.total += n

    def pop_slices(self, final: bool = False) -> list:
        """Drain every complete slice (plus the remainder when
        ``final``) as a list of (data, validity) views."""
        from spark_rapids_tpu.io import arrow_conv

        if self._rows < self.batch_rows and not (final and self._rows):
            return []
        if len(self._parts) == 1:
            data, validity = self._parts[0]
        else:
            data, validity = arrow_conv.concat_host(self._parts,
                                                    self.schema)
        n_full = self._rows // self.batch_rows
        out = []
        for i in range(n_full):
            out.append(_slice_host(data, validity, self.schema,
                                   i * self.batch_rows,
                                   (i + 1) * self.batch_rows))
        rem = self._rows - n_full * self.batch_rows
        if rem and final:
            out.append(_slice_host(data, validity, self.schema,
                                   n_full * self.batch_rows, self._rows))
            rem = 0
        if rem:
            tail = _slice_host(data, validity, self.schema,
                               self._rows - rem, self._rows)
            self._parts = [tail]
        else:
            self._parts = []
        self._rows = rem
        return out


# ---------------------------------------------------------------------------
# the IO pool (read + pack off the task thread)
# ---------------------------------------------------------------------------

_io_pool = None


def _get_io_pool(conf):
    """Shared producer pool: every running producer's consumer is
    blocked draining it, so each submitted producer terminates and
    queued ones always get a slot — saturation serializes, never
    deadlocks."""
    global _io_pool
    with _stats_lock:
        if _io_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            _io_pool = ThreadPoolExecutor(
                max_workers=max(
                    int(conf.get(cfg.MULTIFILE_READ_THREADS)), 2),
                thread_name_prefix="scan-io")
        return _io_pool


# ---------------------------------------------------------------------------
# the pipeline
# ---------------------------------------------------------------------------


def _pack_slices(source, exec_, partition, stats, emit):
    """Producer body shared by both paths: stream the split's chunks,
    re-slice, pack; ``emit(packed)`` returns False to stop early.
    Returns (total_rows, busy_seconds)."""
    from spark_rapids_tpu.execs import interop

    schema = exec_.schema
    acc = _SliceAccum(schema, exec_.batch_rows)
    busy = 0.0
    # duck-typed sources (test doubles, third-party) may predate the
    # chunked-read contract; the whole split as one chunk is always
    # equivalent
    chunk_fn = getattr(source, "read_host_chunks", None)
    chunks = chunk_fn(partition) if chunk_fn is not None else \
        iter([source.read_host_split(partition)])

    def flush(final):
        nonlocal busy
        t0 = time.perf_counter()
        slices = acc.pop_slices(final=final)
        busy += time.perf_counter() - t0
        for data, validity in slices:
            t0 = time.perf_counter()
            with TraceRange("ScanExec.pack"):
                p = interop.pack_host(data, validity, schema, 0,
                                      _host_rows(data, schema),
                                      stats=stats, pack=exec_.pack)
            busy += time.perf_counter() - t0
            if not emit(p):
                return False
        return True

    while True:
        t0 = time.perf_counter()
        try:
            chunk = next(chunks)
        except StopIteration:
            busy += time.perf_counter() - t0
            break
        busy += time.perf_counter() - t0
        _bump(chunks_read=1)
        acc.add(chunk)
        if not flush(final=False):
            return acc.total, busy
    flush(final=True)
    return acc.total, busy


def scan_iter(exec_, partition: int):
    """The body of ScanExec.execute: yields uploaded batches for one
    scan partition through the prefetch pipeline (or the synchronous
    reference path at depth 0), serving/landing the scan cache when
    enabled."""
    from spark_rapids_tpu.memory import semaphore

    source = exec_.source
    schema = exec_.schema
    conf = getattr(source, "conf", None) or cfg.DEFAULT_CONF
    depth = max(int(conf.get(cfg.SCAN_PREFETCH_DEPTH)), 0)
    land = bool(conf.get(cfg.SCAN_LANDING_SPILLABLE)) and \
        not exec_.defer_decode
    key = versions = None
    if land:
        key, versions = _cache_key(exec_, partition)
        land = key is not None
    if land:
        entry = _cache_lookup(key, versions)
        if entry is not None:
            try:
                with semaphore.get():
                    for sb in entry.spillables:
                        b = sb.get_batch()
                        try:
                            yield b
                        finally:
                            sb.release()
            finally:
                _unpin(entry)
            return

    nbytes_fn = getattr(source, "split_nbytes", None)
    _bump(splits_read=1,
          bytes_read=int(nbytes_fn(partition)) if nbytes_fn else 0)
    origin = source.split_origin(partition)
    stats = source.split_stats(partition)
    landing = _Landing() if land else None
    published = False
    try:
        if depth == 0:
            yielded = yield from _scan_sync(exec_, partition, stats,
                                            origin, landing)
        else:
            yielded = yield from _scan_async(exec_, partition, stats,
                                             origin, depth, landing,
                                             conf)
        if land and yielded:
            from spark_rapids_tpu.memory.catalog import get_catalog

            landing.release_upto(len(landing.handles))
            _cache_publish(key, versions, landing.handles,
                           get_catalog())
            published = True
    finally:
        if landing is not None and not published:
            # abandoned (limit / downstream error) or nothing landed:
            # drop pins first so close() is not deferred forever behind
            # a refcount nobody will release
            landing.release_upto(len(landing.handles))
            for sb in landing.handles:
                try:
                    sb.close()
                except Exception:
                    pass


class _Landing:
    """Scan-cache landing in progress: the SpillableBatch handles plus
    a monotonic pin cursor. Each landed batch is registered with one
    acquire held (the active downstream input must not be a spill
    victim); the cursor releases each pin exactly once, in yield
    order, as the next batch takes over."""

    __slots__ = ("handles", "_released")

    def __init__(self):
        self.handles: list = []
        self._released = 0

    def land(self, batch) -> None:
        from spark_rapids_tpu.memory import priorities
        from spark_rapids_tpu.memory.catalog import set_buffer_owner
        from spark_rapids_tpu.memory.spillable import SpillableBatch

        prev = set_buffer_owner("io.scan")
        try:
            sb = SpillableBatch(batch, priorities.SCAN_CACHE_PRIORITY)
        finally:
            set_buffer_owner(prev)
        sb.get_batch()  # pin: active downstream input
        self.handles.append(sb)

    def release_upto(self, upto: int) -> None:
        upto = min(upto, len(self.handles))
        while self._released < upto:
            try:
                self.handles[self._released].release()
            except Exception:
                pass
            self._released += 1


def _scan_sync(exec_, partition, stats, origin, landing):
    """depth=0: fully synchronous read -> pack -> upload on the caller
    thread — the byte-identity reference path."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.execs import interop
    from spark_rapids_tpu.memory import semaphore

    source = exec_.source
    packed: list = []

    def emit(p):
        packed.append(p)
        return True

    # read+pack the whole split first (no overlap by design), then
    # upload under the semaphore exactly like the pre-pipeline scan
    t0 = time.perf_counter()
    total, busy = _pack_slices(source, exec_, partition, stats, emit)
    _bump(decode_s=time.perf_counter() - t0)
    if total == 0:
        yield ColumnarBatch.empty(exec_.schema)
        return False
    n_done = 0
    with semaphore.get():
        for p in packed:
            t0 = time.perf_counter()
            with TraceRange("ScanExec.upload"):
                b = interop.upload_packed(
                    p, defer_decode=exec_.defer_decode)
            _bump(h2d_s=time.perf_counter() - t0, slices_uploaded=1)
            b.origin = origin
            if landing is not None:
                landing.land(b)
            yield b
            n_done += 1
            if landing is not None:
                landing.release_upto(n_done - 1)
    return True


def _scan_async(exec_, partition, stats, origin, depth, landing, conf):
    """depth>=1: producer (IO pool) reads+packs ahead through a bounded
    queue; the consumer issues slice k+1's device_put before yielding
    slice k."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.execs import interop
    from spark_rapids_tpu.memory import semaphore

    source = exec_.source
    q: "_queue.Queue" = _queue.Queue(maxsize=depth)
    stop = threading.Event()
    done_evt = threading.Event()

    def put(item) -> bool:
        """Bounded put that re-checks ``stop`` — a consumer that
        abandons the scan (limit, downstream error) must not leave the
        producer blocked forever pinning packed slices."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def produce():
        try:
            def emit(p):
                # charge the admission budget while the packed slice
                # sits in the queue; refunded at dequeue (or here when
                # the consumer already stopped us)
                nbytes = p.nbytes()
                _add_inflight(nbytes)
                if not put(("packed", p)):
                    _add_inflight(-nbytes)
                    return False
                return True

            total, busy = _pack_slices(source, exec_, partition, stats,
                                       emit)
            _bump(decode_s=busy, prefetch_busy_s=busy)
            put(("done", total))
        except BaseException as e:  # surface in the consumer
            put(("error", e))
        finally:
            done_evt.set()

    _get_io_pool(conf).submit(produce)
    pending = None
    n_done = 0
    try:
        with semaphore.get():
            while True:
                t0 = time.perf_counter()
                kind, val = q.get()
                _bump(prefetch_wait_s=time.perf_counter() - t0)
                if kind == "done":
                    if val == 0:
                        yield ColumnarBatch.empty(exec_.schema)
                        return False
                    if pending is not None:
                        yield pending
                        n_done += 1
                        if landing is not None:
                            landing.release_upto(n_done - 1)
                    break
                if kind == "error":
                    raise val
                _add_inflight(-val.nbytes())
                t0 = time.perf_counter()
                with TraceRange("ScanExec.upload"):
                    b = interop.upload_packed(
                        val, defer_decode=exec_.defer_decode)
                _bump(h2d_s=time.perf_counter() - t0, slices_uploaded=1)
                b.origin = origin
                if landing is not None:
                    landing.land(b)
                if pending is not None:
                    yield pending
                    n_done += 1
                    if landing is not None:
                        landing.release_upto(n_done - 1)
                pending = b
        return True
    finally:
        stop.set()

        def drain():
            while True:
                try:
                    kind, val = q.get_nowait()
                except _queue.Empty:
                    return
                if kind == "packed":
                    _add_inflight(-val.nbytes())

        # a mid-put producer can still land one item after a single
        # drain pass, so keep draining until it reports done — it
        # always terminates once ``stop`` is visible
        while not done_evt.wait(timeout=0.05):
            drain()
        drain()
