"""Dictionary-encoded host string columns.

The scan path used to hand string columns around as numpy object arrays
(one Python ``str`` per row). For a 600k-row TPC-H lineitem scan that
meant two full Python-object passes — ``Array.to_pylist`` and
``np.unique`` over objects — costing ~2s of the 4s scan wall while the
device did 0.5s of work. Arrow already HAS the dictionary encoding the
engine wants (columnar/column.py StringColumn: int32 codes + sorted
dictionary), so the host representation keeps it: codes + dictionary,
produced by arrow's C++ ``dictionary_encode`` with only the (small)
dictionary ever touching Python.

The reference's scan path likewise never materializes row-wise strings:
cuDF keeps device string columns and the plugin copies arrow buffers
straight across (GpuColumnVector / HostColumnarToGpu.scala). This module
is numpy-only so the jax-free CPU oracle may import it.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np


class HostStrings:
    """One host string column: ``codes`` (int32, one per row; invalid
    rows hold 0) indexing ``dictionary`` (object ndarray of unique
    strings, sorted ascending). Supports ``len`` and slice-indexing so
    the scan/upload path can treat it like the object ndarray it
    replaces. Row validity travels separately (the scan's validity
    dict), exactly as for numeric columns."""

    __slots__ = ("codes", "dictionary")

    def __init__(self, codes: np.ndarray, dictionary: np.ndarray):
        self.codes = np.asarray(codes, dtype=np.int32)
        self.dictionary = np.asarray(dictionary, dtype=object)

    def __len__(self) -> int:
        return len(self.codes)

    def __getitem__(self, sl) -> "HostStrings":
        if not isinstance(sl, slice):
            raise TypeError("HostStrings supports slice indexing only")
        return HostStrings(self.codes[sl], self.dictionary)

    def to_objects(self, validity: Optional[np.ndarray] = None
                   ) -> np.ndarray:
        """Decode to the legacy object-ndarray form (None = null) for
        consumers that want row-wise strings (CPU oracle, UDF rows)."""
        if len(self.dictionary):
            out = self.dictionary[
                np.clip(self.codes, 0, len(self.dictionary) - 1)]
            out = np.asarray(out, dtype=object)
        else:
            out = np.full(len(self.codes), None, dtype=object)
        if validity is not None:
            out = out.copy()
            out[~np.asarray(validity, dtype=bool)] = None
        return out

    @staticmethod
    def from_objects(arr: np.ndarray) -> "HostStrings":
        """Object ndarray (None = null) -> HostStrings. Vectorized
        except for the None scan; used for legacy producers (CSV rows,
        UDF outputs) entering the fast path."""
        arr = np.asarray(arr, dtype=object)
        null = np.array([x is None for x in arr], dtype=bool)
        non_null = arr[~null].astype(str) if (~null).any() \
            else np.array([], dtype=str)
        dictionary, inv = (np.unique(non_null, return_inverse=True)
                           if len(non_null) else
                           (np.array([], dtype=object),
                            np.array([], dtype=np.int64)))
        codes = np.zeros(len(arr), dtype=np.int32)
        codes[~null] = inv.astype(np.int32)
        return HostStrings(codes, np.asarray(dictionary, dtype=object))

    @staticmethod
    def concat(parts: List["HostStrings"]) -> "HostStrings":
        """Concatenate columns onto ONE merged sorted dictionary (the
        host mirror of columnar.column.unify_dictionaries)."""
        dicts = [p.dictionary.astype(str) for p in parts
                 if len(p.dictionary)]
        if not dicts:
            return HostStrings(
                np.concatenate([p.codes for p in parts])
                if parts else np.array([], dtype=np.int32),
                np.array([], dtype=object))
        merged = np.unique(np.concatenate(dicts))
        out_codes = []
        for p in parts:
            if len(p.dictionary):
                remap = np.searchsorted(
                    merged, p.dictionary.astype(str)).astype(np.int32)
                out_codes.append(remap[p.codes])
            else:
                out_codes.append(p.codes)
        return HostStrings(np.concatenate(out_codes),
                           np.asarray(merged, dtype=object))
