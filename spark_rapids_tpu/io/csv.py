"""CSV scan (GpuCSVScan analogue, GpuBatchScanExec.scala:507).

The reference parses CSV with cuDF's device parser behind many compat
gates (timestamp formats, RapidsConf.scala:482). Host-side pyarrow CSV
fills that role here; an explicit Schema may be supplied (the common Spark
usage) or types are inferred from the first file. Splits are whole files.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.io import arrow_conv
from spark_rapids_tpu.io.filesrc import FileSourceBase, Filter


class CsvSource(FileSourceBase):
    def __init__(self, paths, schema: Optional[Schema] = None,
                 header: bool = True, delimiter: str = ",",
                 columns: Optional[List[str]] = None,
                 filters: Optional[Sequence[Filter]] = None,
                 conf: Optional[cfg.RapidsConf] = None):
        super().__init__(paths, columns, filters, conf)
        self.declared_schema = schema
        self.header = header
        self.delimiter = delimiter

    def timestamp_formats(self) -> List[str]:
        """Accepted strptime patterns for TIMESTAMP columns
        (rapids.tpu.sql.csv.timestampFormats), tried in order."""
        return [f.strip() for f in str(
            self.conf.get(cfg.CSV_TIMESTAMP_FORMATS)).split(",")
            if f.strip()]

    def timestamps_enabled(self) -> bool:
        return bool(self.conf.get(cfg.CSV_TIMESTAMPS_ENABLED))

    def _read_options(self):
        from pyarrow import csv as pacsv

        ropts = {}
        copts = {}
        if self.declared_schema is not None:
            # TIMESTAMP columns parse tz-NAIVE (the configured formats
            # carry no offsets; engine timestamps are UTC storage) —
            # _read_file casts the parsed column up to the tz-aware
            # engine type afterwards
            import pyarrow as pa

            copts["column_types"] = {
                n: (pa.timestamp("us") if t is dt.TIMESTAMP
                    else dt.to_arrow(t))
                for n, t in zip(self.declared_schema.names,
                                self.declared_schema.types)}
            if not self.header:
                ropts["column_names"] = list(self.declared_schema.names)
        elif not self.header:
            raise ValueError("headerless CSV requires an explicit schema")
        # timestamp compat gate (the reference gates cuDF CSV timestamp
        # parsing behind spark.rapids.sql.csvTimestamps.enabled,
        # RapidsConf.scala:482). The gate is enforced by the PLANNER
        # (plan/overrides._ScanRule tags the scan will_not_work), so the
        # accelerated path only ever reads timestamps under the
        # configured formats; this reader must keep working with the
        # gate off because the CPU-fallback engine reads through the
        # same source (with arrow's permissive default parsers — the
        # Spark-CPU-semantics stand-in).
        if self.timestamps_enabled():
            # configured formats govern INFERRED timestamp columns too,
            # not just declared ones — otherwise arrow's built-in
            # parsers would accept spellings outside the compat gate
            copts["timestamp_parsers"] = self.timestamp_formats()
        return (pacsv.ReadOptions(**ropts),
                pacsv.ParseOptions(delimiter=self.delimiter),
                pacsv.ConvertOptions(**copts,
                                     strings_can_be_null=True))

    def _read_file(self, path: str):
        from pyarrow import csv as pacsv

        ropts, popts, copts = self._read_options()
        table = pacsv.read_csv(path, read_options=ropts,
                               parse_options=popts,
                               convert_options=copts)
        if self.declared_schema is not None:
            # naive-parsed timestamps -> the tz-aware engine type (the
            # parsed wall time IS the UTC storage value)
            for n, t in zip(self.declared_schema.names,
                            self.declared_schema.types):
                if t is not dt.TIMESTAMP or n not in table.column_names:
                    continue
                i = table.column_names.index(n)
                table = table.set_column(
                    i, n, table.column(n).cast(dt.to_arrow(t)))
        return table

    def _file_schema(self) -> Schema:
        if self.declared_schema is not None and self.columns is None:
            return self.declared_schema
        table = self._read_file(self.paths[0])
        return arrow_conv.schema_from_arrow(table.schema, self.columns)

    def _build_splits(self) -> list:
        self.chunks_total += len(self.paths)
        if self._pruning_enabled():
            # CSV carries no footer statistics: filters were pushed down
            # but nothing can prune — record the reason explicitly so
            # bytes-read accounting stays honest across formats
            import os

            from spark_rapids_tpu.io import scanpipe

            total = 0
            for p in self.paths:
                try:
                    total += os.path.getsize(p)
                except OSError:  # pragma: no cover - raced unlink
                    pass
            scanpipe.record_unprunable("csv", "no-footer-stats",
                                       len(self.paths), total)
        return list(self.paths)

    def _read_split(self, desc: str):
        table = self._read_file(desc)
        return table.select(list(self.schema().names))
