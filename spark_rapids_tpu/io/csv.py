"""CSV scan (GpuCSVScan analogue, GpuBatchScanExec.scala:507).

The reference parses CSV with cuDF's device parser behind many compat
gates (timestamp formats, RapidsConf.scala:482). Host-side pyarrow CSV
fills that role here; an explicit Schema may be supplied (the common Spark
usage) or types are inferred from the first file. Splits are whole files.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.io import arrow_conv
from spark_rapids_tpu.io.filesrc import FileSourceBase, Filter


class CsvSource(FileSourceBase):
    def __init__(self, paths, schema: Optional[Schema] = None,
                 header: bool = True, delimiter: str = ",",
                 columns: Optional[List[str]] = None,
                 filters: Optional[Sequence[Filter]] = None,
                 conf: Optional[cfg.RapidsConf] = None):
        super().__init__(paths, columns, filters, conf)
        self.declared_schema = schema
        self.header = header
        self.delimiter = delimiter

    def _read_options(self):
        from pyarrow import csv as pacsv

        ropts = {}
        copts = {}
        if self.declared_schema is not None:
            col_types = {n: dt.to_arrow(t) for n, t in
                         zip(self.declared_schema.names,
                             self.declared_schema.types)}
            copts["column_types"] = col_types
            if not self.header:
                ropts["column_names"] = list(self.declared_schema.names)
        elif not self.header:
            raise ValueError("headerless CSV requires an explicit schema")
        return (pacsv.ReadOptions(**ropts),
                pacsv.ParseOptions(delimiter=self.delimiter),
                pacsv.ConvertOptions(**copts,
                                     strings_can_be_null=True))

    def _read_file(self, path: str):
        from pyarrow import csv as pacsv

        ropts, popts, copts = self._read_options()
        return pacsv.read_csv(path, read_options=ropts,
                              parse_options=popts, convert_options=copts)

    def _file_schema(self) -> Schema:
        if self.declared_schema is not None and self.columns is None:
            return self.declared_schema
        table = self._read_file(self.paths[0])
        return arrow_conv.schema_from_arrow(table.schema, self.columns)

    def _build_splits(self) -> list:
        self.chunks_total += len(self.paths)
        return list(self.paths)

    def _read_split(self, desc: str):
        table = self._read_file(desc)
        return table.select(list(self.schema().names))
