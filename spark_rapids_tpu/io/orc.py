"""ORC scan: stripe-split host decode (GpuOrcScan.scala analogue).

The reference filters ORC stripes with search arguments on the CPU then
decodes on device (GpuOrcScan.scala, OrcFilters.scala:206). pyarrow's ORC
reader exposes stripe-granular reads but not stripe statistics, so splits
are stripes (scan parallelism is preserved) and pruning conjuncts are
applied only as a whole-file row-count shortcut.
"""
from __future__ import annotations

import dataclasses

from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.io import arrow_conv
from spark_rapids_tpu.io.filesrc import FileSourceBase


@dataclasses.dataclass(frozen=True)
class _StripeSplit:
    path: str
    stripes: tuple  # () = whole file


class OrcSource(FileSourceBase):
    def _file_schema(self) -> Schema:
        from pyarrow import orc

        return arrow_conv.schema_from_arrow(
            orc.ORCFile(self.paths[0]).schema, self.columns)

    def _build_splits(self) -> list:
        from pyarrow import orc

        splits = []
        for path in self.paths:
            f = orc.ORCFile(path)
            n = f.nstripes
            self.chunks_total += max(n, 1)
            if n <= 1:
                splits.append(_StripeSplit(path, ()))
            else:
                splits.extend(_StripeSplit(path, (i,)) for i in range(n))
        return splits

    def _read_split(self, desc: _StripeSplit):
        import pyarrow as pa
        from pyarrow import orc

        f = orc.ORCFile(desc.path)
        names = list(self.schema().names)
        if not desc.stripes:
            return f.read(columns=names)
        batches = [f.read_stripe(i, columns=names) for i in desc.stripes]
        return pa.Table.from_batches(batches)
