"""ORC scan: stripe statistics pushdown + stripe-split host decode.

The reference filters ORC stripes with search arguments on the CPU then
decodes on device (GpuOrcScan.scala, OrcFilters.scala:206). pyarrow's
ORC reader exposes stripe-granular reads but not stripe statistics, so
the engine reads the ORC tail itself (io/orc_meta.py): pruning filters
drop stripes whose min/max cannot match, and surviving stripes' stats
feed ``Column.stats`` (the packed-key groupby path) — the same two
consumers the parquet footer serves.
"""
from __future__ import annotations

import dataclasses

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.io import arrow_conv
from spark_rapids_tpu.io.filesrc import FileSourceBase, filter_may_match


@dataclasses.dataclass(frozen=True)
class _StripeSplit:
    path: str
    stripes: tuple  # () = whole file
    # ((col, lo, hi), ...) from stripe statistics — Column.stats feed
    stats: tuple = ()
    # estimated on-disk bytes (file size / stripe count: the ORC tail
    # we parse does not carry per-stripe byte lengths)
    nbytes: int = 0


class OrcSource(FileSourceBase):
    _dump_prefix_conf = cfg.ORC_DEBUG_DUMP_PREFIX

    def _file_schema(self) -> Schema:
        from pyarrow import orc

        return arrow_conv.schema_from_arrow(
            orc.ORCFile(self.paths[0]).schema, self.columns)

    def estimated_row_count(self):
        """Tail-metadata row counts (the ORC side of the join-reorder
        size signal)."""
        from pyarrow import orc

        if self._est_rows is None:
            try:
                self._est_rows = sum(int(orc.ORCFile(p).nrows)
                                     for p in self.paths)
            except Exception:  # pragma: no cover - corrupt tail
                self._est_rows = -1
        return None if self._est_rows < 0 else self._est_rows

    def _build_splits(self) -> list:
        import os

        from pyarrow import orc

        from spark_rapids_tpu.io import scanpipe
        from spark_rapids_tpu.io.orc_meta import stripe_statistics

        schema = self.schema()
        types = dict(zip(schema.names, schema.types))
        prune = self._pruning_enabled()
        splits = []
        for path in self.paths:
            f = orc.ORCFile(path)
            n = f.nstripes
            self.chunks_total += max(n, 1)
            try:
                fsize = os.path.getsize(path)
            except OSError:  # pragma: no cover - raced unlink
                fsize = 0
            stripe_bytes = fsize // max(n, 1)
            # statistics map by the FILE schema's field order — a column
            # projection must not shift which physical column a name's
            # stats come from (parquet resolves by name the same way)
            per_stripe = stripe_statistics(path, list(f.schema.names)) \
                if n >= 1 else None
            if per_stripe is not None and len(per_stripe) != n:
                per_stripe = None  # tail/stripe mismatch: trust reads
            if per_stripe is None and prune:
                # filters were pushed down but this file's tail carries
                # no usable stripe statistics: say so, don't silently
                # skip pruning (bytes-read accounting stays honest)
                scanpipe.record_unprunable("orc", "no-stripe-statistics",
                                           max(n, 1), fsize)
            for i in range(max(n, 1)):
                sid = () if n <= 1 else (i,)
                if per_stripe is not None and prune and \
                        not filter_may_match(self.filters,
                                             per_stripe[i]):
                    self.chunks_pruned += 1
                    scanpipe.record_pruned("orc", 1, stripe_bytes)
                    continue
                st = self._split_stats(per_stripe[i], types) \
                    if per_stripe else ()
                splits.append(_StripeSplit(
                    path, sid, st,
                    stripe_bytes if sid else fsize))
        return splits

    @staticmethod
    def _split_stats(stats: dict, types) -> tuple:
        from spark_rapids_tpu.columnar import dtypes as dt

        out = []
        for name, (lo, hi, _has_null) in stats.items():
            typ = types.get(name)
            # orc_meta decodes int/double/date statistics; only the
            # discrete kinds feed packed keys (no timestampStatistics)
            if typ is not None and (typ.is_integral or typ is dt.DATE):
                out.append((name, int(lo), int(hi)))
        return tuple(out)

    # split_stats: FileSourceBase merges per-desc stats, incl. packed
    # multi-file partitions

    def _read_split(self, desc: _StripeSplit):
        import pyarrow as pa
        from pyarrow import orc

        self._maybe_debug_dump(desc.path)
        f = orc.ORCFile(desc.path)
        names = list(self.schema().names)
        if not desc.stripes:
            return f.read(columns=names)
        batches = [f.read_stripe(i, columns=names) for i in desc.stripes]
        return pa.Table.from_batches(batches)

    def _desc_chunks(self, desc: _StripeSplit):
        """Stripe-granular streaming read for the scan pipeline."""
        import pyarrow as pa
        from pyarrow import orc

        self._maybe_debug_dump(desc.path)
        f = orc.ORCFile(desc.path)
        schema = self.schema()
        names = list(schema.names)
        if not desc.stripes:
            yield arrow_conv.table_to_host(f.read(columns=names),
                                           schema)
            return
        for i in desc.stripes:
            batch = f.read_stripe(i, columns=names)
            yield arrow_conv.table_to_host(
                pa.Table.from_batches([batch]), schema)

    def _desc_nbytes(self, desc: _StripeSplit) -> int:
        if desc.nbytes:
            return desc.nbytes
        return super()._desc_nbytes(desc)
