"""Shared file-source machinery: path resolution, split -> partition
mapping, the multi-file thread pool, and pushed-down filters.

Filters are conjunct triples ``(column, op, value)`` with op in
``= < <= > >=`` — the subset the planner can extract from a FilterNode
condition (GpuParquetScan.scala:228-265 does the same with Spark's
pushed-down sources.filters). They are used ONLY for pruning (row groups /
stripes / files); exact filtering still happens in the plan's FilterNode,
so pruning that keeps extra rows is always safe.
"""
from __future__ import annotations

import glob
import os
import threading
from spark_rapids_tpu.utils import lockorder
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence, Tuple

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.io import arrow_conv
from spark_rapids_tpu.plan.nodes import DataSource

Filter = Tuple[str, str, object]

_OPS = ("=", "<", "<=", ">", ">=")


def resolve_paths(paths) -> List[str]:
    """file | directory | glob | list of those -> sorted file list."""
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        p = os.fspath(p)
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                out.extend(os.path.join(root, f) for f in files
                           if not f.startswith((".", "_")))
        elif any(ch in p for ch in "*?["):
            out.extend(f for f in glob.glob(p) if os.path.isfile(f))
        else:
            out.append(p)
    out = sorted(dict.fromkeys(out))
    if not out:
        raise FileNotFoundError(f"no input files for {paths!r}")
    return out


def filter_may_match(filters: Sequence[Filter], stats: dict) -> bool:
    """May any row in a chunk with the given per-column ``{name: (min, max,
    has_nulls)}`` stats satisfy every conjunct? Missing stats -> True (keep:
    pruning must be conservative)."""
    for name, op, value in filters:
        st = stats.get(name)
        if st is None:
            continue
        lo, hi, _ = st
        if lo is None or hi is None:
            continue
        try:
            if op == "=" and not (lo <= value <= hi):
                return False
            if op == "<" and not (lo < value):
                return False
            if op == "<=" and not (lo <= value):
                return False
            if op == ">" and not (hi > value):
                return False
            if op == ">=" and not (hi >= value):
                return False
        except TypeError:
            continue  # incomparable stats: keep the chunk
    return True


class PackedSplit:
    """Several small single-file splits served as ONE scan partition
    (Spark's FilePartition packing, sql.files.maxPartitionBytes)."""

    __slots__ = ("members",)

    def __init__(self, members: list):
        self.members = list(members)


class FileSourceBase(DataSource):
    """A DataSource over files with splits, projection and pruning filters.

    ``PackedSplit`` (below) groups several small single-file splits into
    one scan partition, Spark-FilePartition-style.

    Subclasses implement ``_build_splits()`` (returning opaque split
    descriptors, already pruned) and ``_read_split(desc)`` (returning a
    pyarrow Table with exactly the projected columns).
    """

    def __init__(self, paths, columns: Optional[List[str]] = None,
                 filters: Optional[Sequence[Filter]] = None,
                 conf: Optional[cfg.RapidsConf] = None):
        self.paths = resolve_paths(paths)
        self.columns = list(columns) if columns is not None else None
        self.filters: List[Filter] = list(filters or [])
        for f in self.filters:
            assert f[1] in _OPS, f"bad pushdown op {f[1]!r}"
        self.conf = conf or cfg.DEFAULT_CONF
        # pack small per-file splits into shared scan partitions
        # (Spark's FilePartition packing under maxPartitionBytes,
        # FilePartition.scala getFilePartitions). Disabled by the
        # planner when the query reads input_file_name/block metadata —
        # a packed partition spans files, so per-row file identity
        # would be lost (the reference declines to split/merge there
        # the same way).
        self.pack_splits = True
        self._schema: Optional[Schema] = None
        self._splits: Optional[list] = None
        # reentrant: splits() -> _build_splits() -> schema() nests
        self._lock = lockorder.make_rlock("io.filesrc.splits")
        # observability for tests / explain (pruning effectiveness)
        self.chunks_total = 0
        self.chunks_pruned = 0
        self._est_rows: Optional[int] = None

    # scans ship inside remote map-task closures (cluster runtime): the
    # lock is process-local; splits re-derive from paths on arrival
    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_lock", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = lockorder.make_rlock("io.filesrc.splits")

    # conf key naming the debug-dump directory for this format (None =
    # no dump support); subclasses point at their format's key
    _dump_prefix_conf = None

    def _maybe_debug_dump(self, path: str) -> None:
        """Copy read inputs for offline repro when the format's
        debug.dumpPrefix conf is set (the reference's dump-on-read,
        RapidsConf.scala:575-589)."""
        import os
        import shutil

        if self._dump_prefix_conf is None:
            return
        prefix = self.conf.get(self._dump_prefix_conf)
        if not prefix:
            return
        os.makedirs(prefix, exist_ok=True)
        dest = os.path.join(prefix, os.path.basename(path))
        if not os.path.exists(dest):
            shutil.copyfile(path, dest)

    # -- subclass surface --------------------------------------------------

    def _file_schema(self) -> Schema:
        raise NotImplementedError

    def _build_splits(self) -> list:
        raise NotImplementedError

    def _read_split(self, desc):
        raise NotImplementedError

    # -- DataSource --------------------------------------------------------

    def schema(self) -> Schema:
        with self._lock:
            if self._schema is None:
                self._schema = self._file_schema()
            return self._schema

    def splits(self) -> list:
        with self._lock:
            if self._splits is None:
                raw = self._build_splits()
                if self.pack_splits and len(raw) > 1:
                    raw = self._pack(raw)
                self._splits = raw
            return self._splits

    def _pack(self, raw: list) -> list:
        """Group consecutive splits into PackedSplit partitions up to
        the reader batch-size target. Fewer, bigger scan partitions:
        each partition is one host read + one device upload + one trip
        through every per-batch kernel downstream — at ~100 ms fixed
        cost per dispatch, 4 splits of a 20 MB table cost 4x the
        dispatches of 1 packed split for zero parallelism gain. The
        pack target is additionally capped by maxPartitionBytes so
        packing never undoes the partition-size contract."""
        target = min(self.conf.get(cfg.MAX_READER_BATCH_SIZE_BYTES),
                     self.conf.get(cfg.SCAN_MAX_PARTITION_BYTES))
        per_path_count: dict = {}
        for d in raw:
            p = d if isinstance(d, str) else d.path
            per_path_count[p] = per_path_count.get(p, 0) + 1
        out: list = []
        cur: list = []
        cur_bytes = 0
        for d in raw:
            p = d if isinstance(d, str) else d.path
            try:
                sz = os.path.getsize(p) // max(per_path_count[p], 1)
            except OSError:  # pragma: no cover - raced unlink
                sz = target  # unknown size: never pack with others
            if cur and cur_bytes + sz > target:
                out.append(cur[0] if len(cur) == 1
                           else PackedSplit(cur))
                cur, cur_bytes = [], 0
            cur.append(d)
            cur_bytes += sz
        if cur:
            out.append(cur[0] if len(cur) == 1 else PackedSplit(cur))
        return out

    def num_splits(self) -> int:
        return max(len(self.splits()), 1)

    def _read_desc(self, desc):
        if isinstance(desc, PackedSplit):
            import pyarrow as pa

            tables = [self._read_split(m) for m in desc.members]
            return tables[0] if len(tables) == 1 else \
                pa.concat_tables(tables)
        return self._read_split(desc)

    def read_host_split(self, split: int):
        descs = self.splits()
        if not descs:
            return arrow_conv.empty_host(self.schema())
        table = self._read_desc(descs[split])
        return arrow_conv.table_to_host(table, self.schema())

    def _pruning_enabled(self) -> bool:
        """Footer-stat pruning gate: filters pushed down AND the knob
        on. Checked by subclasses before dropping any chunk."""
        return bool(self.filters) and \
            bool(self.conf.get(cfg.SCAN_PRUNING_ENABLED))

    def _desc_chunks(self, desc):
        """Yield one split descriptor's host data chunk by chunk;
        format subclasses refine to row-group / stripe granularity so
        the scan pipeline streams instead of materializing the split."""
        yield arrow_conv.table_to_host(self._read_split(desc),
                                       self.schema())

    def read_host_chunks(self, split: int):
        """Stream one split as (data, validity) host chunks — the scan
        pipeline (io/scanpipe) re-slices these to exact batch-row
        boundaries, so chunk granularity never changes results."""
        descs = self.splits()
        if not descs:
            yield arrow_conv.empty_host(self.schema())
            return
        desc = descs[split]
        members = desc.members if isinstance(desc, PackedSplit) \
            else [desc]
        for m in members:
            yield from self._desc_chunks(m)

    def _desc_nbytes(self, desc) -> int:
        """On-disk bytes one split descriptor will read (whole file by
        default; subclasses narrow to the chunks actually kept)."""
        path = desc if isinstance(desc, str) else \
            getattr(desc, "path", None)
        if not path:
            return 0
        try:
            return os.path.getsize(path)
        except OSError:  # pragma: no cover - raced unlink
            return 0

    def split_nbytes(self, split: int) -> int:
        """On-disk bytes reading this scan partition will touch
        (telemetry: the bytes_read side of pruning accounting)."""
        descs = self.splits()
        if not descs:
            return 0
        desc = descs[split]
        members = desc.members if isinstance(desc, PackedSplit) \
            else [desc]
        return sum(self._desc_nbytes(m) for m in members)

    def _desc_stats(self, desc) -> Optional[dict]:
        s = getattr(desc, "stats", None)
        if not s:
            return None
        return dict((c, (lo, hi)) for c, lo, hi in s) or None

    def split_stats(self, split: int):
        descs = self.splits()
        if not descs:
            return None
        desc = descs[split]
        if not isinstance(desc, PackedSplit):
            return self._desc_stats(desc)
        merged: Optional[dict] = None
        for m in desc.members:
            s = self._desc_stats(m)
            if s is None:
                return None  # one member unknown -> whole range unknown
            if merged is None:
                merged = dict(s)
                continue
            for c in list(merged):
                if c in s:
                    merged[c] = (min(merged[c][0], s[c][0]),
                                 max(merged[c][1], s[c][1]))
                else:
                    del merged[c]
        return merged or None

    def split_origin(self, split: int):
        descs = self.splits()
        if not descs:
            return None
        desc = descs[split]
        if isinstance(desc, PackedSplit):
            # spans files: no single (path, start, len) identity; the
            # planner disables packing when the query reads it
            return None
        path = desc if isinstance(desc, str) else desc.path
        try:
            size = os.path.getsize(path)
        except OSError:  # pragma: no cover - raced unlink
            size = -1
        return (path, 0, size)

    def read_host(self):
        """Read ALL splits through the multi-file thread pool and stitch
        (MultiFileParquetPartitionReader analogue,
        GpuParquetScan.scala:700-839)."""
        descs = self.splits()
        if not descs:
            return arrow_conv.empty_host(self.schema())
        schema = self.schema()
        n_threads = min(self.conf.get(cfg.MULTIFILE_READ_THREADS),
                        len(descs))
        if n_threads <= 1 or len(descs) == 1:
            parts = [arrow_conv.table_to_host(self._read_desc(d), schema)
                     for d in descs]
        else:
            with ThreadPoolExecutor(max_workers=n_threads) as pool:
                tables = list(pool.map(self._read_desc, descs))
            parts = [arrow_conv.table_to_host(t, schema) for t in tables]
        return arrow_conv.concat_host(parts, schema)

    def with_filters(self, filters: Sequence[Filter]) -> "FileSourceBase":
        """New source with extra pruning conjuncts (planner pushdown)."""
        import copy

        c = copy.copy(self)
        c.filters = self.filters + list(filters)
        c._splits = None
        c._lock = lockorder.make_rlock("io.filesrc.splits")
        c.chunks_total = 0
        c.chunks_pruned = 0
        return c
