"""Minimal ORC tail reader: per-STRIPE column statistics.

The reference prunes ORC stripes with search arguments before device
decode (OrcFilters.scala:206, GpuOrcScan.scala); pyarrow's ORC binding
exposes stripe READS but not stripe statistics, so this module walks the
ORC file tail directly:

    [metadata][footer][postscript][psLen: 1 byte]

- postscript (uncompressed protobuf): footerLength=1,
  compression=2 (0 none / 1 zlib / 5 zstd), compressionBlockSize=3,
  metadataLength=5
- the metadata section is an ORC compressed stream (3-byte block
  headers, (len << 1) | isOriginal) holding the Metadata protobuf:
  repeated StripeStatistics stripeStats=1, each a repeated
  ColumnStatistics colStats=1 with intStatistics=2 (sint64 min=1/max=2),
  doubleStatistics=3 (double min=1/max=2), dateStatistics=7
  (sint32 days min=1/max=2) and hasNull=10.

Only the statistic kinds the pruning filters consume are decoded; any
unknown compression or malformed tail degrades to "no stats" (scan
correctness never depends on pruning). Column index: colStats[0] is the
whole-struct column, flat schema field i sits at colStats[i + 1].
"""
from __future__ import annotations

import struct
import zlib
from typing import Dict, List, Optional, Tuple


def _varint(buf: bytes, pos: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _fields(buf: bytes):
    """Iterate (field_number, wire_type, value) over a protobuf buffer.
    value: int for varint, bytes for length-delimited, raw 8/4 bytes for
    fixed."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _varint(buf, pos)
        fnum, wt = key >> 3, key & 7
        if wt == 0:
            v, pos = _varint(buf, pos)
        elif wt == 2:
            ln, pos = _varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wt == 1:
            v = buf[pos:pos + 8]
            pos += 8
        elif wt == 5:
            v = buf[pos:pos + 4]
            pos += 4
        else:  # pragma: no cover - groups unused by ORC
            return
        yield fnum, wt, v


def _decompress_stream(data: bytes, kind: int) -> Optional[bytes]:
    """ORC compressed stream: series of 3-byte-header blocks."""
    if kind == 0:
        return data
    out = bytearray()
    pos = 0
    while pos + 3 <= len(data):
        header = data[pos] | (data[pos + 1] << 8) | (data[pos + 2] << 16)
        pos += 3
        ln = header >> 1
        original = header & 1
        chunk = data[pos:pos + ln]
        pos += ln
        if original:
            out += chunk
        elif kind == 1:  # zlib (raw deflate)
            out += zlib.decompress(chunk, -15)
        elif kind == 5:  # zstd
            try:
                import zstandard

                out += zstandard.ZstdDecompressor().decompress(
                    chunk, max_output_size=1 << 26)
            except Exception:
                return None
        else:  # snappy/lzo: no codec available
            return None
    return bytes(out)


def _column_stats(buf: bytes) -> Tuple[Optional[Tuple], bool]:
    """ColumnStatistics -> ((min, max) or None, has_null)."""
    mn = mx = None
    has_null = False
    for fnum, wt, v in _fields(buf):
        if fnum == 2 and wt == 2:            # intStatistics
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == 0:
                    mn = _zigzag(v2)
                elif f2 == 2 and w2 == 0:
                    mx = _zigzag(v2)
        elif fnum == 3 and wt == 2:          # doubleStatistics
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == 1:
                    mn = struct.unpack("<d", v2)[0]
                elif f2 == 2 and w2 == 1:
                    mx = struct.unpack("<d", v2)[0]
        elif fnum == 7 and wt == 2:          # dateStatistics (days)
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == 0:
                    mn = _zigzag(v2)
                elif f2 == 2 and w2 == 0:
                    mx = _zigzag(v2)
        elif fnum == 10 and wt == 0:         # hasNull
            has_null = bool(v)
    if mn is None or mx is None:
        return None, has_null
    return (mn, mx), has_null


def stripe_statistics(path: str, column_names: List[str]
                      ) -> Optional[List[Dict[str, tuple]]]:
    """Per-stripe {column: (min, max, has_null)} for a FLAT schema, or
    None when the tail can't be decoded (unknown codec, nested schema,
    old writer). Shape matches parquet's row-group stats consumer
    (io/filesrc.filter_may_match)."""
    try:
        with open(path, "rb") as f:
            f.seek(0, 2)
            size = f.tell()
            tail_len = min(size, 1 << 20)
            f.seek(size - tail_len)
            tail = f.read(tail_len)
        ps_len = tail[-1]
        ps = tail[-1 - ps_len:-1]
        footer_len = metadata_len = 0
        compression = 0
        for fnum, wt, v in _fields(ps):
            if fnum == 1 and wt == 0:
                footer_len = v
            elif fnum == 2 and wt == 0:
                compression = v
            elif fnum == 5 and wt == 0:
                metadata_len = v
        if metadata_len == 0:
            return None
        meta_end = len(tail) - 1 - ps_len - footer_len
        meta_raw = tail[meta_end - metadata_len:meta_end]
        if len(meta_raw) != metadata_len:
            return None  # tail window too small (huge footer)
        meta = _decompress_stream(meta_raw, compression)
        if meta is None:
            return None
        out: List[Dict[str, tuple]] = []
        for fnum, wt, v in _fields(meta):
            if fnum != 1 or wt != 2:
                continue
            cols = [v2 for f2, w2, v2 in _fields(v)
                    if f2 == 1 and w2 == 2]
            stats: Dict[str, tuple] = {}
            # cols[0] = struct root; flat field i at cols[i + 1]
            for i, name in enumerate(column_names):
                if i + 1 >= len(cols):
                    break
                rng, has_null = _column_stats(cols[i + 1])
                if rng is not None:
                    stats[name] = (rng[0], rng[1], has_null)
            out.append(stats)
        return out or None
    except Exception:
        return None
