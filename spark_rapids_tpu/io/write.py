"""Columnar file writes (GpuParquetFileFormat / GpuOrcFileFormat /
GpuFileFormatWriter / GpuInsertIntoHadoopFsRelationCommand analogues).

The reference encodes each batch on device then streams the encoded buffer
to the filesystem (ColumnarOutputWriter, sql-plugin ~1750 LoC §2.7); the
TPU-native path downloads the device batch and encodes with pyarrow. The
command returns write statistics — one row per written file (path, rows,
bytes) — the BasicColumnarWriteStatsTracker surface.
"""
from __future__ import annotations

import os
from typing import Iterator, List, Optional

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.execs import interop
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.io import arrow_conv
from spark_rapids_tpu.plan.nodes import PlanNode
from spark_rapids_tpu.utils.tracing import TraceRange

STATS_SCHEMA = Schema(["path", "num_rows", "bytes"],
                      [dt.STRING, dt.INT64, dt.INT64])

FORMATS = ("parquet", "orc")


class WriteFilesNode(PlanNode):
    """Write the child's output to ``path`` as parquet/ORC; optional hive
    partitioned layout (``partition_by`` = prefix of child columns written
    as key=value directories, dropped from the data files)."""

    def __init__(self, child: PlanNode, path: str, format: str = "parquet",
                 partition_by: Optional[List[str]] = None,
                 mode: str = "overwrite"):
        super().__init__([child])
        assert format in FORMATS, format
        assert mode in ("overwrite", "error"), mode
        self.path = path
        self.format = format
        self.partition_by = list(partition_by or [])
        child_names = child.output_schema().names
        for c in self.partition_by:
            assert c in child_names, f"partition column {c} not in child"
        self.mode = mode

    def output_schema(self) -> Schema:
        return STATS_SCHEMA

    def data_schema(self) -> Schema:
        """Schema of rows inside the data files (partition cols removed)."""
        s = self.children[0].output_schema()
        keep = [(n, t) for n, t in zip(s.names, s.types)
                if n not in self.partition_by]
        return Schema([n for n, _ in keep], [t for _, t in keep])

    def describe(self) -> str:
        part = f", partitionBy={self.partition_by}" \
            if self.partition_by else ""
        return f"WriteFiles[{self.format}, {self.path}{part}]"


def _prepare_dir(path: str, mode: str):
    if os.path.exists(path):
        if mode == "error":
            raise FileExistsError(path)
        import shutil

        shutil.rmtree(path)
    os.makedirs(path, exist_ok=True)


def _write_table(table, path: str, format: str) -> int:
    if format == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(table, path)
    else:
        from pyarrow import orc

        orc.write_table(table, path)
    return os.path.getsize(path)


def write_table_stream(chunks, path: str, format: str = "parquet"
                       ) -> int:
    """Stream an iterator of arrow tables into ONE file without ever
    materializing their concatenation: each chunk appends through the
    format's incremental writer, so peak host memory is one chunk.
    The large-scale-factor datagen path rides on this (sf100 lineitem
    is tens of GB as a single host table). Returns the file size."""
    it = iter(chunks)
    try:
        first = next(it)
    except StopIteration:
        raise ValueError("write_table_stream: empty chunk stream")
    if format == "parquet":
        import pyarrow.parquet as pq

        with pq.ParquetWriter(path, first.schema) as w:
            w.write_table(first)
            for t in it:
                w.write_table(t)
    else:
        from pyarrow import orc

        with orc.ORCWriter(path) as w:
            w.write(first)
            for t in it:
                w.write(t)
    return os.path.getsize(path)


def _partition_dir(base: str, cols: List[str], values) -> str:
    parts = []
    for c, v in zip(cols, values):
        sv = "__HIVE_DEFAULT_PARTITION__" if v is None else str(v)
        parts.append(f"{c}={sv}")
    return os.path.join(base, *parts)


class _Stats:
    """Accumulates (path, rows, bytes) rows (GpuWriteStatsTracker)."""

    def __init__(self):
        self.rows: List[tuple] = []

    def add(self, path: str, n: int, size: int):
        self.rows.append((path, n, size))

    def to_host(self):
        paths = np.array([r[0] for r in self.rows], dtype=object)
        rows = np.array([r[1] for r in self.rows], dtype=np.int64)
        sizes = np.array([r[2] for r in self.rows], dtype=np.int64)
        data = {"path": paths, "num_rows": rows, "bytes": sizes}
        validity = {k: np.ones(len(self.rows), dtype=bool) for k in data}
        return data, validity


def write_arrow_table(table, node: WriteFilesNode, task_id: int,
                      stats: _Stats, seq: List[int]):
    """Write one arrow table (all of one task's batch) honoring the
    partitioned layout. ``seq`` is the per-task file counter."""
    ext = "parquet" if node.format == "parquet" else "orc"
    if not node.partition_by:
        fname = f"part-{task_id:05d}-{seq[0]:04d}.{ext}"
        seq[0] += 1
        full = os.path.join(node.path, fname)
        size = _write_table(table, full, node.format)
        stats.add(full, table.num_rows, size)
        return
    import pyarrow.compute as pc

    data_cols = [n for n in table.column_names
                 if n not in node.partition_by]
    keys = table.select(node.partition_by).to_pylist()
    uniq = sorted({tuple(k.values()) for k in keys},
                  key=lambda t: tuple((v is None, str(v)) for v in t))
    for combo in uniq:
        mask = None
        for c, v in zip(node.partition_by, combo):
            m = pc.is_null(table.column(c)) if v is None else \
                pc.equal(table.column(c), v)
            mask = m if mask is None else pc.and_kleene(mask, m)
        sub = table.filter(mask).select(data_cols)
        d = _partition_dir(node.path, node.partition_by, combo)
        os.makedirs(d, exist_ok=True)
        fname = f"part-{task_id:05d}-{seq[0]:04d}.{ext}"
        seq[0] += 1
        full = os.path.join(d, fname)
        size = _write_table(sub, full, node.format)
        stats.add(full, sub.num_rows, size)


class WriteFilesExec(TpuExec):
    """Drains the child per partition (one 'task' per partition, like
    GpuFileFormatDataWriter's task commit protocol) and emits the stats
    batch from partition 0."""

    def __init__(self, node: WriteFilesNode, child: TpuExec):
        super().__init__([child], STATS_SCHEMA)
        self.node = node

    @property
    def num_partitions(self) -> int:
        return 1

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            child = self.children[0]
            child_schema = self.node.children[0].output_schema()
            _prepare_dir(self.node.path, self.node.mode)
            stats = _Stats()
            for task in range(child.num_partitions):
                seq = [0]
                for b in child.execute(task):
                    if b.realized_num_rows() == 0:
                        continue
                    with TraceRange("WriteFilesExec.encode"):
                        table = arrow_conv.batch_to_arrow(b, child_schema)
                        write_arrow_table(table, self.node, task, stats,
                                          seq)
            data, validity = stats.to_host()
            yield interop.host_to_batch(data, validity, STATS_SCHEMA)
        return timed(self, it())


def execute_write_cpu(node: WriteFilesNode):
    """CPU-engine implementation (the oracle writes with the same pyarrow
    encoder into its own directory)."""
    from spark_rapids_tpu.cpu.engine import CpuFrame, execute_cpu
    from spark_rapids_tpu.cpu.evaluator import CV

    child = execute_cpu(node.children[0])
    _prepare_dir(node.path, node.mode)
    stats = _Stats()
    schema = node.children[0].output_schema()
    import pyarrow as pa

    arrays = []
    for name, typ, c in zip(schema.names, schema.types, child.cols):
        valid = c.valid_mask()
        mask = ~valid
        if typ is dt.STRING:
            vals = [c.data[i] if valid[i] else None
                    for i in range(child.num_rows)]
            arrays.append(pa.array(vals, type=pa.string()))
        elif typ is dt.DATE:
            arrays.append(pa.array(
                np.asarray(c.data, dtype=np.int32), mask=mask
            ).cast(pa.date32()))
        elif typ is dt.TIMESTAMP:
            arrays.append(pa.array(
                np.asarray(c.data, dtype=np.int64), mask=mask
            ).cast(pa.timestamp("us", tz="UTC")))
        else:
            arrays.append(pa.array(
                np.asarray(c.data, dtype=typ.np_dtype), mask=mask,
                type=dt.to_arrow(typ)))
    table = pa.Table.from_arrays(arrays, names=list(schema.names))
    write_arrow_table(table, node, 0, stats, [0])
    data, validity = stats.to_host()
    cols = []
    for name, typ in zip(STATS_SCHEMA.names, STATS_SCHEMA.types):
        arr = data[name]
        if typ is not dt.STRING:
            arr = arr.astype(typ.np_dtype)
        cols.append(CV(typ, arr, validity[name]))
    return CpuFrame(STATS_SCHEMA, cols, len(data["path"]))
