"""Out-of-process pandas-UDF workers.

The reference bootstraps SEPARATE Python worker processes with device
pools pre-initialized and streams Arrow batches to them
(python/rapids/worker.py:22-50 patches the worker main; daemon.py:36-60
pre-forks them); the in-process default here is faster for small UDFs
but shares the interpreter — a UDF that leaks, crashes, or holds the
GIL hurts the engine. With ``rapids.tpu.python.worker.process.enabled``
the pandas function runs in a pooled worker process instead:

- workers are persistent subprocesses running this module's loop,
  speaking length-prefixed cloudpickle frames over stdin/stdout (the
  pipe is the Arrow-stream analogue; pandas frames pickle efficiently),
- a function ships ONCE per worker, cached by content digest (the
  serialized-lineage model: later calls send only the payload),
- checkout from the pool bounds concurrency exactly like
  PythonWorkerSemaphore bounds the in-process path,
- a worker that dies mid-call surfaces the error and is replaced on
  the next checkout; the engine process never crashes with it.

Workers force ``JAX_PLATFORMS=cpu`` so they can never contend for the
attached TPU (the reference's workers get their own memory pool slice
for the same reason).
"""
from __future__ import annotations

import os
import queue
import struct
import subprocess
import sys
import threading
from spark_rapids_tpu.utils import lockorder
from typing import Optional

_HDR = struct.Struct("<I")
_FN_CACHE_MAX = 64  # distinct UDFs cached per worker before reset


def _send(pipe, payload: bytes) -> None:
    pipe.write(_HDR.pack(len(payload)))
    pipe.write(payload)
    pipe.flush()


def _recv(pipe) -> Optional[bytes]:
    hdr = pipe.read(_HDR.size)
    if len(hdr) < _HDR.size:
        return None
    (n,) = _HDR.unpack(hdr)
    return pipe.read(n)


class _Worker:
    def __init__(self):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "spark_rapids_tpu.udf.pyworker"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env)
        # digests of functions this worker already holds; BOTH sides
        # bound this cache with the same clear-on-add-when-full rule, so
        # contents stay in lockstep (see _worker_main)
        self._shipped = set()
        # pipe EOF can be observed BEFORE waitpid sees the exit: a dead
        # worker must never pass an `alive` check in that window
        self._dead = False

    @property
    def alive(self) -> bool:
        return not self._dead and self.proc.poll() is None

    def run(self, fn, args):
        import hashlib

        import cloudpickle

        # keyed by CONTENT, not id(): CPython reuses ids of collected
        # functions, which would make the worker run a stale cached fn
        blob = cloudpickle.dumps(fn)
        fn_id = hashlib.sha1(blob).hexdigest()
        fn_bytes = None if fn_id in self._shipped else blob
        try:
            _send(self.proc.stdin,
                  cloudpickle.dumps((fn_id, fn_bytes, args)))
        except (BrokenPipeError, OSError) as e:
            self._dead = True
            raise RuntimeError(f"python worker died: {e}")
        if fn_bytes is not None and len(self._shipped) >= _FN_CACHE_MAX:
            self._shipped.clear()
        self._shipped.add(fn_id)
        reply = _recv(self.proc.stdout)
        if reply is None:
            self._dead = True
            raise RuntimeError(
                "python worker died mid-call (exit "
                f"{self.proc.poll()})")
        import pickle

        status, payload = pickle.loads(reply)
        if status != "ok":
            raise RuntimeError(f"python worker UDF failed:\n{payload}")
        return payload

    def close(self):
        try:
            self.proc.stdin.close()
            self.proc.wait(timeout=5)
        except Exception:
            self.proc.kill()


class PythonWorkerPool:
    """Fixed-size pool; checkout blocks (the process-level analogue of
    PythonWorkerSemaphore.scala:144's slot bound)."""

    def __init__(self, n: int):
        self.n = max(n, 1)  # 0/negative would hang every checkout
        self._q: "queue.Queue[_Worker]" = queue.Queue()
        for _ in range(self.n):
            self._q.put(_Worker())

    def run(self, fn, *args):
        w = self._q.get()
        if not w.alive:  # replace a worker that crashed last call
            w.close()
            w = _Worker()
        try:
            return w.run(fn, args)
        finally:
            if not w.alive:
                w.close()
                w = _Worker()  # keep the pool at size even on failure
            self._q.put(w)

    def shutdown(self):
        while True:
            try:
                self._q.get_nowait().close()
            except queue.Empty:
                break


_POOL: Optional[PythonWorkerPool] = None
_POOL_LOCK = lockorder.make_lock("udf.pyworker.pool")


def run_udf(conf, fn, *args):
    """The single UDF seam: in-process call by default; through the
    worker-process pool when the session enables it. Wrap per-query
    constants (the user fn, schemas, key names) into ``fn`` via
    functools.partial so they ship ONCE per worker — only the pandas
    payload should travel in ``args`` per batch."""
    from spark_rapids_tpu import config as cfg

    if conf is None or not conf.get(cfg.PYTHON_WORKER_PROCESS):
        return fn(*args)
    global _POOL
    want = max(conf.get(cfg.PYTHON_WORKER_SLOTS), 1)
    with _POOL_LOCK:
        if _POOL is None or _POOL.n != want:
            if _POOL is not None:  # a later session resized the pool
                _POOL.shutdown()
            _POOL = PythonWorkerPool(want)
            import atexit

            atexit.register(shutdown_pool)
    return _POOL.run(fn, *args)


def shutdown_pool() -> None:
    global _POOL
    if _POOL is not None:
        _POOL.shutdown()
        _POOL = None


def _worker_main() -> None:  # pragma: no cover - subprocess body
    import pickle

    import cloudpickle

    fns = {}
    stdin = sys.stdin.buffer
    stdout = sys.stdout.buffer
    # stray prints from user UDFs must not corrupt the frame protocol
    sys.stdout = sys.stderr
    while True:
        msg = _recv(stdin)
        if msg is None:
            return
        try:
            fn_id, fn_bytes, args = cloudpickle.loads(msg)
            if fn_bytes is not None:
                # same clear-on-add-when-full rule as _Worker._shipped:
                # identical add sequences keep both caches in lockstep
                if len(fns) >= _FN_CACHE_MAX:
                    fns.clear()
                fns[fn_id] = cloudpickle.loads(fn_bytes)
            result = fns[fn_id](*args)
            out = pickle.dumps(("ok", result))
        except Exception:
            import traceback

            out = pickle.dumps(("err", traceback.format_exc()))
        _send(stdout, out)


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    _worker_main()
