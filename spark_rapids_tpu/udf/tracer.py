"""Symbolic tracer turning Python scalar functions into Expression trees.

Structure mirrors the reference compiler's pieces (SURVEY.md §2.11):

- ``SymbolicValue``        <- the operand-stack values of the symbolic
  executor (State, CatalystExpressionBuilder.scala): every overloaded
  operator or recognized call appends Expression nodes instead of
  computing.
- ``compile_udf``          <- CatalystExpressionBuilder.compile: runs the
  function once on symbolic arguments; any escape (bool coercion = data-
  dependent branch, unknown method, foreign type) raises UdfCompileError.
- ``PythonUdf``            <- the uncompiled ScalaUDF: an opaque
  Expression the TPU planner rejects (so the plan falls back) but the CPU
  engine evaluates row-wise with None-for-NULL semantics.
- ``compile_udfs_in_plan`` <- LogicalPlanRules.apply (udf-compiler/.../
  Plugin.scala:36-94): rewrites every compilable PythonUdf in a plan,
  keeping the original on failure.

``sym_if(cond, a, b)`` is the explicit branch construct (Python's ``if``
on traced values cannot be intercepted without bytecode rewriting — the
JVM compiler gets branches from bytecode; here the user writes the
conditional functionally, as in jax).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.cpu.evaluator import CV, CpuEvalContext, eval_expr
from spark_rapids_tpu.expressions import arithmetic as ar
from spark_rapids_tpu.expressions import conditional as cond
from spark_rapids_tpu.expressions import math as mth
from spark_rapids_tpu.expressions import predicates as pr
from spark_rapids_tpu.expressions import strings as st
from spark_rapids_tpu.expressions.base import (Expression, Literal)
from spark_rapids_tpu.expressions.cast import Cast
from spark_rapids_tpu.plan import nodes as pn


class UdfCompileError(Exception):
    pass


def _lift(v) -> Expression:
    if isinstance(v, SymbolicValue):
        return v.expr
    if isinstance(v, Expression):
        return v
    if isinstance(v, (bool, int, float, str)) or v is None:
        return Literal(v)
    raise UdfCompileError(f"cannot lift {type(v).__name__} into the "
                          "expression language")


class SymbolicValue:
    """Expression-building proxy handed to the traced function."""

    __slots__ = ("expr",)

    def __init__(self, expr: Expression):
        self.expr = expr

    # -- arithmetic -------------------------------------------------------

    def _bin(self, other, klass, flip=False):
        l, r = _lift(self), _lift(other)
        if flip:
            l, r = r, l
        return SymbolicValue(klass(l, r))

    def __add__(self, o):
        if self.expr.dtype is dt.STRING or (
                isinstance(o, str)) or (
                isinstance(o, SymbolicValue) and
                o.expr.dtype is dt.STRING):
            return SymbolicValue(st.ConcatStrings(
                [_lift(self), _lift(o)]))
        return self._bin(o, ar.Add)

    def __radd__(self, o):
        if isinstance(o, str) or self.expr.dtype is dt.STRING:
            return SymbolicValue(st.ConcatStrings(
                [_lift(o), _lift(self)]))
        return self._bin(o, ar.Add, flip=True)

    def __sub__(self, o):
        return self._bin(o, ar.Subtract)

    def __rsub__(self, o):
        return self._bin(o, ar.Subtract, flip=True)

    def __mul__(self, o):
        return self._bin(o, ar.Multiply)

    def __rmul__(self, o):
        return self._bin(o, ar.Multiply, flip=True)

    def __truediv__(self, o):
        return self._bin(o, ar.Divide)

    def __rtruediv__(self, o):
        return self._bin(o, ar.Divide, flip=True)

    def __floordiv__(self, o):
        return self._bin(o, ar.IntegralDivide)

    def __rfloordiv__(self, o):
        return self._bin(o, ar.IntegralDivide, flip=True)

    def __mod__(self, o):
        return self._bin(o, ar.Remainder)

    def __rmod__(self, o):
        return self._bin(o, ar.Remainder, flip=True)

    def __pow__(self, o):
        return self._bin(o, mth.Pow)

    def __rpow__(self, o):
        return self._bin(o, mth.Pow, flip=True)

    def __neg__(self):
        return SymbolicValue(ar.UnaryMinus(_lift(self)))

    def __pos__(self):
        return SymbolicValue(ar.UnaryPositive(_lift(self)))

    def __abs__(self):
        return SymbolicValue(ar.Abs(_lift(self)))

    # -- comparisons ------------------------------------------------------

    def __eq__(self, o):  # type: ignore[override]
        return self._bin(o, pr.EqualTo)

    def __ne__(self, o):  # type: ignore[override]
        return SymbolicValue(pr.Not(pr.EqualTo(_lift(self), _lift(o))))

    def __lt__(self, o):
        return self._bin(o, pr.LessThan)

    def __le__(self, o):
        return self._bin(o, pr.LessThanOrEqual)

    def __gt__(self, o):
        return self._bin(o, pr.GreaterThan)

    def __ge__(self, o):
        return self._bin(o, pr.GreaterThanOrEqual)

    # -- boolean ----------------------------------------------------------

    def __and__(self, o):
        return self._bin(o, pr.And)

    def __rand__(self, o):
        return self._bin(o, pr.And, flip=True)

    def __or__(self, o):
        return self._bin(o, pr.Or)

    def __ror__(self, o):
        return self._bin(o, pr.Or, flip=True)

    def __invert__(self):
        return SymbolicValue(pr.Not(_lift(self)))

    def __bool__(self):
        raise UdfCompileError(
            "data-dependent control flow (if/while/and/or on a traced "
            "value); use sym_if(cond, a, b) or let the UDF fall back")

    def __str__(self):
        raise UdfCompileError(
            "str() on a traced value; use Cast via .astype(STRING)")

    def __repr__(self) -> str:
        return f"Symbolic({self.expr!r})"

    def __hash__(self):  # __eq__ is symbolic; identity hash keeps dicts sane
        return id(self)

    # -- recognized methods (the Instruction.scala method-call table) -----

    def upper(self):
        return SymbolicValue(st.Upper(_lift(self)))

    def lower(self):
        return SymbolicValue(st.Lower(_lift(self)))

    def strip(self):
        return SymbolicValue(st.StringTrim(_lift(self)))

    def lstrip(self):
        return SymbolicValue(st.StringTrimLeft(_lift(self)))

    def rstrip(self):
        return SymbolicValue(st.StringTrimRight(_lift(self)))

    @staticmethod
    def _want_str(v, what: str) -> str:
        # these expressions take literal needles (the reference's
        # GpuSubstring-style lit-only restriction)
        if not isinstance(v, str):
            raise UdfCompileError(f"{what} needs a literal string")
        return v

    def startswith(self, prefix):
        return SymbolicValue(st.StartsWith(
            _lift(self), self._want_str(prefix, "startswith")))

    def endswith(self, suffix):
        return SymbolicValue(st.EndsWith(
            _lift(self), self._want_str(suffix, "endswith")))

    def replace(self, a, b):
        return SymbolicValue(st.StringReplace(
            _lift(self), self._want_str(a, "replace"),
            self._want_str(b, "replace")))

    def __contains__(self, item):
        raise UdfCompileError("`in` coerces to bool; use .contains()")

    def contains(self, item):
        return SymbolicValue(st.Contains(
            _lift(self), self._want_str(item, "contains")))

    def __len__(self):
        raise UdfCompileError("len() must return int; use .length()")

    def length(self):
        return SymbolicValue(st.Length(_lift(self)))

    # -- float/round group ------------------------------------------------

    def sqrt(self):
        return SymbolicValue(mth.Sqrt(_lift(self)))

    def __float__(self):
        raise UdfCompileError("float() coercion is data-dependent; "
                              "use float-typed arithmetic instead")

    def __int__(self):
        raise UdfCompileError("int() coercion is data-dependent; "
                              "use .astype(dtype) instead")

    def astype(self, to: dt.DType):
        return SymbolicValue(Cast(_lift(self), to))

    def __floor__(self):
        return SymbolicValue(mth.Floor(_lift(self)))

    def __ceil__(self):
        return SymbolicValue(mth.Ceil(_lift(self)))


def sym_if(cond_v, then_v, else_v):
    """Functional conditional for traced UDFs (the If/CaseWhen the JVM
    compiler folds branches into). With concrete (non-traced) arguments it
    evaluates eagerly, so a sym_if-using UDF also runs row-wise when the
    surrounding function is untraceable."""
    if not any(isinstance(v, SymbolicValue)
               for v in (cond_v, then_v, else_v)):
        return then_v if cond_v else else_v
    return SymbolicValue(cond.If(_lift(cond_v), _lift(then_v),
                                 _lift(else_v)))


def compile_udf(fn: Callable, args: Sequence[Expression]
                ) -> Optional[Expression]:
    """Compile ``fn`` over symbolic arguments; returns the compiled
    expression or None when the function escapes the compilable subset
    (the reference's silent-fallback contract). Two attempts:
    1. direct symbolic trace (fast; inlines helper calls naturally),
    2. bytecode symbolic execution (udf/bytecode.py) — folds REAL
       ``if``/``and``/``or`` control flow into If expressions, the
       capability the reference gets from its JVM CFG walk."""
    sym_args = [SymbolicValue(a) for a in args]
    try:
        out = fn(*sym_args)
        return _lift(out)
    except UdfCompileError:
        pass
    except TypeError:
        # e.g. math.sqrt(SymbolicValue) — the C function rejects proxies
        pass
    except Exception:
        return None
    from spark_rapids_tpu.udf.bytecode import compile_udf_bytecode

    return compile_udf_bytecode(fn, args)


# ---------------------------------------------------------------------------
# The opaque UDF expression
# ---------------------------------------------------------------------------


class PythonUdf(Expression):
    """Uncompiled Python scalar UDF over child expressions.

    TPU planner: no rule exists -> subtree falls back (the reference's
    GpuOverrides would equally reject an unreplaced ScalaUDF). CPU
    engine: row-wise apply with None passed for NULL inputs and a None
    result meaning NULL (Spark UDF semantics)."""

    def __init__(self, fn: Callable, children: Sequence[Expression],
                 return_dtype: dt.DType, name: Optional[str] = None):
        super().__init__(list(children))
        self.fn = fn
        self._dtype = return_dtype
        self.udf_name = name or getattr(fn, "__name__", "udf")

    @property
    def dtype(self) -> dt.DType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return True

    @property
    def device_only(self) -> bool:
        return False

    def eval_cpu(self, ctx: CpuEvalContext) -> CV:
        ins = [eval_expr(c, ctx) for c in self.children]
        n = ctx.num_rows
        out_dtype = self._dtype
        if out_dtype is dt.STRING:
            data = np.empty(n, dtype=object)
        else:
            data = np.zeros(n, dtype=out_dtype.np_dtype)
        validity = np.ones(n, dtype=bool)
        for i in range(n):
            row = [None if (cv.validity is not None and not cv.validity[i])
                   else cv.data[i] for cv in ins]
            # numpy scalars -> python values so user code sees plain types
            row = [v.item() if isinstance(v, np.generic) else v
                   for v in row]
            r = self.fn(*row)
            if r is None:
                validity[i] = False
            else:
                data[i] = r
        return CV(out_dtype, data, validity)

    def __repr__(self) -> str:  # pragma: no cover
        return f"PythonUdf({self.udf_name})"


# ---------------------------------------------------------------------------
# Plan rewrite (LogicalPlanRules analogue)
# ---------------------------------------------------------------------------


def _rewrite_expr(e: Expression, stats: List[int]) -> Expression:
    def fn(node: Expression) -> Expression:
        if isinstance(node, PythonUdf):
            compiled = compile_udf(node.fn, node.children)
            if compiled is not None:
                if compiled.dtype is not node.dtype:
                    # honor the declared return type (the traced tree may
                    # naturally be narrower/wider)
                    compiled = Cast(compiled, node.dtype)
                stats[0] += 1
                return compiled
            stats[1] += 1
        return node
    return e.transform(fn)


def compile_udfs_in_plan(plan: pn.PlanNode) -> pn.PlanNode:
    """Rewrite compilable PythonUdfs throughout a plan tree. Safe on any
    node type; only expression-bearing nodes are touched."""
    stats = [0, 0]
    new_children = [compile_udfs_in_plan(c) for c in plan.children]
    plan = plan.with_children(new_children) if plan.children else plan
    import copy

    if isinstance(plan, pn.ProjectNode):
        plan = copy.copy(plan)
        plan.exprs = [_rewrite_expr(e, stats) for e in plan.exprs]
    elif isinstance(plan, pn.FilterNode):
        plan = copy.copy(plan)
        plan.condition = _rewrite_expr(plan.condition, stats)
    return plan
