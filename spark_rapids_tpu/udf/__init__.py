"""Python-UDF compiler: trace opaque user functions into the expression
layer (SURVEY.md §2.11).

The reference compiles Scala UDF *JVM bytecode* into Catalyst expressions
(udf-compiler: LambdaReflection -> CFG -> symbolic execution,
CatalystExpressionBuilder.scala:44-100), falling back silently to the
original UDF when compilation fails. The TPU-native analogue traces the
*Python callable* with symbolic operands: operators and recognized
method/builtin calls record expression nodes, so a successful trace turns
the UDF into native expressions that fuse into the jitted projection.
Failures (data-dependent branches, unknown calls) leave the UDF opaque —
it then runs row-wise on the CPU engine, the reference's fallback path.
"""
from spark_rapids_tpu.udf.tracer import (PythonUdf, UdfCompileError,
                                         compile_udf,
                                         compile_udfs_in_plan, sym_if)

__all__ = ["PythonUdf", "UdfCompileError", "compile_udf",
           "compile_udfs_in_plan", "sym_if"]
