"""CPython-bytecode symbolic executor: compiles Python UDFs WITH real
control flow into Expression trees.

This is the TPU build's analogue of the reference's JVM-bytecode compiler
(SURVEY.md §2.11): LambdaReflection -> ``dis`` over the live function;
CFG/BB (CFG.scala:329) -> jump-target-aware instruction walk;
Instruction.scala's opcode table -> ``_STEP`` handlers; the symbolic
executor folding branches into If/CaseWhen
(CatalystExpressionBuilder.scala:44-100) -> ``_Frame.run``: a
conditional jump on a traced value executes BOTH successor paths and
merges their return expressions into ``If(cond, then, else)``.

Scope (escapes raise UdfCompileError -> the caller falls back silently,
exactly the reference's contract):
- straight-line code, ``if``/``elif``/``else``, ``and``/``or``/``not``,
  comparisons and chained conditionals, local variable assignment,
  ``x is None`` / ``is not None`` (IsNull), ``x in (lit, ...)`` (In),
  calls to recognized builtins (abs, min, max) and ``math.*`` functions,
  method calls resolved through SymbolicValue (upper/strip/replace/...),
- no loops (backward jumps), comprehensions, globals mutation, try, or
  data-dependent Python coercions (bool()/int()/float()/str()).

Python bytecode changes across versions; opcodes below cover 3.11/3.12.
Unknown opcodes raise UdfCompileError — i.e. new-version drift degrades
to the row-wise CPU path, never to wrong results.
"""
from __future__ import annotations

import dis
import math
from typing import Dict, List, Optional, Sequence

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions import arithmetic as ar
from spark_rapids_tpu.expressions import conditional as cond
from spark_rapids_tpu.expressions import math as mth
from spark_rapids_tpu.expressions import predicates as pr
from spark_rapids_tpu.expressions.base import Expression, Literal
from spark_rapids_tpu.udf.tracer import (SymbolicValue, UdfCompileError,
                                         _lift)

_MAX_FORKS = 64          # exponential-blowup guard on branch nesting
_MAX_STEPS = 20_000      # runaway guard per path

_BINARY_OPS = {
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "/": lambda a, b: a / b,
    "//": lambda a, b: a // b,
    "%": lambda a, b: a % b,
    "**": lambda a, b: a ** b,
    "&": lambda a, b: a & b,
    "|": lambda a, b: a | b,
    "^": lambda a, b: a ^ b,
    "<<": lambda a, b: a << b,
    ">>": lambda a, b: a >> b,
}

_COMPARE_OPS = {
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}

#: recognized global callables (the Instruction.scala method-call table)
_KNOWN_CALLS = {
    abs: lambda a: SymbolicValue(ar.Abs(_lift(a))),
    math.sqrt: lambda a: SymbolicValue(mth.Sqrt(_lift(a))),
    math.floor: lambda a: SymbolicValue(mth.Floor(_lift(a))),
    math.ceil: lambda a: SymbolicValue(mth.Ceil(_lift(a))),
    math.exp: lambda a: SymbolicValue(mth.Exp(_lift(a))),
    math.log: lambda a: SymbolicValue(mth.Log(_lift(a))),
    math.log10: lambda a: SymbolicValue(mth.Log10(_lift(a))),
    math.sin: lambda a: SymbolicValue(mth.Sin(_lift(a))),
    math.cos: lambda a: SymbolicValue(mth.Cos(_lift(a))),
    math.tan: lambda a: SymbolicValue(mth.Tan(_lift(a))),
    math.pow: lambda a, b: SymbolicValue(mth.Pow(_lift(a), _lift(b))),
    min: lambda a, b: SymbolicValue(cond.If(
        pr.LessThanOrEqual(_lift(a), _lift(b)), _lift(a), _lift(b))),
    max: lambda a, b: SymbolicValue(cond.If(
        pr.GreaterThanOrEqual(_lift(a), _lift(b)), _lift(a), _lift(b))),
}


def _merge_returns(c: Expression, a, b) -> SymbolicValue:
    """If(cond, then, else) with None-literal dtype reconciliation."""
    if a is None and b is None:
        raise UdfCompileError("both branches return None")
    if a is None:
        a = Literal(None, _lift(b).dtype)
    elif b is None:
        b = Literal(None, _lift(a).dtype)
    ea, eb = _lift(a), _lift(b)
    ta, tb = ea.dtype, eb.dtype
    if ta is not tb:
        if isinstance(ea, Literal) and ea.value is None:
            ea = Literal(None, tb)
        elif isinstance(eb, Literal) and eb.value is None:
            eb = Literal(None, ta)
        else:
            raise UdfCompileError(
                f"branches return different types ({ta} vs {tb})")
    return SymbolicValue(cond.If(c, ea, eb))


class _Frame:
    """One symbolic execution path (State analogue)."""

    def __init__(self, code, instrs: List[dis.Instruction],
                 by_offset: Dict[int, int], globals_: dict,
                 closure_vals: dict, budget: List[int]):
        self.code = code
        self.instrs = instrs
        self.by_offset = by_offset
        self.globals = globals_
        self.closure = closure_vals
        self.budget = budget  # [forks_left, steps_left]

    def run(self, pos: int, stack: list, local: dict):
        """Execute from instruction index ``pos`` until RETURN; returns
        the returned value (SymbolicValue or concrete)."""
        instrs = self.instrs
        while True:
            self.budget[1] -= 1
            if self.budget[1] <= 0:
                raise UdfCompileError("instruction budget exceeded")
            ins = instrs[pos]
            op = ins.opname

            if op in ("RESUME", "NOP", "CACHE", "PRECALL", "PUSH_NULL",
                      "MAKE_CELL", "COPY_FREE_VARS", "EXTENDED_ARG"):
                if op == "PUSH_NULL":
                    stack.append(_NULL_SENTINEL)
                pos += 1
                continue
            if op == "POP_TOP":
                stack.pop()
                pos += 1
                continue
            if op == "COPY":
                stack.append(stack[-ins.arg])
                pos += 1
                continue
            if op == "SWAP":
                stack[-1], stack[-ins.arg] = stack[-ins.arg], stack[-1]
                pos += 1
                continue
            if op in ("LOAD_FAST", "LOAD_FAST_CHECK",
                      "LOAD_FAST_AND_CLEAR"):
                if ins.argval not in local:
                    raise UdfCompileError(
                        f"read of unbound local {ins.argval!r}")
                stack.append(local[ins.argval])
                pos += 1
                continue
            if op == "STORE_FAST":
                local[ins.argval] = stack.pop()
                pos += 1
                continue
            if op == "LOAD_CONST":
                stack.append(ins.argval)
                pos += 1
                continue
            if op == "RETURN_CONST":
                return ins.argval
            if op == "RETURN_VALUE":
                return stack.pop()
            if op == "LOAD_GLOBAL":
                # 3.11+: bit0 of arg = "push NULL for a call"
                name = ins.argval
                if name in self.globals:
                    v = self.globals[name]
                elif hasattr(__builtins__, name) if not isinstance(
                        __builtins__, dict) else name in __builtins__:
                    v = (__builtins__[name] if isinstance(__builtins__,
                                                          dict)
                         else getattr(__builtins__, name))
                else:
                    raise UdfCompileError(f"unknown global {name!r}")
                if ins.arg & 1:
                    stack.append(_NULL_SENTINEL)
                stack.append(v)
                pos += 1
                continue
            if op == "LOAD_DEREF":
                if ins.argval not in self.closure:
                    raise UdfCompileError(
                        f"unknown closure var {ins.argval!r}")
                stack.append(self.closure[ins.argval])
                pos += 1
                continue
            if op in ("LOAD_ATTR", "LOAD_METHOD"):
                obj = stack.pop()
                name = ins.argval
                is_method = op == "LOAD_METHOD" or (ins.arg & 1)
                try:
                    attr = getattr(obj, name)
                except (AttributeError, UdfCompileError) as e:
                    raise UdfCompileError(str(e))
                if is_method and op == "LOAD_ATTR":
                    # method form occupies two slots; getattr gave a
                    # BOUND method, so the self slot is our NULL marker
                    # (tolerant CALL below accepts either slot order)
                    stack.append(_NULL_SENTINEL)
                    stack.append(attr)
                else:
                    stack.append(attr)
                pos += 1
                continue
            if op == "BINARY_OP":
                b = stack.pop()
                a = stack.pop()
                fn = _BINARY_OPS.get(ins.argrepr.rstrip("=")
                                     if "=" not in ins.argrepr
                                     else ins.argrepr[:-1])
                # in-place variants ("+=") share the same semantics here
                fn = fn or _BINARY_OPS.get(ins.argrepr)
                if fn is None:
                    raise UdfCompileError(
                        f"unsupported binary op {ins.argrepr!r}")
                stack.append(self._apply(fn, a, b))
                pos += 1
                continue
            if op == "COMPARE_OP":
                b = stack.pop()
                a = stack.pop()
                key = ins.argrepr.split()[0] if ins.argrepr else ""
                fn = _COMPARE_OPS.get(key)
                if fn is None:
                    raise UdfCompileError(
                        f"unsupported comparison {ins.argrepr!r}")
                stack.append(self._apply(fn, a, b))
                pos += 1
                continue
            if op == "IS_OP":
                b = stack.pop()
                a = stack.pop()
                sym, other = (a, b) if isinstance(a, SymbolicValue) \
                    else (b, a)
                if isinstance(sym, SymbolicValue):
                    if other is not None:
                        raise UdfCompileError(
                            "`is` on traced values only supports None")
                    e = pr.IsNull(_lift(sym))
                    if ins.arg == 1:  # is not
                        e = pr.IsNotNull(_lift(sym))
                    stack.append(SymbolicValue(e))
                else:
                    r = a is b
                    stack.append(r != bool(ins.arg))
                pos += 1
                continue
            if op == "CONTAINS_OP":
                container = stack.pop()
                item = stack.pop()
                if isinstance(container, SymbolicValue):
                    raise UdfCompileError(
                        "`in <traced string>` unsupported; use "
                        ".contains()")
                if not isinstance(item, SymbolicValue):
                    r = item in container
                    stack.append(r != bool(ins.arg))
                else:
                    vals = list(container)
                    if not all(isinstance(v, (int, float, str, bool,
                                              type(None)))
                               for v in vals):
                        raise UdfCompileError(
                            "`in` container must hold literals")
                    e: Expression = pr.In(_lift(item),
                                          [Literal(v) for v in vals])
                    if ins.arg == 1:  # not in
                        e = pr.Not(e)
                    stack.append(SymbolicValue(e))
                pos += 1
                continue
            if op == "UNARY_NEGATIVE":
                stack.append(self._apply(lambda a: -a, stack.pop()))
                pos += 1
                continue
            if op == "UNARY_NOT":
                a = stack.pop()
                if isinstance(a, SymbolicValue):
                    stack.append(SymbolicValue(pr.Not(_lift(a))))
                else:
                    stack.append(not a)
                pos += 1
                continue
            if op == "UNARY_INVERT":
                stack.append(self._apply(lambda a: ~a, stack.pop()))
                pos += 1
                continue
            if op in ("BUILD_TUPLE", "BUILD_LIST"):
                n = ins.arg
                vals = stack[len(stack) - n:] if n else []
                del stack[len(stack) - n:]
                stack.append(tuple(vals) if op == "BUILD_TUPLE"
                             else list(vals))
                pos += 1
                continue
            if op == "CALL":
                argc = ins.arg
                args = stack[len(stack) - argc:] if argc else []
                del stack[len(stack) - argc:]
                # two slots below the args: callable + self-or-NULL, in
                # either order (our LOAD_GLOBAL/LOAD_ATTR emulation
                # always fills the self slot with the NULL marker)
                x = stack.pop()
                if x is _NULL_SENTINEL:
                    callee = stack.pop()
                elif stack and stack[-1] is _NULL_SENTINEL:
                    stack.pop()
                    callee = x
                else:
                    callee = x
                stack.append(self._call(callee, args))
                pos += 1
                continue
            if op in ("JUMP_FORWARD", "JUMP_BACKWARD_NO_INTERRUPT"):
                pos = self.by_offset[ins.argval]
                continue
            if op == "JUMP_BACKWARD":
                raise UdfCompileError("loops are not compilable")
            if op in ("POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE",
                      "POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE"):
                c = stack.pop()
                if not isinstance(c, SymbolicValue):
                    taken = self._concrete_jump(op, c)
                    pos = self.by_offset[ins.argval] if taken else pos + 1
                    continue
                ce = self._jump_condition(op, c)
                self.budget[0] -= 1
                if self.budget[0] <= 0:
                    raise UdfCompileError("too many branches")
                # fork: taken path vs fall-through, merged at return.
                # NULL-condition semantics must match row-wise Python
                # (None is falsy): If(cond, a, b) picks b when cond is
                # NULL, so the jump-on-false branch must sit in the
                # ELSE slot with the UN-negated condition — negating
                # would send NULL rows down the then-path instead
                taken_r = self.run(self.by_offset[ins.argval],
                                   list(stack), dict(local))
                fall_r = self.run(pos + 1, list(stack), dict(local))
                if op in ("POP_JUMP_IF_FALSE",):
                    return _merge_returns(ce, fall_r, taken_r)
                return _merge_returns(ce, taken_r, fall_r)
            raise UdfCompileError(f"unsupported opcode {op}")

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _apply(fn, *vals):
        try:
            return fn(*vals)
        except UdfCompileError:
            raise
        except Exception as e:
            raise UdfCompileError(str(e))

    def _call(self, callee, args):
        if callee is _NULL_SENTINEL:
            raise UdfCompileError("malformed call")
        handler = _KNOWN_CALLS.get(callee)
        if handler is not None:
            if any(isinstance(a, SymbolicValue) for a in args):
                return self._apply(handler, *args)
            return self._apply(callee, *args)
        # bound methods of SymbolicValue (upper/replace/...) and
        # sym_if-style helpers execute directly
        self_obj = getattr(callee, "__self__", None)
        if isinstance(self_obj, SymbolicValue) or \
                getattr(callee, "__module__", "").startswith(
                    "spark_rapids_tpu"):
            return self._apply(callee, *args)
        if not any(isinstance(a, SymbolicValue) for a in args) and \
                not isinstance(callee, SymbolicValue):
            return self._apply(callee, *args)  # pure-constant call
        raise UdfCompileError(
            f"call to unrecognized function "
            f"{getattr(callee, '__name__', callee)!r}")

    @staticmethod
    def _concrete_jump(op: str, c) -> bool:
        if op == "POP_JUMP_IF_FALSE":
            return not c
        if op == "POP_JUMP_IF_TRUE":
            return bool(c)
        if op == "POP_JUMP_IF_NONE":
            return c is None
        return c is not None

    @staticmethod
    def _jump_condition(op: str, c: SymbolicValue) -> Expression:
        e = _lift(c)
        if op in ("POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE"):
            return pr.IsNull(e) if op == "POP_JUMP_IF_NONE" \
                else pr.IsNotNull(e)
        if e.dtype is not dt.BOOLEAN:
            # Python truthiness of non-boolean traced values (0/""-is-
            # false) is NOT SQL boolean semantics — refuse, don't guess
            raise UdfCompileError(
                "branch on a non-boolean traced value")
        # both jump flavors keep the UN-negated condition; the caller
        # places the branches so NULL lands on the Python-falsy path
        return e


_NULL_SENTINEL = object()


def compile_udf_bytecode(fn, args: Sequence[Expression]
                         ) -> Optional[Expression]:
    """Symbolically execute ``fn``'s bytecode over Expression arguments;
    None when the function escapes the compilable subset."""
    try:
        code = fn.__code__
    except AttributeError:
        return None
    if code.co_kwonlyargcount or code.co_flags & 0x0C:  # *args/**kw
        return None
    if code.co_argcount != len(args):
        return None
    try:
        instrs = [i for i in dis.get_instructions(fn)]
    except Exception:
        return None
    by_offset = {ins.offset: idx for idx, ins in enumerate(instrs)}
    closure_vals = {}
    if fn.__closure__:
        for name, cell in zip(code.co_freevars, fn.__closure__):
            try:
                closure_vals[name] = cell.cell_contents
            except ValueError:
                return None
    local = {name: SymbolicValue(a)
             for name, a in zip(code.co_varnames, args)}
    frame = _Frame(code, instrs, by_offset, fn.__globals__,
                   closure_vals, [_MAX_FORKS, _MAX_STEPS])
    try:
        out = frame.run(0, [], local)
        return _lift(out)
    except UdfCompileError:
        return None
    except Exception:
        return None
