"""ML framework handoff (the ColumnarRdd / InternalColumnarRddConverter
surface, SURVEY.md §2.6: ColumnarRdd.scala:20-49 exposes RDD[Table] so
XGBoost builds DMatrix from GPU memory without a row round-trip)."""
from spark_rapids_tpu.ml.handoff import (DeviceBatchesSource,
                                         batch_to_torch,
                                         collect_feature_matrix,
                                         exec_to_device_matrices,
                                         from_device_arrays)

__all__ = ["DeviceBatchesSource", "batch_to_torch",
           "collect_feature_matrix", "exec_to_device_matrices",
           "from_device_arrays"]
