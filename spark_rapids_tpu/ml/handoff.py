"""Zero-copy-where-possible handoff of columnar results to ML frameworks.

The reference's ColumnarRdd gives XGBoost the raw device tables
(ColumnarRdd.scala:20-49); the TPU analogue hands jax arrays (or torch
tensors via dlpack) straight from the exec pipeline — BASELINE config #5's
ETL -> DMatrix flow.
"""
from __future__ import annotations

from typing import Iterator, List, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.base import TpuExec


def exec_to_device_matrices(exec_: TpuExec
                            ) -> Iterator[Tuple[jax.Array, jax.Array]]:
    """Stream (features, validity) float32 device matrices per batch —
    rows trimmed to the live count, columns = the exec's numeric outputs.
    The RDD[Table] analogue: consumers keep everything on device."""
    numeric = [i for i, t in enumerate(exec_.schema.types)
               if t.is_numeric or t is dt.BOOLEAN]
    if not numeric:
        raise ValueError("no numeric columns to hand off")
    for p in range(exec_.num_partitions):
        for b in exec_.execute(p):
            n = b.realized_num_rows()
            if n == 0:
                continue
            cols = []
            valids = []
            for i in numeric:
                c = b.columns[i]
                cols.append(c.data[:n].astype(jnp.float32))
                v = c.validity
                valids.append(jnp.ones(n, dtype=bool) if v is None
                              else v[:n])
            yield jnp.stack(cols, axis=1), jnp.stack(valids, axis=1)


def collect_feature_matrix(exec_: TpuExec) -> jax.Array:
    """One (rows, features) float32 device matrix from the whole exec
    (the DMatrix build input). NULLs become NaN — XGBoost's missing-value
    convention."""
    mats = []
    for feats, valid in exec_to_device_matrices(exec_):
        mats.append(jnp.where(valid, feats, jnp.nan))
    if not mats:
        ncols = sum(1 for t in exec_.schema.types
                    if t.is_numeric or t is dt.BOOLEAN)
        return jnp.zeros((0, ncols), dtype=jnp.float32)
    return jnp.concatenate(mats, axis=0)


from spark_rapids_tpu.plan.nodes import DataSource


class DeviceBatchesSource(DataSource):
    """DataSource over ALREADY-DEVICE-RESIDENT batches — the reverse
    ColumnarRdd path (InternalColumnarRddConverter.scala: build a
    DataFrame from a GPU RDD without a row round trip). The TPU exec
    yields the batches as-is; only the CPU oracle materializes host
    copies."""

    def __init__(self, batches, schema):
        self.batches = list(batches)
        self._schema = schema

    def schema(self):
        return self._schema

    def num_splits(self) -> int:
        return max(len(self.batches), 1)

    def read_host_split(self, split: int):
        from spark_rapids_tpu.execs.interop import batch_to_frame
        from spark_rapids_tpu.io.arrow_conv import empty_host

        if not self.batches:
            return empty_host(self._schema)
        frame = batch_to_frame(self.batches[split], self._schema)
        data, validity = {}, {}
        for i, name in enumerate(self._schema.names):
            c = frame.cols[i]
            data[name] = c.data
            validity[name] = c.valid_mask()
        return data, validity

    def read_host(self):
        from spark_rapids_tpu.io.arrow_conv import concat_host

        return concat_host([self.read_host_split(i)
                            for i in range(len(self.batches))],
                           self._schema)


def from_device_arrays(session, arrays, names: List[str],
                       dtypes: List[dt.DType], validities=None):
    """DataFrame over jax (or dlpack-importable, e.g. torch) device
    arrays — zero-copy where backends share memory."""
    from spark_rapids_tpu.api.dataframe import DataFrame
    from spark_rapids_tpu.columnar.batch import Schema
    from spark_rapids_tpu.columnar.column import Column
    from spark_rapids_tpu.plan import nodes as pn

    cols = []
    n = None
    vin = validities or [None] * len(arrays)
    for a, t, v in zip(arrays, dtypes, vin):
        if not isinstance(a, jax.Array):
            try:
                a = jnp.from_dlpack(a)
            except Exception:
                import numpy as _np

                a = jnp.asarray(_np.asarray(a))
        n = int(a.shape[0]) if n is None else n
        cols.append(Column(t, a.astype(t.kernel_dtype),
                           None if v is None else jnp.asarray(v)))
    batch = ColumnarBatch(cols, n or 0)
    schema = Schema(names, dtypes)
    src = DeviceBatchesSource([batch], schema)
    return DataFrame(pn.ScanNode(src), session)


def batch_to_torch(batch: ColumnarBatch, schema_types: List[dt.DType]):
    """Device batch -> dict of torch tensors, dlpack zero-copy when the
    backends share memory (CPU<->CPU), explicit copy otherwise."""
    import torch

    n = batch.realized_num_rows()
    out = {}
    for i, (c, t) in enumerate(zip(batch.columns, schema_types)):
        if t is dt.STRING:
            continue  # torch has no string tensors; keep numerics
        arr = c.data[:max(n, 1)][:n]
        try:
            tensor = torch.from_dlpack(arr)
        except Exception:
            import numpy as np

            tensor = torch.from_numpy(np.asarray(jax.device_get(arr)))
        out[i] = tensor
    return out
