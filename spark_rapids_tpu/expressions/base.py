"""Expression layer core.

TPU-native analogue of ``GpuExpression.columnarEval`` (reference
sql-plugin/.../GpuExpressions.scala): expressions evaluate over columnar
batches producing a column or a scalar. The crucial TPU twist: evaluation is
split into

- a **fused device path**: any subtree whose nodes are ``device_only``
  evaluates inside ONE jitted function over raw ``(data, validity)`` arrays —
  an entire project/filter pipeline becomes a single XLA executable (the
  reference instead launches one cuDF kernel per operator node);
- an **eager path** for nodes needing host-side metadata (string dictionary
  transforms): still device compute (gathers/remaps), dispatched op-by-op.

``expressions/compiler.py`` picks the path per tree.

Null semantics follow Spark SQL three-valued logic: unless a node overrides,
output validity = AND of input validities.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, Scalar, StringColumn


@dataclasses.dataclass
class ColV:
    """A column value during evaluation: raw arrays plus (eager mode only)
    the source StringColumn for dictionary access."""

    dtype: dt.DType
    data: jax.Array
    validity: Optional[jax.Array]
    scol: Optional[StringColumn] = None  # dictionary carrier (eager mode)

    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    def to_column(self) -> Column:
        if self.dtype is dt.STRING and self.scol is not None:
            return StringColumn(self.data, self.scol.dictionary,
                                self.validity)
        return Column(self.dtype, self.data, self.validity)


EvalValue = Union[ColV, Scalar]


class EvalContext:
    """What an expression sees during evaluation."""

    def __init__(self, columns: List[ColV], capacity: int, num_rows,
                 conf=None, in_jit: bool = False, task_info=None,
                 origin=None):
        self.columns = columns
        self.capacity = capacity
        self.num_rows = num_rows
        self.conf = conf
        self.in_jit = in_jit
        self.task_info = task_info  # partition id etc (nondeterministic exprs)
        self.origin = origin  # (file, block_start, block_len) above scans

    @staticmethod
    def from_batch(batch: ColumnarBatch, conf=None,
                   task_info=None) -> "EvalContext":
        cols = []
        for c in batch.columns:
            scol = c if isinstance(c, StringColumn) else None
            cols.append(ColV(c.dtype, c.data, c.validity, scol))
        return EvalContext(cols, batch.capacity, batch.num_rows_device(),
                           conf=conf, task_info=task_info,
                           origin=batch.origin)


class Expression:
    """Base expression node."""

    def __init__(self, children: Sequence["Expression"] = ()):
        self.children = list(children)

    def tree_key(self):
        """Hashable structural fingerprint, or None when this tree can't
        be keyed. Two expressions with equal keys compile to the same
        fused kernel, so CompiledProjection/CompiledFilter share one
        jitted function across plan instances (a fresh plan per query —
        the reference's per-query GpuOverrides pass — must not re-trace
        every projection)."""
        params = []
        for k in sorted(vars(self)):
            if k == "children":
                continue
            v = vars(self)[k]
            private = k.startswith("_")
            if isinstance(v, (float, np.floating)):
                # repr keys: NaN would never dict-hit (NaN != NaN, so
                # every lookup misses and the cache only grows) and
                # -0.0 == 0.0 would alias two semantically different
                # constants onto one kernel
                params.append((k, ("#f", repr(float(v)))))
            elif isinstance(v, (int, str, bool, bytes, type(None))):
                params.append((k, v))
            elif isinstance(v, (np.integer, np.bool_)):
                params.append((k, ("#np", v.item())))
            elif isinstance(v, (list, tuple)) and all(
                    isinstance(x, (int, str, bool, type(None)))
                    for x in v):
                params.append((k, ("#seq",) + tuple(v)))
            elif isinstance(v, (list, tuple)) and all(
                    isinstance(x, (int, float, str, bool, type(None)))
                    for x in v):
                params.append((k, ("#seq",) + tuple(
                    ("#f", repr(float(x)))
                    if isinstance(x, float) else x for x in v)))
            elif isinstance(v, (list, tuple)) and all(
                    isinstance(x, Expression) for x in v):
                subs = tuple(x.tree_key() for x in v)
                if any(s is None for s in subs):
                    return None
                params.append((k, ("#exprs",) + subs))
            elif hasattr(v, "name") and hasattr(v, "kernel_dtype"):
                params.append((k, ("#dtype", v.name)))
            elif isinstance(v, Expression):
                sub = v.tree_key()
                if sub is None:
                    return None
                params.append((k, sub))
            elif private:
                continue  # private unkeyable attrs are caches, not params
            else:
                return None  # unkeyable payload (arrays, callables, ...)
        kids = []
        for c in self.children:
            if c is None:
                kids.append(None)
                continue
            ck = c.tree_key()
            if ck is None:
                return None
            kids.append(ck)
        return (type(self).__module__, type(self).__qualname__,
                tuple(params), tuple(kids))

    # -- static properties -------------------------------------------------

    @property
    def dtype(self) -> dt.DType:
        raise NotImplementedError

    @property
    def nullable(self) -> bool:
        return any(c.nullable for c in self.children)

    @property
    def device_only(self) -> bool:
        """True if this node evaluates purely on (data, validity) arrays —
        i.e. is legal inside jit. String-dictionary ops return False."""
        return all(c.device_only for c in self.children)

    @property
    def deterministic(self) -> bool:
        return all(c.deterministic for c in self.children)

    @property
    def name(self) -> str:
        return type(self).__name__

    # -- evaluation --------------------------------------------------------

    def eval(self, ctx: EvalContext) -> EvalValue:
        raise NotImplementedError

    # -- tree utilities ----------------------------------------------------

    def transform(self, fn: Callable[["Expression"], "Expression"]
                  ) -> "Expression":
        new_children = [c.transform(fn) for c in self.children]
        node = self
        if new_children != self.children:
            node = self._with_children(new_children)
        return fn(node)

    def _with_children(self, children: List["Expression"]) -> "Expression":
        import copy

        node = copy.copy(self)
        node.children = children
        return node

    def collect(self, pred: Callable[["Expression"], bool]
                ) -> List["Expression"]:
        out = [self] if pred(self) else []
        for c in self.children:
            out.extend(c.collect(pred))
        return out

    def references(self) -> List[int]:
        """Ordinals of all bound references under this node."""
        return sorted({e.ordinal for e in self.collect(
            lambda n: isinstance(n, BoundReference))})

    def __repr__(self) -> str:  # pragma: no cover
        if self.children:
            return f"{self.name}({', '.join(map(repr, self.children))})"
        return self.name


class LeafExpression(Expression):
    def __init__(self):
        super().__init__(())


class BoundReference(LeafExpression):
    """Ordinal-bound input column (GpuBoundReference analogue,
    GpuBoundAttribute.scala)."""

    def __init__(self, ordinal: int, dtype: dt.DType, nullable: bool = True):
        super().__init__()
        self.ordinal = ordinal
        self._dtype = dtype
        self._nullable = nullable

    @property
    def dtype(self) -> dt.DType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self._nullable

    @property
    def device_only(self) -> bool:
        return True

    def eval(self, ctx: EvalContext) -> EvalValue:
        return ctx.columns[self.ordinal]

    def __repr__(self) -> str:  # pragma: no cover
        return f"input[{self.ordinal}:{self._dtype}]"


class Literal(LeafExpression):
    """Typed literal (GpuLiteral analogue, literals.scala)."""

    def __init__(self, value, dtype: Optional[dt.DType] = None):
        super().__init__()
        if dtype is None:
            dtype = _infer_literal_type(value)
        self._dtype = dtype
        self.value = value

    @property
    def dtype(self) -> dt.DType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self.value is None

    @property
    def device_only(self) -> bool:
        return True

    def eval(self, ctx: EvalContext) -> EvalValue:
        return Scalar(self._dtype, self.value)

    def __repr__(self) -> str:  # pragma: no cover
        return f"lit({self.value!r})"


class Alias(Expression):
    """Named projection output (GpuAlias analogue)."""

    def __init__(self, child: Expression, alias: str):
        super().__init__([child])
        self.alias = alias

    @property
    def dtype(self) -> dt.DType:
        return self.children[0].dtype

    def eval(self, ctx: EvalContext) -> EvalValue:
        return self.children[0].eval(ctx)


# ---------------------------------------------------------------------------
# Evaluation helpers shared by all expression modules.
# ---------------------------------------------------------------------------

def broadcast(v: EvalValue, ctx: EvalContext) -> ColV:
    """Materialize a scalar into a column value (full capacity)."""
    if isinstance(v, ColV):
        return v
    if v.is_null:
        if v.dtype is dt.STRING:
            import numpy as np

            codes = jnp.zeros(ctx.capacity, dtype=jnp.int32)
            sc = StringColumn(codes, np.array([], dtype=object),
                              jnp.zeros(ctx.capacity, dtype=bool))
            return ColV(dt.STRING, codes, sc.validity, sc)
        return ColV(v.dtype, jnp.zeros(ctx.capacity,
                                       dtype=v.dtype.kernel_dtype),
                    jnp.zeros(ctx.capacity, dtype=bool))
    if v.dtype is dt.STRING:
        sc = StringColumn.from_strings([v.value] * 1, capacity=ctx.capacity)
        data = jnp.zeros(ctx.capacity, dtype=jnp.int32)
        return ColV(dt.STRING, data, None, StringColumn(
            data, sc.dictionary, None))
    return ColV(v.dtype, jnp.full(ctx.capacity, v.value,
                                  dtype=v.dtype.kernel_dtype), None)


def and_validity(*vs: Optional[jax.Array]) -> Optional[jax.Array]:
    out = None
    for v in vs:
        if v is None:
            continue
        out = v if out is None else (out & v)
    return out


def scalar_data(v: EvalValue):
    """jnp-compatible raw operand: scalar -> python value, ColV -> array."""
    if isinstance(v, Scalar):
        return jnp.asarray(v.value, dtype=v.dtype.kernel_dtype)
    return v.data


def value_validity(v: EvalValue) -> Optional[jax.Array]:
    if isinstance(v, Scalar):
        return None  # null scalars are special-cased by callers
    return v.validity


def eval_unary(expr: Expression, ctx: EvalContext, fn,
               out_dtype: dt.DType, null_out=None) -> EvalValue:
    """Standard unary: null in -> null out (GpuUnaryExpression analogue)."""
    v = expr.children[0].eval(ctx)
    if isinstance(v, Scalar):
        if v.is_null:
            return Scalar(out_dtype, None)
        r = fn(jnp.asarray(v.value, dtype=v.dtype.kernel_dtype))
        return Scalar(out_dtype, _to_py(r, out_dtype))
    return ColV(out_dtype, fn(v.data).astype(out_dtype.kernel_dtype),
                v.validity)


def eval_binary(expr: Expression, ctx: EvalContext, fn,
                out_dtype: dt.DType) -> EvalValue:
    """Standard binary: null if either side null
    (GpuBinaryExpression analogue)."""
    a = expr.children[0].eval(ctx)
    b = expr.children[1].eval(ctx)
    if isinstance(a, Scalar) and isinstance(b, Scalar):
        if a.is_null or b.is_null:
            return Scalar(out_dtype, None)
        r = fn(jnp.asarray(a.value, a.dtype.kernel_dtype),
               jnp.asarray(b.value, b.dtype.kernel_dtype))
        return Scalar(out_dtype, _to_py(r, out_dtype))
    if (isinstance(a, Scalar) and a.is_null) or \
            (isinstance(b, Scalar) and b.is_null):
        return Scalar(out_dtype, None)
    data = fn(scalar_data(a), scalar_data(b))
    validity = and_validity(value_validity(a), value_validity(b))
    return ColV(out_dtype, data.astype(out_dtype.kernel_dtype), validity)


def _to_py(x, out_dtype: dt.DType):
    v = jax.device_get(x)
    if out_dtype is dt.BOOLEAN:
        return bool(v)
    if out_dtype.is_floating:
        return float(v)
    return int(v)


def _infer_literal_type(value) -> dt.DType:
    if value is None:
        raise ValueError("untyped null literal; pass dtype explicitly")
    if isinstance(value, bool):
        return dt.BOOLEAN
    if isinstance(value, int):
        return dt.INT64 if not (-2**31 <= value < 2**31) else dt.INT32
    if isinstance(value, float):
        return dt.FLOAT64
    if isinstance(value, str):
        return dt.STRING
    raise TypeError(f"cannot infer literal type for {value!r}")
