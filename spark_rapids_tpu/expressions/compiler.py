"""Expression tree -> XLA fusion compiler.

The reference launches one cuDF kernel per expression node
(GpuExpressions.scala columnarEval chains). On TPU that would be a dispatch
per node; instead, any projection/filter whose nodes are all ``device_only``
compiles into ONE jitted function over the batch's raw arrays — XLA fuses
the whole tree into a single executable (usually a single fused loop over
HBM). Trees containing dictionary-dependent string ops fall back to eager
per-node evaluation (still device compute, host dictionary transforms).
"""
from __future__ import annotations

import threading
from spark_rapids_tpu.utils import lockorder
from functools import partial
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, Scalar, StringColumn
from spark_rapids_tpu.expressions.base import (
    Alias,
    BoundReference,
    ColV,
    EvalContext,
    Expression,
    Literal,
    broadcast,
)


#: fused-kernel reuse across plan instances: every query gets a FRESH
#: plan/exec tree (the per-query override pass), but two structurally
#: identical projections must share ONE jitted function or each query
#: re-traces (and re-loads) every kernel. Keyed by Expression.tree_key.
_FUSED_CACHE: dict = {}
_FUSED_CACHE_MAX = 1024
#: hit/miss telemetry surfaced by utils/progcache.stats(): a miss is a
#: fresh trace (and, cold, an XLA compile); a None key can never cache
_FUSED_CACHE_STATS = {"hits": 0, "misses": 0, "unkeyed": 0}
#: single-flight coordination: key -> Event while a builder traces it.
#: Guarded (with _FUSED_CACHE and its stats) by _FUSED_CACHE_LOCK —
#: the cross-tenant compile fence requires that N concurrent queries
#: racing one program key trace/compile it at most ONCE; the old
#: unlocked get/build/put raced N tracers to the same slot.
_FUSED_CACHE_LOCK = lockorder.make_lock("expressions.fusedCache")
_FUSED_BUILDING: dict = {}


def _fused_cache_get(key):
    if key is None:
        _FUSED_CACHE_STATS["unkeyed"] += 1
        return None
    with _FUSED_CACHE_LOCK:
        fn = _FUSED_CACHE.get(key)
        if fn is not None:
            _FUSED_CACHE_STATS["hits"] += 1
        else:
            _FUSED_CACHE_STATS["misses"] += 1
        return fn


def _fused_cache_put(key, fn):
    if key is None:
        return
    with _FUSED_CACHE_LOCK:
        if len(_FUSED_CACHE) >= _FUSED_CACHE_MAX:
            _FUSED_CACHE.clear()  # crude bound; keys tiny, fns are jits
        _FUSED_CACHE[key] = fn


def fused_cache_get_or_build(key, builder):
    """Single-flight lookup: at most one thread runs ``builder()`` per
    key; concurrent losers WAIT for the winner's program and count as
    hits (they got the shared executable — the multi-tenant outcome
    the progcache hit-rate fence measures). A failed build releases the
    key so a later caller may retry."""
    if key is None:
        _FUSED_CACHE_STATS["unkeyed"] += 1
        return builder()
    while True:
        with _FUSED_CACHE_LOCK:
            fn = _FUSED_CACHE.get(key)
            if fn is not None:
                _FUSED_CACHE_STATS["hits"] += 1
                return fn
            ev = _FUSED_BUILDING.get(key)
            if ev is None:
                ev = _FUSED_BUILDING[key] = threading.Event()
                _FUSED_CACHE_STATS["misses"] += 1
                building = True
            else:
                building = False
        if not building:
            # the winner is tracing: wait, then loop to pick its
            # program up (or claim the build if it failed)
            ev.wait(timeout=120)
            continue
        try:
            fn = builder()
        except BaseException:
            with _FUSED_CACHE_LOCK:
                _FUSED_BUILDING.pop(key, None)
            ev.set()
            raise
        with _FUSED_CACHE_LOCK:
            if len(_FUSED_CACHE) >= _FUSED_CACHE_MAX:
                _FUSED_CACHE.clear()
            _FUSED_CACHE[key] = fn
            _FUSED_BUILDING.pop(key, None)
        ev.set()
        return fn


def _unwrap_alias(e: Expression) -> Expression:
    while isinstance(e, Alias):
        e = e.children[0]
    return e


def _passthrough_ref(e: Expression) -> Optional[int]:
    e = _unwrap_alias(e)
    if isinstance(e, BoundReference):
        return e.ordinal
    return None


_I64 = (-(1 << 63), (1 << 63) - 1)


def derive_stats(e: Expression, cols) -> Optional[tuple]:
    """Host-known (min, max) of a projected expression, derived from the
    input columns' stats where the transform's bounds are computable:
    refs/aliases, casts between discrete types, +/-/* by integer
    literals, pmod by a positive literal, year() of a date. Conservative
    None everywhere else. This keeps the packed-key groupby path alive
    through projections like ``GROUP BY k % 4`` or ``year(d)``
    (round-2 verdict: stats died at the first projection)."""
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.expressions import arithmetic as ar
    from spark_rapids_tpu.expressions import datetime as dte
    from spark_rapids_tpu.expressions.cast import Cast

    e = _unwrap_alias(e)
    if isinstance(e, BoundReference):
        return getattr(cols[e.ordinal], "stats", None)
    if isinstance(e, Cast):
        if not (e.to.is_integral or e.to in (dt.DATE, dt.TIMESTAMP)):
            return None
        src_t = e.children[0].dtype
        if (src_t is dt.TIMESTAMP) != (e.to is dt.TIMESTAMP):
            # date<->timestamp casts SCALE (days vs microseconds);
            # passing bounds through unscaled corrupts packed keys
            return None
        s = derive_stats(e.children[0], cols)
        if s is None:
            return None
        lo, hi = int(s[0]), int(s[1])
        if e.to.is_integral:
            import numpy as np

            info = np.iinfo(e.to.np_dtype)
            if lo < info.min or hi > info.max:
                return None  # would wrap; bounds no longer hold
        return (lo, hi)
    if isinstance(e, (ar.Add, ar.Subtract, ar.Multiply)):
        sides = []
        for c in e.children:
            if isinstance(c, Literal) and isinstance(c.value, int):
                sides.append(("lit", c.value))
            else:
                s = derive_stats(c, cols)
                if s is None:
                    return None
                sides.append(("col", s))
        kinds = [k for k, _ in sides]
        if kinds == ["lit", "lit"]:
            a, b = sides[0][1], sides[1][1]
            v = (a + b if isinstance(e, ar.Add) else
                 a - b if isinstance(e, ar.Subtract) else a * b)
            return (v, v)
        if "lit" not in kinds:
            return None  # col-op-col bounds not tracked
        (ka, va), (kb, vb) = sides
        if ka == "lit":
            lit, (lo, hi) = va, vb
            if isinstance(e, ar.Subtract):
                lo, hi = lit - hi, lit - lo
            elif isinstance(e, ar.Add):
                lo, hi = lo + lit, hi + lit
            else:
                lo, hi = sorted((lo * lit, hi * lit))
        else:
            (lo, hi), lit = va, vb
            if isinstance(e, ar.Subtract):
                lo, hi = lo - lit, hi - lit
            elif isinstance(e, ar.Add):
                lo, hi = lo + lit, hi + lit
            else:
                lo, hi = sorted((lo * lit, hi * lit))
        # bounds must fit the EXPRESSION dtype: int32 arithmetic that
        # wraps on device must not advertise unwrapped bounds
        if e.dtype.is_integral:
            import numpy as np

            info = np.iinfo(e.dtype.np_dtype)
            if lo < info.min or hi > info.max:
                return None
        elif lo < _I64[0] or hi > _I64[1]:
            return None
        return (lo, hi)
    if isinstance(e, ar.Pmod):
        m = e.children[1]
        if isinstance(m, Literal) and isinstance(m.value, int) \
                and m.value > 0:
            return (0, m.value - 1)
        return None
    if isinstance(e, dte.Year):
        s = derive_stats(e.children[0], cols)
        if s is None or e.children[0].dtype is not dt.DATE:
            return None
        import numpy as np

        base = np.datetime64("1970-01-01", "D")
        y = [(base + np.timedelta64(int(v), "D")).astype(
            "datetime64[Y]").astype(int) + 1970 for v in s[:2]]
        return (int(y[0]), int(y[1]))  # year() is monotone over days
    return None


class CompiledProjection:
    """Callable batch->batch for a fixed projection list."""

    def __init__(self, exprs: Sequence[Expression], conf=None):
        self.exprs = list(exprs)
        self.conf = conf
        self.fused = all(e.device_only for e in self.exprs)
        if self.fused:
            key = None
            # Alias is an eval passthrough — key on the unwrapped tree so
            # q5's Alias(rev) and q10's Alias(revenue) share one kernel
            kparts = tuple(_unwrap_alias(e).tree_key()
                           for e in self.exprs)
            if all(k is not None for k in kparts):
                key = ("projection", kparts)
            self._jit = fused_cache_get_or_build(key,
                                                 self._build_fused)

    def _build_fused(self):
        exprs = self.exprs

        @partial(jax.jit, static_argnames=("types",))
        def run(datas, validities, num_rows, task, types):
            capacity = datas[0].shape[0] if datas else 128
            cols = [ColV(t, d, v) for (t, d, v) in
                    zip(types, datas, validities)]
            ctx = EvalContext(cols, capacity, num_rows, in_jit=True,
                              task_info=task)
            outs = []
            for e in exprs:
                v = e.eval(ctx)
                o = broadcast(v, ctx)
                outs.append((o.data, o.validity))
            return outs

        return run

    # ships inside remote map-task closures; the jitted program is
    # process-local state and rebuilds (or re-hits the fused cache) on
    # the receiving executor
    def __getstate__(self):
        return {"exprs": self.exprs, "conf": self.conf}

    def __setstate__(self, state):
        self.__init__(state["exprs"], state["conf"])

    def __call__(self, batch: ColumnarBatch,
                 task_info=None) -> ColumnarBatch:
        from spark_rapids_tpu.expressions.nondeterministic import TaskInfo

        if task_info is None:
            task_info = TaskInfo.make()
        if self.fused:
            datas = [c.data for c in batch.columns]
            validities = [c.validity for c in batch.columns]
            types = tuple(c.dtype for c in batch.columns)
            outs = self._jit(datas, validities, batch.num_rows_device(),
                             task_info, types)
            cols = []
            for e, (data, validity) in zip(self.exprs, outs):
                if e.dtype is dt.STRING:
                    ref = _passthrough_ref(e)
                    if ref is not None:
                        src = batch.columns[ref]
                        assert isinstance(src, StringColumn)
                        cols.append(StringColumn(data, src.dictionary,
                                                 validity))
                        continue
                    lit = _unwrap_alias(e)
                    assert isinstance(lit, Literal), \
                        "device_only string expr must be a ref or literal"
                    import numpy as np

                    dictionary = np.array(
                        [] if lit.value is None else [lit.value],
                        dtype=object)
                    cols.append(StringColumn(data, dictionary, validity))
                else:
                    col = Column(e.dtype, data, validity)
                    # stats flow through refs AND derivable transforms
                    # (+c, *c, pmod, casts, year) so downstream groupbys
                    # keep the packed-key sort
                    col.stats = derive_stats(e, batch.columns)
                    cols.append(col)
            return ColumnarBatch(cols, batch.num_rows)
        # eager path
        ctx = EvalContext.from_batch(batch, conf=self.conf,
                                     task_info=task_info)
        cols = []
        for e in self.exprs:
            v = broadcast(e.eval(ctx), ctx)
            cols.append(v.to_column())
        return ColumnarBatch(cols, batch.num_rows)


class CompiledFilter:
    """Callable batch->batch applying a boolean condition then compacting
    (GpuFilterExec's columnarEval + tbl.filter,
    basicPhysicalOperators.scala:100-130 — here mask + compaction are two
    XLA executables; the mask fuses with any arithmetic above it)."""

    def __init__(self, condition: Expression, conf=None):
        self.condition = condition
        self.conf = conf
        self.fused = condition.device_only
        if self.fused:
            cond = condition
            key = condition.tree_key()
            key = ("filter", key) if key is not None else None

            def build_mask():
                @partial(jax.jit, static_argnames=("types",))
                def run_mask(datas, validities, num_rows, task, types):
                    capacity = datas[0].shape[0] if datas else 128
                    cols = [ColV(t, d, v) for (t, d, v) in
                            zip(types, datas, validities)]
                    ctx = EvalContext(cols, capacity, num_rows,
                                      in_jit=True, task_info=task)
                    v = broadcast(cond.eval(ctx), ctx)
                    keep = v.data
                    if v.validity is not None:
                        keep = keep & v.validity
                    return keep
                return run_mask

            self._mask = fused_cache_get_or_build(key, build_mask)

    def __getstate__(self):
        return {"condition": self.condition, "conf": self.conf}

    def __setstate__(self, state):
        self.__init__(state["condition"], state["conf"])

    def mask(self, batch: ColumnarBatch, task_info=None):
        """Keep-mask only (no compaction): downstream sorts/groupbys fuse
        it as a live_mask, skipping the compaction pass entirely. Fused
        conditions only."""
        from spark_rapids_tpu.expressions.nondeterministic import TaskInfo

        assert self.fused, "mask() requires a device_only condition"
        if task_info is None:
            task_info = TaskInfo.make()
        datas = [c.data for c in batch.columns]
        validities = [c.validity for c in batch.columns]
        types = tuple(c.dtype for c in batch.columns)
        return self._mask(datas, validities, batch.num_rows_device(),
                          task_info, types)

    def __call__(self, batch: ColumnarBatch,
                 task_info=None) -> ColumnarBatch:
        from spark_rapids_tpu.expressions.nondeterministic import TaskInfo
        from spark_rapids_tpu.ops.filter import compact_batch

        if task_info is None:
            task_info = TaskInfo.make()
        if self.fused:
            keep = self.mask(batch, task_info)
            return compact_batch(batch, keep)
        ctx = EvalContext.from_batch(batch, conf=self.conf,
                                     task_info=task_info)
        v = broadcast(self.condition.eval(ctx), ctx)
        return compact_batch(batch, v.data, v.validity)
