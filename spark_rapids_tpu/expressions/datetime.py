"""Date/time expressions (reference .../datetimeExpressions.scala, 560 LoC):
year/month/day/dayofweek/hour/minute/second, date +- interval, datediff,
unix_timestamp/from_unixtime, last_day. Timestamps are UTC-only int64
microseconds, dates int32 days — same internal encodings as Spark, so all
extraction is pure integer math that runs in-jit (no host calendar calls):
the civil-from-days algorithm below is the classic Howard Hinnant
public-domain integer routine.
"""
from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions.base import Expression, eval_binary, \
    eval_unary

_US_PER_DAY = 86_400_000_000
_US_PER_HOUR = 3_600_000_000
_US_PER_MIN = 60_000_000
_US_PER_SEC = 1_000_000


def _civil_from_days(z):
    """days since 1970-01-01 -> (year, month [1-12], day [1-31])."""
    z = z.astype(jnp.int64) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def _days_from_civil(y, m, d):
    y = y - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


class _DateField(Expression):
    """Extract from DATE (or TIMESTAMP via day conversion)."""

    part = None  # 'year' | 'month' | 'day'

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return dt.INT32

    def _days(self, x):
        if self.children[0].dtype is dt.TIMESTAMP:
            return jnp.floor_divide(x, _US_PER_DAY)
        return x

    def eval(self, ctx):
        part = type(self).part

        def f(x):
            y, m, d = _civil_from_days(self._days(x))
            v = {"year": y, "month": m, "day": d}[part]
            return v.astype(jnp.int32)

        return eval_unary(self, ctx, f, dt.INT32)


class Year(_DateField):
    part = "year"


class Month(_DateField):
    part = "month"


class DayOfMonth(_DateField):
    part = "day"


class _TimeField(Expression):
    divisor = None
    modulus = None

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return dt.INT32

    def eval(self, ctx):
        div, mod = type(self).divisor, type(self).modulus

        def f(x):
            tod = jnp.mod(x, _US_PER_DAY)
            return jnp.mod(tod // div, mod).astype(jnp.int32)

        return eval_unary(self, ctx, f, dt.INT32)


class Hour(_TimeField):
    divisor = _US_PER_HOUR
    modulus = 24


class Minute(_TimeField):
    divisor = _US_PER_MIN
    modulus = 60


class Second(_TimeField):
    divisor = _US_PER_SEC
    modulus = 60


class DayOfWeek(Expression):
    """1 = Sunday ... 7 = Saturday (Spark semantics)."""

    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return dt.INT32

    def eval(self, ctx):
        def f(days):
            # 1970-01-01 was a Thursday (=5 in Spark numbering)
            return (jnp.mod(days.astype(jnp.int64) + 4, 7) + 1) \
                .astype(jnp.int32)

        return eval_unary(self, ctx, f, dt.INT32)


class DateAdd(Expression):
    def __init__(self, start, days):
        super().__init__([start, days])

    @property
    def dtype(self):
        return dt.DATE

    def eval(self, ctx):
        return eval_binary(
            self, ctx,
            lambda a, b: (a.astype(jnp.int64) +
                          b.astype(jnp.int64)).astype(jnp.int32), dt.DATE)


class DateSub(Expression):
    def __init__(self, start, days):
        super().__init__([start, days])

    @property
    def dtype(self):
        return dt.DATE

    def eval(self, ctx):
        return eval_binary(
            self, ctx,
            lambda a, b: (a.astype(jnp.int64) -
                          b.astype(jnp.int64)).astype(jnp.int32), dt.DATE)


class DateDiff(Expression):
    def __init__(self, end, start):
        super().__init__([end, start])

    @property
    def dtype(self):
        return dt.INT32

    def eval(self, ctx):
        return eval_binary(
            self, ctx,
            lambda a, b: (a.astype(jnp.int64) -
                          b.astype(jnp.int64)).astype(jnp.int32), dt.INT32)


class UnixTimestamp(Expression):
    """timestamp -> epoch seconds (UTC only, the reference's constraint:
    GpuOverrides.scala:341,451)."""

    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return dt.INT64

    def eval(self, ctx):
        src = self.children[0].dtype

        def f(x):
            if src is dt.DATE:
                return x.astype(jnp.int64) * 86400
            return jnp.floor_divide(x, _US_PER_SEC)

        return eval_unary(self, ctx, f, dt.INT64)


class FromUnixTime(Expression):
    """epoch seconds -> timestamp (then format via Cast to string if asked)."""

    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return dt.TIMESTAMP

    def eval(self, ctx):
        return eval_unary(
            self, ctx, lambda x: x.astype(jnp.int64) * _US_PER_SEC,
            dt.TIMESTAMP)


class LastDay(Expression):
    """Last day of the month of a date."""

    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return dt.DATE

    def eval(self, ctx):
        def f(days):
            y, m, _ = _civil_from_days(days)
            ny = jnp.where(m == 12, y + 1, y)
            nm = jnp.where(m == 12, 1, m + 1)
            first_next = _days_from_civil(ny, nm, jnp.ones_like(nm))
            return (first_next - 1).astype(jnp.int32)

        return eval_unary(self, ctx, f, dt.DATE)


class DayOfYear(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return dt.INT32

    def eval(self, ctx):
        def f(days):
            y, _, _ = _civil_from_days(days)
            jan1 = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
            return (days.astype(jnp.int64) - jan1 + 1).astype(jnp.int32)

        return eval_unary(self, ctx, f, dt.INT32)


class Quarter(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return dt.INT32

    def eval(self, ctx):
        def f(days):
            _, m, _ = _civil_from_days(days)
            return ((m - 1) // 3 + 1).astype(jnp.int32)

        return eval_unary(self, ctx, f, dt.INT32)


class WeekDay(Expression):
    """0 = Monday ... 6 = Sunday (Spark WeekDay, vs DayOfWeek's
    1=Sunday numbering)."""

    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return dt.INT32

    def eval(self, ctx):
        def f(days):
            # 1970-01-01 was a Thursday (=3 in Monday-0 numbering)
            return jnp.mod(days.astype(jnp.int64) + 3, 7).astype(jnp.int32)

        return eval_unary(self, ctx, f, dt.INT32)


class ToUnixTimestamp(UnixTimestamp):
    """ToUnixTimestamp is UnixTimestamp with reversed SQL argument order;
    as an expression node the semantics are identical (the reference maps
    both onto the same GPU implementation, GpuOverrides.scala registry)."""


class TimeAdd(Expression):
    """timestamp + microsecond delta (Spark TimeAdd with a literal
    CalendarInterval; the reference only supports literal intervals with
    no month component — months are calendar-irregular)."""

    def __init__(self, start, delta_us):
        super().__init__([start, delta_us])

    @property
    def dtype(self):
        return dt.TIMESTAMP

    def eval(self, ctx):
        return eval_binary(
            self, ctx,
            lambda a, b: a.astype(jnp.int64) + b.astype(jnp.int64),
            dt.TIMESTAMP)
