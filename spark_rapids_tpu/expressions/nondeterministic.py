"""Nondeterministic / partition-context expressions (reference §2.5:
GpuRandomExpressions.scala:75, GpuSparkPartitionID.scala:58,
GpuMonotonicallyIncreasingID.scala:75).

These read the per-task ``TaskInfo`` (partition id, rows already emitted
by earlier batches of this partition, session seed) that the exec layer
threads through the compiler — the TaskContext the reference reads on
the JVM side.

``Rand`` is a counter-based generator: value = mix64(seed', position),
with seed' = expr seed + partition id (Spark's rand seeds per partition
the same way). The stream differs from Spark's XORShift — the reference
has the identical caveat with cuDF's Philox and flags the expression
incompatible; so do we.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions.base import (ColV, EvalContext,
                                               EvalValue, Expression,
                                               LeafExpression)


class TaskInfo(NamedTuple):
    """Per-(partition, batch) evaluation context; fields are 0-d device
    scalars so the fused projection jit treats them as dynamic inputs."""

    partition_id: jax.Array   # int32
    row_base: jax.Array       # int64: rows emitted before this batch
    seed: jax.Array           # int64: session seed

    @staticmethod
    def make(partition_id: int = 0, row_base: int = 0,
             seed: int = 0) -> "TaskInfo":
        return TaskInfo(jnp.int32(partition_id), jnp.int64(row_base),
                        jnp.int64(seed))


def _task(ctx: EvalContext) -> TaskInfo:
    if ctx.task_info is not None:
        return ctx.task_info
    return TaskInfo.make()


class SparkPartitionID(LeafExpression):
    """spark_partition_id(): INT32 partition ordinal."""

    @property
    def dtype(self) -> dt.DType:
        return dt.INT32

    @property
    def nullable(self) -> bool:
        return False

    @property
    def device_only(self) -> bool:
        return True

    def eval(self, ctx: EvalContext) -> EvalValue:
        ti = _task(ctx)
        data = jnp.full(ctx.capacity, ti.partition_id, dtype=jnp.int32)
        return ColV(dt.INT32, data, None)


class MonotonicallyIncreasingID(LeafExpression):
    """monotonically_increasing_id(): (partition << 33) + row position —
    Spark's exact encoding (unique, monotonic within a partition)."""

    @property
    def dtype(self) -> dt.DType:
        return dt.INT64

    @property
    def nullable(self) -> bool:
        return False

    @property
    def device_only(self) -> bool:
        return True

    def eval(self, ctx: EvalContext) -> EvalValue:
        ti = _task(ctx)
        iota = jnp.arange(ctx.capacity, dtype=jnp.int64)
        data = (ti.partition_id.astype(jnp.int64) << 33) + \
            ti.row_base + iota
        return ColV(dt.INT64, data, None)


# splitmix64 constants as signed int64 (two's complement)
_GOLDEN = jnp.int64(-7046029254386353131)    # 0x9E3779B97F4A7C15
_MIX1 = jnp.int64(-4658895280553007687)      # 0xBF58476D1CE4E5B9
_MIX2 = jnp.int64(-7723592293110705685)      # 0x94D049BB133111EB


def _lshr(z, k: int):
    """Logical right shift by a STATIC amount on signed int64 (no
    unsigned bitcast — unavailable under the TPU x64 rewrite)."""
    return (z >> k) & jnp.int64((1 << (64 - k)) - 1)


def _mix64(z):
    z = (z ^ _lshr(z, 30)) * _MIX1
    z = (z ^ _lshr(z, 27)) * _MIX2
    return z ^ _lshr(z, 31)


class Rand(LeafExpression):
    """rand(seed): uniform [0, 1) doubles, counter-based (splitmix64 of
    the absolute row position), reproducible per (seed, partition, row)."""

    incompat = True  # stream differs from Spark's XORShiftRandom

    def __init__(self, seed: int = 0):
        super().__init__()
        self.seed = int(seed)

    @property
    def dtype(self) -> dt.DType:
        return dt.FLOAT64

    @property
    def nullable(self) -> bool:
        return False

    @property
    def deterministic(self) -> bool:
        return False

    @property
    def device_only(self) -> bool:
        return True

    def eval(self, ctx: EvalContext) -> EvalValue:
        ti = _task(ctx)
        pos = ti.row_base + jnp.arange(ctx.capacity, dtype=jnp.int64)
        # pre-mix the stream id: a linear (seed+pid+pos)*GOLDEN counter
        # would collide across partitions at shifted positions
        stream = _mix64((jnp.int64(self.seed) +
                         ti.partition_id.astype(jnp.int64)) * _GOLDEN)
        h = _mix64(stream + pos * _GOLDEN)
        u53 = _lshr(h, 11)  # top 53 bits -> exactly representable
        data = u53.astype(jnp.float64) * jnp.float64(2.0 ** -53)
        return ColV(dt.FLOAT64, data, None)


def rand_reference(seed: int, partition_id, positions):
    """numpy mirror of Rand (the CPU oracle), exact to the bit."""
    import numpy as np

    GOLDEN = np.int64(-7046029254386353131)
    MIX1 = np.int64(-4658895280553007687)
    MIX2 = np.int64(-7723592293110705685)

    def lshr(z, k):
        return (z >> k) & np.int64((1 << (64 - k)) - 1)

    def mix(z):
        z = (z ^ lshr(z, 30)) * MIX1
        z = (z ^ lshr(z, 27)) * MIX2
        return z ^ lshr(z, 31)

    with np.errstate(all="ignore"):
        pos = np.asarray(positions, dtype=np.int64)
        stream = mix((np.int64(seed) + np.int64(partition_id)) * GOLDEN)
        z = stream + pos * GOLDEN
        z = (z ^ lshr(z, 30)) * MIX1
        z = (z ^ lshr(z, 27)) * MIX2
        z = z ^ lshr(z, 31)
        return lshr(z, 11).astype(np.float64) * 2.0 ** -53


class _InputFileExpr(LeafExpression):
    """Base for input_file_name/_block_start/_block_length
    (GpuInputFileBlock.scala): batch-constant values read from the scan
    origin; outside a file scan Spark returns ""/-1 and so do we.
    Not device_only — the value is a host scalar broadcast per batch."""

    @property
    def nullable(self):
        return False

    @property
    def device_only(self):
        return False

    def _from_origin(self, origin):  # pragma: no cover - abstract
        raise NotImplementedError

    def eval(self, ctx):
        from spark_rapids_tpu.columnar.column import Scalar

        return Scalar(self.dtype, self._from_origin(ctx.origin))

    def eval_cpu(self, ctx):
        """CPU-oracle evaluation (engine dispatch honors eval_cpu);
        ``ctx.origins`` is [(origin, row_count)] runs from the scan."""
        import numpy as np

        from spark_rapids_tpu.cpu.evaluator import CV

        runs = getattr(ctx, "origins", None) or [(None, ctx.num_rows)]
        np_t = object if self.dtype is dt.STRING else np.int64
        parts = [np.full(count, self._from_origin(o), dtype=np_t)
                 for o, count in runs]
        data = np.concatenate(parts) if parts else np.array([], dtype=np_t)
        return CV(self.dtype, data, None)


class InputFileName(_InputFileExpr):
    @property
    def dtype(self):
        return dt.STRING

    def _from_origin(self, origin):
        return origin[0] if origin else ""


class InputFileBlockStart(_InputFileExpr):
    @property
    def dtype(self):
        return dt.INT64

    def _from_origin(self, origin):
        return int(origin[1]) if origin else -1


class InputFileBlockLength(_InputFileExpr):
    @property
    def dtype(self):
        return dt.INT64

    def _from_origin(self, origin):
        return int(origin[2]) if origin else -1
