"""Arithmetic expressions (reference org/apache/spark/sql/rapids/
arithmetic.scala): add/sub/mul/div/integral-div/remainder/pmod/abs/sign/
unary +-. Spark (non-ANSI) semantics: division/remainder by zero -> NULL;
integer overflow wraps (java semantics == two's-complement jnp)."""
from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.column import Scalar
from spark_rapids_tpu.expressions.base import (
    ColV,
    EvalContext,
    EvalValue,
    Expression,
    and_validity,
    eval_binary,
    eval_unary,
    scalar_data,
    value_validity,
)


class BinaryArithmetic(Expression):
    abstract = True  # template only; never registered or planned

    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    @property
    def dtype(self) -> dt.DType:
        return dt.common_type(self.children[0].dtype, self.children[1].dtype)

    def _common(self):
        return self.dtype.kernel_dtype


class Add(BinaryArithmetic):
    def eval(self, ctx):
        kt = self._common()
        return eval_binary(self, ctx,
                           lambda a, b: a.astype(kt) + b.astype(kt),
                           self.dtype)


class Subtract(BinaryArithmetic):
    def eval(self, ctx):
        kt = self._common()
        return eval_binary(self, ctx,
                           lambda a, b: a.astype(kt) - b.astype(kt),
                           self.dtype)


class Multiply(BinaryArithmetic):
    def eval(self, ctx):
        kt = self._common()
        return eval_binary(self, ctx,
                           lambda a, b: a.astype(kt) * b.astype(kt),
                           self.dtype)


class _DivLike(Expression):
    """Shared null-on-zero-divisor machinery (GpuDivModLike analogue,
    arithmetic.scala)."""

    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    @property
    def nullable(self) -> bool:
        return True

    def _apply(self, ctx: EvalContext, fn, out_dtype: dt.DType) -> EvalValue:
        a = self.children[0].eval(ctx)
        b = self.children[1].eval(ctx)
        if isinstance(a, Scalar) and isinstance(b, Scalar):
            if a.is_null or b.is_null or b.value == 0:
                return Scalar(out_dtype, None)
            import jax

            r = fn(jnp.asarray(a.value, a.dtype.kernel_dtype),
                   jnp.asarray(b.value, b.dtype.kernel_dtype))
            v = jax.device_get(r)
            return Scalar(out_dtype,
                          float(v) if out_dtype.is_floating else int(v))
        if (isinstance(a, Scalar) and a.is_null) or \
                (isinstance(b, Scalar) and b.is_null):
            return Scalar(out_dtype, None)
        ad, bd = scalar_data(a), scalar_data(b)
        nonzero = bd != 0
        safe_b = jnp.where(nonzero, bd, jnp.ones((), bd.dtype))
        data = fn(ad, safe_b)
        validity = and_validity(value_validity(a), value_validity(b))
        validity = nonzero if validity is None else (validity & nonzero)
        if validity.ndim == 0:
            # scalar divisor: validity must still be full-length (the
            # column convention downstream kernels rely on)
            validity = jnp.broadcast_to(validity, data.shape)
        return ColV(out_dtype, data.astype(out_dtype.kernel_dtype), validity)


class Divide(_DivLike):
    """Spark Divide: always fractional output; x/0 -> NULL."""

    @property
    def dtype(self) -> dt.DType:
        return dt.FLOAT64

    def eval(self, ctx):
        return self._apply(
            ctx, lambda a, b: a.astype(jnp.float64) / b.astype(jnp.float64),
            dt.FLOAT64)


class IntegralDivide(_DivLike):
    """div operator: long result, truncation toward zero (java semantics —
    jnp // floors, so adjust)."""

    @property
    def dtype(self) -> dt.DType:
        return dt.INT64

    def eval(self, ctx):
        def f(a, b):
            a = a.astype(jnp.int64)
            b = b.astype(jnp.int64)
            q = a // b
            r = a - q * b
            # floor->trunc correction when signs differ and remainder nonzero
            return q + ((r != 0) & ((a < 0) != (b < 0))).astype(jnp.int64)

        return self._apply(ctx, f, dt.INT64)


class Remainder(_DivLike):
    """% : java semantics (sign follows dividend); x%0 -> NULL."""

    @property
    def dtype(self) -> dt.DType:
        return dt.common_type(self.children[0].dtype, self.children[1].dtype)

    def eval(self, ctx):
        out = self.dtype
        kt = out.kernel_dtype

        def f(a, b):
            # truncated remainder = Java % (sign of dividend); also
            # correct for ±Inf operands, unlike jnp.remainder
            return jnp.fmod(a.astype(kt), b.astype(kt))

        return self._apply(ctx, f, out)


class Pmod(_DivLike):
    """pmod(a, b): non-negative remainder."""

    @property
    def dtype(self) -> dt.DType:
        return dt.common_type(self.children[0].dtype, self.children[1].dtype)

    def eval(self, ctx):
        out = self.dtype
        kt = out.kernel_dtype

        def f(a, b):
            # Spark's pmod (arithmetic.scala): r = a % n (truncated);
            # if r < 0 then (r + n) % n — including the Java wrap-around
            # on r + n at integer boundaries (XLA int add wraps too)
            a = a.astype(kt)
            b = b.astype(kt)
            r = jnp.fmod(a, b)
            return jnp.where(r < 0, jnp.fmod(r + b, b), r)

        return self._apply(ctx, f, out)


class UnaryMinus(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self) -> dt.DType:
        return self.children[0].dtype

    def eval(self, ctx):
        return eval_unary(self, ctx, lambda x: -x, self.dtype)


class UnaryPositive(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self) -> dt.DType:
        return self.children[0].dtype

    def eval(self, ctx):
        return self.children[0].eval(ctx)


class Abs(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self) -> dt.DType:
        return self.children[0].dtype

    def eval(self, ctx):
        return eval_unary(self, ctx, jnp.abs, self.dtype)


class Signum(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self) -> dt.DType:
        return dt.FLOAT64

    def eval(self, ctx):
        return eval_unary(
            self, ctx, lambda x: jnp.sign(x.astype(jnp.float64)), dt.FLOAT64)
