"""Predicates & boolean logic (reference .../predicates.scala, 631 LoC):
comparisons, AND/OR with Spark's three-valued-logic short circuits,
IsNull/IsNotNull/IsNaN, EqualNullSafe, In, AtLeastNNonNulls, Not.

Comparisons implement Spark ordering semantics for floats: NaN == NaN is
false under ``=``, but NaN > everything for ``<``/``>`` (we match cuDF/Spark:
IEEE comparisons except where Spark normalizes — the reference relies on
cuDF's IEEE behavior too). String comparisons need unified dictionaries, so
they are NOT device_only unless both sides share one dictionary carrier.
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.column import Scalar, StringColumn, \
    unify_dictionaries
from spark_rapids_tpu.expressions.base import (
    ColV,
    EvalContext,
    EvalValue,
    Expression,
    and_validity,
    broadcast,
    eval_binary,
    scalar_data,
    value_validity,
)


class _Comparison(Expression):
    op = None  # staticmethod on subclass

    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    @property
    def dtype(self) -> dt.DType:
        return dt.BOOLEAN

    @property
    def device_only(self) -> bool:
        # string comparisons require dictionary unification (host)
        if self.children[0].dtype is dt.STRING:
            return False
        return super().device_only

    def _prep_strings(self, a: EvalValue, b: EvalValue):
        """Convert string operands onto one dictionary so code comparison is
        string comparison."""
        from spark_rapids_tpu.columnar.column import StringColumn

        def as_scol(v):
            if isinstance(v, Scalar):
                return None
            return v.scol

        sa, sb = as_scol(a), as_scol(b)
        if isinstance(a, Scalar) and isinstance(b, Scalar):
            return a, b
        if isinstance(a, Scalar) or isinstance(b, Scalar):
            scalar, colv = (a, b) if isinstance(a, Scalar) else (b, a)
            scol = colv.scol
            assert scol is not None, "string ColV missing dictionary"
            import numpy as np

            # place the scalar into code space of this dictionary: exact
            # match -> its code; otherwise use a half-code trick via two
            # comparisons handled by caller through searchsorted position.
            pos = int(np.searchsorted(
                scol.dictionary.astype(str) if len(scol.dictionary)
                else np.array([], dtype=str), str(scalar.value)))
            exact = pos < len(scol.dictionary) and \
                str(scol.dictionary[pos]) == str(scalar.value)
            # encode as code*2 (+1 if between codes) on a doubled axis
            code2 = pos * 2 + (0 if exact else -1)
            a2 = ColV(dt.STRING, colv.data.astype(jnp.int64) * 2,
                      colv.validity, scol)
            s2 = Scalar(dt.INT64, code2)
            return (s2, a2) if isinstance(a, Scalar) else (a2, s2)
        if sa is not None and sb is not None:
            ua, ub = unify_dictionaries([
                StringColumn(a.data, sa.dictionary, a.validity),
                StringColumn(b.data, sb.dictionary, b.validity)])
            return (ColV(dt.STRING, ua.data, ua.validity, ua),
                    ColV(dt.STRING, ub.data, ub.validity, ub))
        raise AssertionError("string ColV missing dictionary")

    def eval(self, ctx: EvalContext) -> EvalValue:
        a = self.children[0].eval(ctx)
        b = self.children[1].eval(ctx)
        # null scalars before any string prep: cmp vs NULL is NULL
        if (isinstance(a, Scalar) and a.is_null) or \
                (isinstance(b, Scalar) and b.is_null):
            return Scalar(dt.BOOLEAN, None)
        if self.children[0].dtype is dt.STRING:
            if isinstance(a, Scalar) and isinstance(b, Scalar):
                # two non-null string scalars: plain host comparison
                return Scalar(dt.BOOLEAN,
                              bool(self.op(str(a.value), str(b.value))))
            a, b = self._prep_strings(a, b)
        if isinstance(a, Scalar) and isinstance(b, Scalar):
            return Scalar(dt.BOOLEAN, bool(self.op(
                jnp.asarray(a.value, a.dtype.kernel_dtype),
                jnp.asarray(b.value, b.dtype.kernel_dtype))))
        if (isinstance(a, Scalar) and a.is_null) or \
                (isinstance(b, Scalar) and b.is_null):
            return Scalar(dt.BOOLEAN, None)
        data = self.op(scalar_data(a), scalar_data(b))
        return ColV(dt.BOOLEAN, data,
                    and_validity(value_validity(a), value_validity(b)))


class EqualTo(_Comparison):
    op = staticmethod(lambda a, b: a == b)


class LessThan(_Comparison):
    op = staticmethod(lambda a, b: a < b)


class LessThanOrEqual(_Comparison):
    op = staticmethod(lambda a, b: a <= b)


class GreaterThan(_Comparison):
    op = staticmethod(lambda a, b: a > b)


class GreaterThanOrEqual(_Comparison):
    op = staticmethod(lambda a, b: a >= b)


class EqualNullSafe(_Comparison):
    """<=>: null <=> null is true; never returns null."""

    op = staticmethod(lambda a, b: a == b)

    @property
    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalContext) -> EvalValue:
        a = self.children[0].eval(ctx)
        b = self.children[1].eval(ctx)
        a_null_s = isinstance(a, Scalar) and a.is_null
        b_null_s = isinstance(b, Scalar) and b.is_null
        if self.children[0].dtype is dt.STRING and not (a_null_s or b_null_s):
            if isinstance(a, Scalar) and isinstance(b, Scalar):
                return Scalar(dt.BOOLEAN,
                              bool(self.op(str(a.value), str(b.value))))
            a, b = self._prep_strings(a, b)
        if isinstance(a, Scalar) and isinstance(b, Scalar):
            if a_null_s or b_null_s:
                return Scalar(dt.BOOLEAN, a_null_s and b_null_s)
            return Scalar(dt.BOOLEAN, bool(self.op(
                jnp.asarray(a.value), jnp.asarray(b.value))))
        av = value_validity(a)
        bv = value_validity(b)
        a_valid = jnp.zeros(ctx.capacity, bool) if a_null_s else \
            (av if av is not None else jnp.ones(ctx.capacity, bool))
        b_valid = jnp.zeros(ctx.capacity, bool) if b_null_s else \
            (bv if bv is not None else jnp.ones(ctx.capacity, bool))
        if a_null_s or b_null_s:
            eq = jnp.zeros(ctx.capacity, dtype=bool)
        else:
            eq = self.op(scalar_data(a), scalar_data(b))
        both_null = (~a_valid) & (~b_valid)
        data = jnp.where(a_valid & b_valid, eq, both_null)
        return ColV(dt.BOOLEAN, data, None)


class And(Expression):
    """Spark 3VL: false AND null = false."""

    def __init__(self, left, right):
        super().__init__([left, right])

    @property
    def dtype(self):
        return dt.BOOLEAN

    def eval(self, ctx: EvalContext) -> EvalValue:
        a = broadcast(self.children[0].eval(ctx), ctx)
        b = broadcast(self.children[1].eval(ctx), ctx)
        av = a.validity if a.validity is not None else \
            jnp.ones(ctx.capacity, bool)
        bv = b.validity if b.validity is not None else \
            jnp.ones(ctx.capacity, bool)
        a_false = av & ~a.data
        b_false = bv & ~b.data
        data = a.data & b.data
        validity = (av & bv) | a_false | b_false
        if a.validity is None and b.validity is None:
            validity = None
        return ColV(dt.BOOLEAN, data, validity)


class Or(Expression):
    """Spark 3VL: true OR null = true."""

    def __init__(self, left, right):
        super().__init__([left, right])

    @property
    def dtype(self):
        return dt.BOOLEAN

    def eval(self, ctx: EvalContext) -> EvalValue:
        a = broadcast(self.children[0].eval(ctx), ctx)
        b = broadcast(self.children[1].eval(ctx), ctx)
        av = a.validity if a.validity is not None else \
            jnp.ones(ctx.capacity, bool)
        bv = b.validity if b.validity is not None else \
            jnp.ones(ctx.capacity, bool)
        a_true = av & a.data
        b_true = bv & b.data
        data = a.data | b.data
        validity = (av & bv) | a_true | b_true
        if a.validity is None and b.validity is None:
            validity = None
        return ColV(dt.BOOLEAN, data, validity)


class Not(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return dt.BOOLEAN

    def eval(self, ctx):
        v = self.children[0].eval(ctx)
        if isinstance(v, Scalar):
            return Scalar(dt.BOOLEAN,
                          None if v.is_null else (not v.value))
        return ColV(dt.BOOLEAN, ~v.data, v.validity)


class IsNull(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return dt.BOOLEAN

    @property
    def nullable(self):
        return False

    def eval(self, ctx):
        v = self.children[0].eval(ctx)
        if isinstance(v, Scalar):
            return Scalar(dt.BOOLEAN, v.is_null)
        if v.validity is None:
            return Scalar(dt.BOOLEAN, False)
        return ColV(dt.BOOLEAN, ~v.validity, None)


class IsNotNull(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return dt.BOOLEAN

    @property
    def nullable(self):
        return False

    def eval(self, ctx):
        v = self.children[0].eval(ctx)
        if isinstance(v, Scalar):
            return Scalar(dt.BOOLEAN, not v.is_null)
        if v.validity is None:
            return Scalar(dt.BOOLEAN, True)
        return ColV(dt.BOOLEAN, v.validity, None)


class IsNaN(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return dt.BOOLEAN

    @property
    def nullable(self):
        return False

    def eval(self, ctx):
        v = self.children[0].eval(ctx)
        if isinstance(v, Scalar):
            import math

            return Scalar(dt.BOOLEAN,
                          False if v.is_null else math.isnan(v.value))
        data = jnp.isnan(v.data)
        if v.validity is not None:
            data = data & v.validity
        return ColV(dt.BOOLEAN, data, None)


class In(Expression):
    """IN (literal list). Null semantics: x IN (...) is null if x is null,
    or if no match and the list contains null."""

    def __init__(self, child: Expression, values: List):
        super().__init__([child])
        from spark_rapids_tpu.expressions.base import Literal

        # contract: raw python values; unwrap Literal wrappers so both
        # calling conventions mean the same thing on both engines
        self.values = [v.value if isinstance(v, Literal) else v
                       for v in values]

    @property
    def dtype(self):
        return dt.BOOLEAN

    @property
    def device_only(self) -> bool:
        return super().device_only and self.children[0].dtype is not dt.STRING

    def eval(self, ctx):
        from spark_rapids_tpu.expressions.base import LeafExpression, Literal

        child = self.children[0]
        child_value = child.eval(ctx)  # evaluate the subtree ONCE

        class _Precomputed(LeafExpression):
            dtype = child.dtype
            nullable = child.nullable
            device_only = True

            def eval(self, _ctx):
                return child_value

        pre = _Precomputed()
        result: Optional[Expression] = None
        has_null = any(v is None for v in self.values)
        for v in self.values:
            if v is None:
                continue
            term = EqualTo(pre, Literal(v, child.dtype))
            result = term if result is None else Or(result, term)
        if result is None:
            out = Scalar(dt.BOOLEAN, None if has_null else False)
            return out
        r = result.eval(ctx)
        if has_null:
            # no-match becomes null: validity &= data
            if isinstance(r, Scalar):
                if not r.is_null and not r.value:
                    return Scalar(dt.BOOLEAN, None)
                return r
            valid = r.validity if r.validity is not None else \
                jnp.ones(ctx.capacity, bool)
            return ColV(dt.BOOLEAN, r.data, valid & r.data)
        return r


class AtLeastNNonNulls(Expression):
    def __init__(self, n: int, children: List[Expression]):
        super().__init__(children)
        self.n = n

    @property
    def dtype(self):
        return dt.BOOLEAN

    @property
    def nullable(self):
        return False

    def eval(self, ctx):
        count = jnp.zeros(ctx.capacity, dtype=jnp.int32)
        for c in self.children:
            v = c.eval(ctx)
            if isinstance(v, Scalar):
                if not v.is_null:
                    count = count + 1
                continue
            nn = v.validity if v.validity is not None else None
            if v.dtype.is_floating:
                not_nan = ~jnp.isnan(v.data)
                nn = not_nan if nn is None else (nn & not_nan)
            count = count + (nn.astype(jnp.int32) if nn is not None else 1)
        return ColV(dt.BOOLEAN, count >= self.n, None)


#: InSet is Catalyst's optimized literal-set variant of In; as a plan
#: node the semantics are identical (GpuInSet in the reference registry)
InSet = In
