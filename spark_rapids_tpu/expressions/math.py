"""Math expressions (reference .../mathExpressions.scala, registry at
GpuOverrides.scala:702-957): trig/log/exp/sqrt/cbrt/rint/floor/ceil/pow/...

All lower to single jnp ops -> fuse into the surrounding XLA computation.
Transcendentals whose TPU approximations differ from java.lang.Math in ulps
are flagged ``incompat`` at the planner (GpuOverrides incompat analogue).
"""
from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions.base import Expression, eval_binary, \
    eval_unary


class _UnaryMathF64(Expression):
    fn = None
    incompat = False

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return dt.FLOAT64

    def eval(self, ctx):
        f = type(self).fn
        return eval_unary(self, ctx,
                          lambda x: f(x.astype(jnp.float64)), dt.FLOAT64)


class Sqrt(_UnaryMathF64):
    fn = staticmethod(jnp.sqrt)


class Cbrt(_UnaryMathF64):
    fn = staticmethod(jnp.cbrt)


class Exp(_UnaryMathF64):
    fn = staticmethod(jnp.exp)
    incompat = True


class Expm1(_UnaryMathF64):
    fn = staticmethod(jnp.expm1)
    incompat = True


class Log(_UnaryMathF64):
    fn = staticmethod(jnp.log)
    incompat = True


class Log1p(_UnaryMathF64):
    fn = staticmethod(jnp.log1p)
    incompat = True


class Log2(_UnaryMathF64):
    fn = staticmethod(jnp.log2)
    incompat = True


class Log10(_UnaryMathF64):
    fn = staticmethod(jnp.log10)
    incompat = True


class Sin(_UnaryMathF64):
    fn = staticmethod(jnp.sin)
    incompat = True


class Cos(_UnaryMathF64):
    fn = staticmethod(jnp.cos)
    incompat = True


class Tan(_UnaryMathF64):
    fn = staticmethod(jnp.tan)
    incompat = True


class Asin(_UnaryMathF64):
    fn = staticmethod(jnp.arcsin)
    incompat = True


class Acos(_UnaryMathF64):
    fn = staticmethod(jnp.arccos)
    incompat = True


class Atan(_UnaryMathF64):
    fn = staticmethod(jnp.arctan)
    incompat = True


class Sinh(_UnaryMathF64):
    fn = staticmethod(jnp.sinh)
    incompat = True


class Cosh(_UnaryMathF64):
    fn = staticmethod(jnp.cosh)
    incompat = True


class Tanh(_UnaryMathF64):
    fn = staticmethod(jnp.tanh)
    incompat = True


class Asinh(_UnaryMathF64):
    fn = staticmethod(jnp.arcsinh)
    incompat = True


class Acosh(_UnaryMathF64):
    fn = staticmethod(jnp.arccosh)
    incompat = True


class Atanh(_UnaryMathF64):
    fn = staticmethod(jnp.arctanh)
    incompat = True


class Cot(_UnaryMathF64):
    fn = staticmethod(lambda x: 1.0 / jnp.tan(x))
    incompat = True


class ToDegrees(_UnaryMathF64):
    fn = staticmethod(jnp.degrees)


class ToRadians(_UnaryMathF64):
    fn = staticmethod(jnp.radians)


class Rint(_UnaryMathF64):
    fn = staticmethod(jnp.rint)


def _java_f64_to_i64(y):
    """Java (long) cast on device: NaN -> 0, saturate at Long.MIN/MAX
    (XLA's out-of-range float->int convert is implementation-defined,
    so the edges must be explicit)."""
    hi = y >= jnp.float64(9.223372036854776e18)   # 2^63
    lo = y <= jnp.float64(-9.223372036854776e18)
    nan = jnp.isnan(y)
    safe = jnp.where(hi | lo | nan, 0.0, y).astype(jnp.int64)
    safe = jnp.where(hi, jnp.int64(2**63 - 1), safe)
    safe = jnp.where(lo, jnp.int64(-(2**63)), safe)
    return jnp.where(nan, jnp.int64(0), safe)


class Floor(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return dt.INT64

    def eval(self, ctx):
        return eval_unary(
            self, ctx,
            lambda x: _java_f64_to_i64(jnp.floor(x.astype(jnp.float64))),
            dt.INT64)


class Ceil(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return dt.INT64

    def eval(self, ctx):
        return eval_unary(
            self, ctx,
            lambda x: _java_f64_to_i64(jnp.ceil(x.astype(jnp.float64))),
            dt.INT64)


class Pow(Expression):
    incompat = True

    def __init__(self, left, right):
        super().__init__([left, right])

    @property
    def dtype(self):
        return dt.FLOAT64

    def eval(self, ctx):
        return eval_binary(
            self, ctx,
            lambda a, b: jnp.power(a.astype(jnp.float64),
                                   b.astype(jnp.float64)), dt.FLOAT64)


class Logarithm(Expression):
    """log(base, x) — Spark's two-argument logarithm."""

    incompat = True

    def __init__(self, base: Expression, child: Expression):
        super().__init__([base, child])

    @property
    def dtype(self):
        return dt.FLOAT64

    def eval(self, ctx):
        return eval_binary(
            self, ctx,
            lambda b, x: jnp.log(x.astype(jnp.float64)) /
            jnp.log(b.astype(jnp.float64)),
            dt.FLOAT64)


class Atan2(Expression):
    incompat = True

    def __init__(self, left, right):
        super().__init__([left, right])

    @property
    def dtype(self):
        return dt.FLOAT64

    def eval(self, ctx):
        return eval_binary(
            self, ctx,
            lambda a, b: jnp.arctan2(a.astype(jnp.float64),
                                     b.astype(jnp.float64)), dt.FLOAT64)


class Round(Expression):
    """ROUND(x[, scale]) with Spark/Java HALF_UP semantics (round .5 away
    from zero — jnp.rint would bankers-round). Fractional input returns
    double; integral input returns the column type (unchanged when
    scale >= 0). Reference: GpuOverrides.scala registry (Round via cudf
    round)."""

    def __init__(self, child: Expression, scale: int = 0):
        super().__init__([child])
        self.scale = int(scale)

    @property
    def dtype(self):
        ct = self.children[0].dtype
        return ct if ct.is_integral else dt.FLOAT64

    def eval(self, ctx):
        s = self.scale
        in_t = self.children[0].dtype

        def f(x):
            if in_t.is_integral and s >= 0:
                return x
            p = jnp.float64(10.0 ** s)
            scaled = x.astype(jnp.float64) * p
            r = jnp.where(scaled >= 0, jnp.floor(scaled + 0.5),
                          jnp.ceil(scaled - 0.5))
            r = r / p
            if in_t.is_integral:
                return _java_f64_to_i64(r).astype(in_t.kernel_dtype)
            return r

        return eval_unary(self, ctx, f, self.dtype)
