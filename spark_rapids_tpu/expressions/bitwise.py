"""Bitwise and shift expressions (reference org/apache/spark/sql/rapids/
bitwise.scala; registered in GpuOverrides.scala expression table).

Spark semantics: operands are integral; shifts take an INT shift amount
and, like Java, mask it by the value width (x << 33 on an int == x << 1).
ShiftRight is arithmetic, ShiftRightUnsigned logical.
"""
from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions.base import (EvalContext, EvalValue,
                                               Expression, eval_binary,
                                               eval_unary)


class _BitwiseBinary(Expression):
    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])
        assert left.dtype.is_integral and right.dtype.is_integral, \
            "bitwise ops require integral operands"

    @property
    def dtype(self) -> dt.DType:
        return dt.common_type(self.children[0].dtype,
                              self.children[1].dtype)

    def _op(self, a, b):
        raise NotImplementedError

    def eval(self, ctx: EvalContext) -> EvalValue:
        kt = self.dtype.kernel_dtype
        return eval_binary(self, ctx,
                           lambda a, b: self._op(a.astype(kt),
                                                 b.astype(kt)),
                           self.dtype)


class BitwiseAnd(_BitwiseBinary):
    def _op(self, a, b):
        return a & b


class BitwiseOr(_BitwiseBinary):
    def _op(self, a, b):
        return a | b


class BitwiseXor(_BitwiseBinary):
    def _op(self, a, b):
        return a ^ b


class BitwiseNot(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])
        assert child.dtype.is_integral

    @property
    def dtype(self) -> dt.DType:
        return self.children[0].dtype

    def eval(self, ctx: EvalContext) -> EvalValue:
        return eval_unary(self, ctx, lambda x: ~x, self.dtype)


class _Shift(Expression):
    """value width decides the Java shift-amount mask (31 or 63)."""

    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])
        assert left.dtype in (dt.INT32, dt.INT64), \
            "shifts take int or bigint values (Spark)"
        assert right.dtype.is_integral

    @property
    def dtype(self) -> dt.DType:
        return self.children[0].dtype

    def _mask(self):
        return 63 if self.children[0].dtype is dt.INT64 else 31

    def _op(self, a, s):
        raise NotImplementedError

    def eval(self, ctx: EvalContext) -> EvalValue:
        kt = self.dtype.kernel_dtype

        def f(a, s):
            return self._op(a.astype(kt),
                            (s.astype(jnp.int32) & self._mask()))
        return eval_binary(self, ctx, f, self.dtype)


class ShiftLeft(_Shift):
    def _op(self, a, s):
        return a << s.astype(a.dtype)


class ShiftRight(_Shift):
    """Arithmetic (sign-propagating) right shift — Java >>."""

    def _op(self, a, s):
        return a >> s.astype(a.dtype)


class ShiftRightUnsigned(_Shift):
    """Logical right shift — Java >>>: arithmetic shift then clear the
    sign-propagated top bits (no unsigned bitcast: bitcast_convert on
    64-bit types is unavailable under the TPU x64 rewrite)."""

    def _op(self, a, s):
        width = 64 if self.children[0].dtype is dt.INT64 else 32
        sa = s.astype(a.dtype)
        shifted = a >> sa
        sc = jnp.maximum(sa, 1)          # avoid shift-by-width UB below
        keep = (jnp.ones((), a.dtype) << (width - sc)) - 1
        return jnp.where(sa == 0, a, shifted & keep)
