"""Cast (reference GpuCast.scala, 904 LoC): the full type matrix with
per-direction compat gates (RapidsConf.scala:450-482 — float->string,
string->float/int/date/timestamp each behind its own config; checked at the
planner, see planning/overrides.py).

Device-friendly casts (numeric<->numeric, bool, date<->timestamp) run in-jit
with Java/Spark (non-ANSI) semantics: float->int clamps to the target range
and NaN -> 0 (Java (long)double behavior, GpuCast.scala:188). String casts
run eagerly via dictionary transforms: parse/format each *dictionary entry*
host-side (once per unique value), then a device gather by code.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.column import Scalar, StringColumn
from spark_rapids_tpu.expressions.base import ColV, EvalContext, EvalValue, \
    Expression


class Cast(Expression):
    def __init__(self, child: Expression, to: dt.DType, ansi: bool = False):
        super().__init__([child])
        self.to = to
        self.ansi = ansi

    @property
    def dtype(self) -> dt.DType:
        return self.to

    @property
    def nullable(self) -> bool:
        # string parses can fail -> null
        if self.children[0].dtype is dt.STRING and self.to is not dt.STRING:
            return True
        return self.children[0].nullable

    @property
    def device_only(self) -> bool:
        if self.children[0].dtype is dt.STRING or self.to is dt.STRING:
            return False
        return super().device_only

    def eval(self, ctx: EvalContext) -> EvalValue:
        src = self.children[0].dtype
        v = self.children[0].eval(ctx)
        if src is self.to:
            return v
        if isinstance(v, Scalar):
            return self._cast_scalar(v, src)
        if src is dt.STRING:
            return _cast_from_string(v, self.to)
        if self.to is dt.STRING:
            return _cast_to_string(v, src, ctx)
        data, validity = _device_cast(v.data, v.validity, src, self.to)
        return ColV(self.to, data, validity)

    def _cast_scalar(self, v: Scalar, src: dt.DType) -> Scalar:
        if v.is_null:
            return Scalar(self.to, None)
        if src is dt.STRING:
            val, ok = _parse_one(str(v.value), self.to)
            return Scalar(self.to, val if ok else None)
        if self.to is dt.STRING:
            return Scalar(dt.STRING, _format_one(v.value, src))
        arr = jnp.asarray(v.value, dtype=src.kernel_dtype)
        data, validity = _device_cast(arr[None], None, src, self.to)
        import jax

        out = jax.device_get(data)[0]
        if self.to is dt.BOOLEAN:
            return Scalar(self.to, bool(out))
        if self.to.is_floating:
            return Scalar(self.to, float(out))
        return Scalar(self.to, int(out))


# ---------------------------------------------------------------------------
# device casts
# ---------------------------------------------------------------------------

_US_PER_DAY = 86_400_000_000


def _device_cast(data: jnp.ndarray, validity, src: dt.DType, to: dt.DType):
    if src is dt.BOOLEAN:
        return data.astype(to.kernel_dtype), validity
    if to is dt.BOOLEAN:
        return (data != 0), validity
    if src is dt.DATE and to is dt.TIMESTAMP:
        return data.astype(jnp.int64) * _US_PER_DAY, validity
    if src is dt.TIMESTAMP and to is dt.DATE:
        return jnp.floor_divide(data, _US_PER_DAY).astype(jnp.int32), validity
    if src.is_floating and (to.is_integral or to in (dt.DATE, dt.TIMESTAMP)):
        # Java (long)double: NaN -> 0, saturate to target range, truncate.
        # Explicit range tests rather than clip-then-convert: float(max) may
        # not be representable (2^63-1 rounds up to 2^63) and XLA's
        # out-of-range convert is implementation-defined.
        kd = to.kernel_dtype
        info = jnp.iinfo(kd)
        x = jnp.trunc(jnp.nan_to_num(data, nan=0.0))
        big = x >= float(info.max)
        small = x <= float(info.min)
        safe = jnp.where(big | small, jnp.zeros((), x.dtype), x).astype(kd)
        out = jnp.where(big, jnp.asarray(info.max, kd),
                        jnp.where(small, jnp.asarray(info.min, kd), safe))
        return out, validity
    return data.astype(to.kernel_dtype), validity


# ---------------------------------------------------------------------------
# string casts (eager, dictionary-transform based)
# ---------------------------------------------------------------------------

def _parse_one(s: str, to: dt.DType):
    s = s.strip()
    try:
        if to is dt.BOOLEAN:
            ls = s.lower()
            if ls in ("t", "true", "y", "yes", "1"):
                return True, True
            if ls in ("f", "false", "n", "no", "0"):
                return False, True
            return None, False
        if to.is_integral:
            return int(s), True
        if to.is_floating:
            return float(s), True
        if to is dt.DATE:
            import datetime

            d = datetime.date.fromisoformat(s[:10])
            return (d - datetime.date(1970, 1, 1)).days, True
        if to is dt.TIMESTAMP:
            import datetime

            x = datetime.datetime.fromisoformat(s)
            if x.tzinfo is None:
                x = x.replace(tzinfo=datetime.timezone.utc)
            return int(x.timestamp() * 1_000_000), True
    except (ValueError, OverflowError):
        return None, False
    return None, False


def _format_one(value, src: dt.DType) -> str:
    if src is dt.BOOLEAN:
        return "true" if value else "false"
    if src is dt.DATE:
        import datetime

        return (datetime.date(1970, 1, 1) +
                datetime.timedelta(days=int(value))).isoformat()
    if src is dt.TIMESTAMP:
        import datetime

        x = datetime.datetime.fromtimestamp(value / 1_000_000,
                                            tz=datetime.timezone.utc)
        return x.strftime("%Y-%m-%d %H:%M:%S") + (
            f".{x.microsecond:06d}".rstrip("0")
            if x.microsecond else "")
    if src.is_floating:
        # java Double.toString-ish; exact corner cases gated by config
        f = float(value)
        if f != f:
            return "NaN"
        if f in (float("inf"), float("-inf")):
            return "Infinity" if f > 0 else "-Infinity"
        if f == int(f) and abs(f) < 1e16:
            return f"{f:.1f}"
        return repr(f)
    return str(int(value))


def _cast_from_string(v: ColV, to: dt.DType) -> ColV:
    assert v.scol is not None
    dic = v.scol.dictionary
    vals = np.zeros(max(len(dic), 1), dtype=to.np_dtype)
    ok = np.zeros(max(len(dic), 1), dtype=bool)
    for i, s in enumerate(dic):
        val, good = _parse_one(str(s), to)
        if good:
            try:
                vals[i] = val  # may overflow the target numpy dtype -> NULL
                ok[i] = True
            except (OverflowError, ValueError):
                pass
    data = jnp.take(jnp.asarray(vals), v.data, mode="clip")
    good = jnp.take(jnp.asarray(ok), v.data, mode="clip")
    validity = good if v.validity is None else (v.validity & good)
    return ColV(to, data, validity)


def _cast_to_string(v: ColV, src: dt.DType, ctx: EvalContext) -> ColV:
    """Format each row host-side. For low-cardinality sources this could
    dictionary-share; formatting is correct first, fast later."""
    import jax

    n_cap = v.capacity
    raw = np.asarray(jax.device_get(v.data))
    strings = [_format_one(x, src) for x in raw]
    sc = StringColumn.from_strings(strings, capacity=n_cap)
    return ColV(dt.STRING, sc.data, v.validity, sc)
