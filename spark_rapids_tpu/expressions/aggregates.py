"""Declarative aggregate functions (reference
org/apache/spark/sql/rapids/AggregateFunctions.scala): each function declares
its *update* half (raw rows -> partial) and *merge* half (partials ->
partials) as lists of kernel ops, plus a final-evaluation expression over its
partial columns — exactly the CudfAggregate update/merge split (e.g. Average
= sum + count, evaluated as sum/count). The aggregate exec drives these for
partial/final/complete modes (execs/aggregate.py)."""
from __future__ import annotations

from typing import List, Optional

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.column import Scalar
from spark_rapids_tpu.expressions.base import BoundReference, Expression


class AggregateFunction(Expression):
    """Base: children[0] (if any) is the input expression."""

    distinct = False

    @property
    def input(self) -> Optional[Expression]:
        return self.children[0] if self.children else None

    # ---- declarative halves ---------------------------------------------

    def partial_types(self) -> List[dt.DType]:
        """Types of this function's partial (intermediate) columns."""
        raise NotImplementedError

    def update_ops(self) -> List[str]:
        """Kernel ops (ops/groupby.AGG_OPS) applied to the input projection,
        one per partial column."""
        raise NotImplementedError

    def merge_ops(self) -> List[str]:
        """Kernel ops merging partial columns (same arity)."""
        raise NotImplementedError

    def evaluate(self, partials: List[Expression]) -> Expression:
        """Final expression over the partial columns."""
        return partials[0]

    def default_result(self) -> Scalar:
        """Result on empty input (reduction with no rows,
        aggregate.scala:488-501)."""
        return Scalar(self.dtype, None)


class Min(AggregateFunction):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return self.children[0].dtype

    def partial_types(self):
        return [self.dtype]

    def update_ops(self):
        return ["min"]

    def merge_ops(self):
        return ["min"]


class Max(AggregateFunction):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return self.children[0].dtype

    def partial_types(self):
        return [self.dtype]

    def update_ops(self):
        return ["max"]

    def merge_ops(self):
        return ["max"]


class Sum(AggregateFunction):
    def __init__(self, child: Expression, distinct: bool = False):
        super().__init__([child])
        self.distinct = distinct

    @property
    def dtype(self):
        t = self.children[0].dtype
        return dt.INT64 if (t.is_integral or t is dt.BOOLEAN) else dt.FLOAT64

    def partial_types(self):
        return [self.dtype]

    def update_ops(self):
        return ["sum"]

    def merge_ops(self):
        return ["sum"]


class Count(AggregateFunction):
    """count(expr); count(*) when child is None."""

    def __init__(self, child: Optional[Expression] = None,
                 distinct: bool = False):
        super().__init__([child] if child is not None else [])
        self.distinct = distinct

    @property
    def dtype(self):
        return dt.INT64

    @property
    def nullable(self):
        return False

    def partial_types(self):
        return [dt.INT64]

    def update_ops(self):
        return ["count" if self.children else "count_star"]

    def merge_ops(self):
        return ["sum"]

    def default_result(self) -> Scalar:
        return Scalar(dt.INT64, 0)


class Average(AggregateFunction):
    """avg = sum + count partials, final sum/count
    (AggregateFunctions.scala GpuAverage)."""

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return dt.FLOAT64

    def partial_types(self):
        return [dt.FLOAT64, dt.INT64]

    def update_ops(self):
        return ["sum", "count"]

    def merge_ops(self):
        return ["sum", "sum"]

    def evaluate(self, partials: List[Expression]) -> Expression:
        from spark_rapids_tpu.expressions.arithmetic import Divide

        return Divide(partials[0], partials[1])


class First(AggregateFunction):
    def __init__(self, child: Expression, ignore_nulls: bool = False):
        super().__init__([child])
        self.ignore_nulls = ignore_nulls

    @property
    def dtype(self):
        return self.children[0].dtype

    @property
    def deterministic(self):
        return False

    def partial_types(self):
        return [self.dtype]

    def update_ops(self):
        return ["any_valid" if self.ignore_nulls else "first"]

    def merge_ops(self):
        return ["any_valid" if self.ignore_nulls else "first"]


class Last(AggregateFunction):
    def __init__(self, child: Expression, ignore_nulls: bool = False):
        super().__init__([child])
        self.ignore_nulls = ignore_nulls

    @property
    def dtype(self):
        return self.children[0].dtype

    @property
    def deterministic(self):
        return False

    def partial_types(self):
        return [self.dtype]

    def update_ops(self):
        return ["last"]

    def merge_ops(self):
        return ["last"]


class _CentralMoment(AggregateFunction):
    """Shared base for variance/stddev (Spark CentralMomentAgg / cuDF
    variance role, AggregateFunctions.scala).

    Partials: [sum, count, m2, r] where ``m2`` is the EXACT per-batch
    centered second moment (kernel op computes it shifted by the group's
    first value — no large-magnitude cancellation) and ``r`` is the
    Konig correction term (sum)^2/n. All four merge by plain addition;
    the final evaluation recovers the total moment as
    ``m2 + (sum_of_r - s^2/n)`` — exact for a single batch (the
    correction cancels identically) and mean-dispersion-accurate across
    merged batches.

    ``_denom_minus``: 0 for population, 1 for sample. Sample variants
    return NaN for single-row groups (Spark CentralMomentAgg n==1) and
    NULL for empty/all-null groups via partial validity."""

    abstract = True
    _denom_minus = 1
    _sqrt = False

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return dt.FLOAT64

    @property
    def nullable(self):
        return True

    def partial_types(self):
        return [dt.FLOAT64, dt.INT64, dt.FLOAT64, dt.FLOAT64]

    def update_ops(self):
        return ["sum", "count", "m2", "rterm"]

    def merge_ops(self):
        return ["sum", "sum", "sum", "sum"]

    def evaluate(self, partials: List[Expression]) -> Expression:
        from spark_rapids_tpu.expressions.arithmetic import (Add, Divide,
                                                             Multiply,
                                                             Subtract)
        from spark_rapids_tpu.expressions.cast import Cast
        from spark_rapids_tpu.expressions.conditional import If
        from spark_rapids_tpu.expressions.math import Sqrt
        from spark_rapids_tpu.expressions.predicates import (EqualTo,
                                                             LessThan)
        from spark_rapids_tpu.expressions.base import Literal

        s, n, m2, r = partials
        nf = Cast(n, dt.FLOAT64)
        # Konig merge correction: zero (exactly) when one batch
        corr = Subtract(r, Divide(Multiply(s, s), nf))
        total = Add(m2, corr)
        total = If(LessThan(total, Literal(0.0)), Literal(0.0), total)
        denom = Subtract(nf, Literal(float(self._denom_minus))) \
            if self._denom_minus else nf
        out = Divide(total, denom)
        if self._denom_minus:
            # Spark: sample variance/stddev of ONE row is NaN, not NULL
            out = If(EqualTo(n, Literal(1, dt.INT64)),
                     Literal(float("nan"), dt.FLOAT64), out)
        return Sqrt(out) if self._sqrt else out


class VarianceSamp(_CentralMoment):
    """var_samp / variance."""


class VariancePop(_CentralMoment):
    _denom_minus = 0


class StddevSamp(_CentralMoment):
    """stddev_samp / stddev / std."""

    _sqrt = True


class StddevPop(_CentralMoment):
    _denom_minus = 0
    _sqrt = True
