"""Conditional & null-handling expressions (reference
.../conditionalExpressions.scala + nullExpressions.scala): If, CaseWhen,
Coalesce, Nvl/IfNull, NaNvl.

Unlike the reference's lazy per-branch evaluation (both branches are cheap
under XLA fusion and select is free), branches evaluate unconditionally and
combine with ``where`` — the idiomatic compiler-friendly form.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.column import Scalar
from spark_rapids_tpu.expressions.base import (
    ColV,
    EvalContext,
    EvalValue,
    Expression,
    broadcast,
)


def _string_safe(children: List[Expression]) -> bool:
    return all(c.dtype is not dt.STRING for c in children)


class If(Expression):
    def __init__(self, pred: Expression, then: Expression, other: Expression):
        super().__init__([pred, then, other])

    @property
    def dtype(self):
        return self.children[1].dtype

    @property
    def device_only(self) -> bool:
        # string branches need dictionary merge -> eager
        return super().device_only and self.dtype is not dt.STRING

    def eval(self, ctx: EvalContext) -> EvalValue:
        pred = self.children[0].eval(ctx)
        if isinstance(pred, Scalar):
            pick = self.children[1] if (not pred.is_null and pred.value) \
                else self.children[2]
            return pick.eval(ctx)
        t = self.children[1].eval(ctx)
        e = self.children[2].eval(ctx)
        return _select(ctx, pred, t, e, self.dtype)


class CaseWhen(Expression):
    def __init__(self, branches: List[Tuple[Expression, Expression]],
                 else_value: Optional[Expression] = None):
        flat: List[Expression] = []
        for c, v in branches:
            flat.extend([c, v])
        if else_value is not None:
            flat.append(else_value)
        super().__init__(flat)
        self.n_branches = len(branches)
        self.has_else = else_value is not None

    @property
    def dtype(self):
        return self.children[1].dtype

    @property
    def device_only(self) -> bool:
        return super().device_only and self.dtype is not dt.STRING

    def eval(self, ctx: EvalContext) -> EvalValue:
        out_t = self.dtype
        if self.has_else:
            result = self.children[-1].eval(ctx)
        else:
            result = Scalar(out_t, None)
        # fold right-to-left so earlier branches win
        for i in reversed(range(self.n_branches)):
            pred = self.children[2 * i].eval(ctx)
            val = self.children[2 * i + 1].eval(ctx)
            if isinstance(pred, Scalar):
                if not pred.is_null and pred.value:
                    result = val
                continue
            result = _select(ctx, pred, val, result, out_t)
        return result


class Coalesce(Expression):
    def __init__(self, children: List[Expression]):
        super().__init__(children)

    @property
    def dtype(self):
        return self.children[0].dtype

    @property
    def device_only(self) -> bool:
        return super().device_only and self.dtype is not dt.STRING

    def eval(self, ctx: EvalContext) -> EvalValue:
        result: EvalValue = Scalar(self.dtype, None)
        for c in reversed(self.children):
            v = c.eval(ctx)
            if isinstance(v, Scalar):
                if not v.is_null:
                    result = v
                continue
            if v.validity is None:
                result = v
                continue
            pred = ColV(dt.BOOLEAN, v.validity, None)
            result = _select(ctx, pred, v, result, self.dtype)
        return result


class Nvl(Coalesce):
    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])


class NaNvl(Expression):
    """nanvl(a, b): a unless a is NaN."""

    def __init__(self, left, right):
        super().__init__([left, right])

    @property
    def dtype(self):
        return self.children[0].dtype

    def eval(self, ctx):
        a = self.children[0].eval(ctx)
        b = self.children[1].eval(ctx)
        av = broadcast(a, ctx)
        # pick the replacement ONLY for valid NaN inputs; NULL left -> NULL
        # (null slots hold a NaN sentinel, so mask with validity)
        a_valid = av.validity if av.validity is not None else \
            jnp.ones(ctx.capacity, bool)
        pick_a = (~jnp.isnan(av.data)) | (~a_valid)
        pred = ColV(dt.BOOLEAN, pick_a, None)
        return _select(ctx, pred, av, b, self.dtype)


def _select(ctx: EvalContext, pred: ColV, t: EvalValue, e: EvalValue,
            out_t: dt.DType) -> ColV:
    """where(pred is true, t, e) with Spark null semantics: null predicate
    selects the else branch; result validity follows the chosen side."""
    if out_t is dt.STRING:
        tb, eb = broadcast(t, ctx), broadcast(e, ctx)
        from spark_rapids_tpu.columnar.column import StringColumn, \
            unify_dictionaries

        st = tb.scol if tb.scol is not None else None
        se = eb.scol if eb.scol is not None else None
        assert st is not None and se is not None
        ut, ue = unify_dictionaries([
            StringColumn(tb.data, st.dictionary, tb.validity),
            StringColumn(eb.data, se.dictionary, eb.validity)])
        tb = ColV(dt.STRING, ut.data, ut.validity, ut)
        eb = ColV(dt.STRING, ue.data, ue.validity, ue)
    else:
        tb, eb = broadcast(t, ctx), broadcast(e, ctx)
    cond = pred.data
    if pred.validity is not None:
        cond = cond & pred.validity
    data = jnp.where(cond, tb.data, eb.data)
    tvalid = tb.validity if tb.validity is not None else \
        jnp.ones(ctx.capacity, bool)
    evalid = eb.validity if eb.validity is not None else \
        jnp.ones(ctx.capacity, bool)
    validity = jnp.where(cond, tvalid, evalid)
    if tb.validity is None and eb.validity is None:
        validity = None
    scol = tb.scol if out_t is dt.STRING else None
    return ColV(out_t, data, validity, scol)


class _GreatestLeast(Expression):
    """n-ary greatest/least with Spark null-skipping (NULL only when all
    arguments are NULL). One evaluation per child — the planner must NOT
    lower these as nested Ifs (3^n trace blowup, r3 review finding).
    NaN follows Spark's NaN-is-largest ordering: greatest propagates NaN
    (jnp.maximum), least SKIPS it (jnp.fmin). STRING inputs are
    unsupported (dictionary codes are not comparable across columns)."""

    abstract = True
    _combine = None

    def __init__(self, children: List[Expression]):
        assert len(children) >= 2
        if any(c.dtype is dt.STRING for c in children):
            raise TypeError("greatest/least over strings is unsupported")
        super().__init__(children)

    @property
    def dtype(self):
        return self.children[0].dtype

    @property
    def nullable(self):
        return all(c.nullable for c in self.children)

    @property
    def device_only(self) -> bool:
        return super().device_only and self.dtype is not dt.STRING

    def eval(self, ctx: EvalContext) -> EvalValue:
        op = type(self)._combine
        acc = broadcast(self.children[0].eval(ctx), ctx)
        data, valid = acc.data, acc.validity
        for c in self.children[1:]:
            v = broadcast(c.eval(ctx), ctx)
            combined = op(data, v.data)
            if valid is None and v.validity is None:
                data = combined
            elif valid is None:
                data = jnp.where(v.validity, combined, data)
                # acc always valid -> result stays valid
            elif v.validity is None:
                data = jnp.where(valid, combined, v.data)
                valid = None
            else:
                data = jnp.where(
                    valid & v.validity, combined,
                    jnp.where(valid, data, v.data))
                valid = valid | v.validity
        return ColV(self.dtype, data, valid)


class Greatest(_GreatestLeast):
    _combine = staticmethod(jnp.maximum)


class Least(_GreatestLeast):
    # fmin: prefer the non-NaN operand — Spark orders NaN LARGEST, so
    # least() skips NaN while greatest() (jnp.maximum) propagates it
    _combine = staticmethod(jnp.fmin)
