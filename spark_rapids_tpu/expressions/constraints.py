"""Float-normalization constraint expressions (reference:
sql-plugin/.../NormalizeFloatingNumbers.scala via GpuOverrides registry,
constraintExpressions.scala): Catalyst inserts these around grouping/join
keys; the engine must honor them so NaN/-0.0 keys group identically."""
from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions.base import Expression, eval_unary


class NormalizeNaNAndZero(Expression):
    """-0.0 -> +0.0 and every NaN -> one canonical NaN."""

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self) -> dt.DType:
        return self.children[0].dtype

    def eval(self, ctx):
        def f(x):
            # explicit select: XLA's algebraic simplifier folds x + 0.0
            # back to x, which would keep -0.0's sign
            x = jnp.where(x == 0, jnp.asarray(0.0, dtype=x.dtype), x)
            return jnp.where(jnp.isnan(x),
                             jnp.asarray(jnp.nan, dtype=x.dtype), x)

        return eval_unary(self, ctx, f, self.dtype)


class KnownFloatingPointNormalized(Expression):
    """Marker: the child is already normalized — evaluation is identity
    (constraintExpressions.scala)."""

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self) -> dt.DType:
        return self.children[0].dtype

    def eval(self, ctx):
        return self.children[0].eval(ctx)
