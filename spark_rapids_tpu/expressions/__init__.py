"""Expression layer: ~80 expression classes mirroring the reference's GPU
expression inventory (SURVEY.md §2.5), evaluated either fused-in-jit or
eagerly with dictionary transforms (see compiler.py)."""
from spark_rapids_tpu.expressions.base import (  # noqa: F401
    Alias,
    BoundReference,
    ColV,
    EvalContext,
    Expression,
    Literal,
)
from spark_rapids_tpu.expressions.arithmetic import (  # noqa: F401
    Abs,
    Add,
    Divide,
    IntegralDivide,
    Multiply,
    Pmod,
    Remainder,
    Signum,
    Subtract,
    UnaryMinus,
    UnaryPositive,
)
from spark_rapids_tpu.expressions.predicates import (  # noqa: F401
    And,
    AtLeastNNonNulls,
    EqualNullSafe,
    EqualTo,
    GreaterThan,
    GreaterThanOrEqual,
    In,
    IsNaN,
    IsNotNull,
    IsNull,
    LessThan,
    LessThanOrEqual,
    Not,
    Or,
)
from spark_rapids_tpu.expressions.conditional import (  # noqa: F401
    CaseWhen,
    Coalesce,
    If,
    NaNvl,
    Nvl,
)
from spark_rapids_tpu.expressions.cast import Cast  # noqa: F401
from spark_rapids_tpu.expressions.compiler import (  # noqa: F401
    CompiledFilter,
    CompiledProjection,
)
from spark_rapids_tpu.expressions.aggregates import (  # noqa: F401
    AggregateFunction,
    Average,
    Count,
    First,
    Last,
    Max,
    Min,
    Sum,
)
from spark_rapids_tpu.expressions.predicates import InSet  # noqa: F401
from spark_rapids_tpu.expressions.constraints import (  # noqa: F401
    KnownFloatingPointNormalized,
    NormalizeNaNAndZero,
)
from spark_rapids_tpu.expressions.conditional import CaseWhen  # noqa: F401
