"""String expressions (reference .../stringFunctions.scala, 862 LoC:
substr/pad/split/locate/replace/trim/starts/ends/contains/like/concat/
upper/lower/length).

TPU-native strategy: strings are dictionary-encoded (sorted dict host-side,
int32 codes on device). Every string function factors as

    per-dictionary-entry host transform  (once per UNIQUE value)
  + device gather by code               (once per row)

so row-scale work stays on device and host work is O(cardinality). This is
the honest TPU answer to cuDF's native string kernels (SURVEY.md §7
"Strings" flags them as the biggest compat risk): semantics first, with the
host transform amortized across batches by dictionary caching.

These nodes are ``device_only = False`` — the planner keeps them out of
fused jit regions (they still do their row-scale gathers on device).

LIKE patterns support %, _ with regex translation; the reference similarly
gates regexp to trivially-convertible patterns (GpuOverrides.scala:343-351).
"""
from __future__ import annotations

import re
from typing import Callable, List, Optional

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.column import Scalar, StringColumn
from spark_rapids_tpu.expressions.base import ColV, EvalContext, EvalValue, \
    Expression


def _dict_map_str(v: ColV, fn: Callable[[str], str]) -> ColV:
    """str->str via dictionary rebuild + device remap."""
    assert v.scol is not None
    dic = v.scol.dictionary
    if len(dic) == 0:
        return v
    transformed = np.array([fn(str(s)) for s in dic], dtype=object)
    new_dict, inv = np.unique(transformed.astype(str), return_inverse=True)
    remap = jnp.asarray(inv.astype(np.int32))
    codes = jnp.take(remap, v.data, mode="clip")
    sc = StringColumn(codes, new_dict.astype(object), v.validity)
    return ColV(dt.STRING, codes, v.validity, sc)


def _dict_map_val(v: ColV, fn: Callable[[str], object],
                  out_dtype: dt.DType) -> ColV:
    """str->numeric/bool via per-entry table + device gather."""
    assert v.scol is not None
    dic = v.scol.dictionary
    table = np.array([fn(str(s)) for s in dic] if len(dic) else [0],
                     dtype=out_dtype.np_dtype)
    data = jnp.take(jnp.asarray(table), v.data, mode="clip")
    return ColV(out_dtype, data, v.validity)


def _eval_str_unary(expr: Expression, ctx: EvalContext, fn_str,
                    out_dtype: dt.DType) -> EvalValue:
    v = expr.children[0].eval(ctx)
    if isinstance(v, Scalar):
        if v.is_null:
            return Scalar(out_dtype, None)
        return Scalar(out_dtype, fn_str(str(v.value)))
    if out_dtype is dt.STRING:
        return _dict_map_str(v, fn_str)
    return _dict_map_val(v, fn_str, out_dtype)


class _StrUnary(Expression):
    out_type = dt.STRING

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return type(self).out_type

    @property
    def device_only(self):
        return False

    def fn(self, s: str):  # pragma: no cover - abstract
        raise NotImplementedError

    def eval(self, ctx):
        return _eval_str_unary(self, ctx, self.fn, self.dtype)


class Upper(_StrUnary):
    """Flagged incompat in the reference for non-ASCII unicode corner cases
    (GpuOverrides.scala:337-340); python .upper() is unicode-correct."""

    def fn(self, s):
        return s.upper()


class Lower(_StrUnary):
    def fn(self, s):
        return s.lower()


class Length(_StrUnary):
    out_type = dt.INT32

    def fn(self, s):
        return len(s)


class StringTrim(_StrUnary):
    def fn(self, s):
        return s.strip()


class StringTrimLeft(_StrUnary):
    def fn(self, s):
        return s.lstrip()


class StringTrimRight(_StrUnary):
    def fn(self, s):
        return s.rstrip()


class InitCap(_StrUnary):
    def fn(self, s):
        return " ".join(w.capitalize() for w in s.split(" "))


class Reverse(_StrUnary):
    def fn(self, s):
        return s[::-1]


class Substring(Expression):
    """substring(str, pos, len) with Spark 1-based/negative pos semantics.
    pos/len must be literals (the planner falls back otherwise — matching
    the reference's lit-only GpuSubstring, GpuOverrides.scala:398-421)."""

    def __init__(self, child: Expression, pos: int, length: Optional[int]):
        super().__init__([child])
        self.pos = pos
        self.length = length

    @property
    def dtype(self):
        return dt.STRING

    @property
    def device_only(self):
        return False

    def fn(self, s: str) -> str:
        pos, ln = self.pos, self.length
        if pos > 0:
            start = pos - 1
        elif pos < 0:
            start = len(s) + pos
        else:
            start = 0
        # clamp only after end is derived from the unclamped start, so
        # substring('abc', -5, 2) = '' (Spark UTF8String.substringSQL), not 'ab'
        end = len(s) if ln is None else start + ln
        return s[max(start, 0):max(end, 0)]

    def eval(self, ctx):
        v = self.children[0].eval(ctx)
        if not isinstance(v, Scalar):
            from spark_rapids_tpu.native.kernels import strings as nks

            out = nks.substring_colv(v, self.pos, self.length)
            if out is not None:
                return out
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(dt.STRING, None)
            return Scalar(dt.STRING, self.fn(str(v.value)))
        return _dict_map_str(v, self.fn)


class StringReplace(Expression):
    def __init__(self, child: Expression, search: str, replace: str):
        super().__init__([child])
        self.search = search
        self.replace = replace

    @property
    def dtype(self):
        return dt.STRING

    @property
    def device_only(self):
        return False

    def eval(self, ctx):
        return _eval_str_unary(
            self, ctx, lambda s: s.replace(self.search, self.replace),
            dt.STRING)


class SubstringIndex(_StrUnary):
    """substring_index(str, delim, count): count>0 keeps everything
    before the count-th delimiter from the left, count<0 everything after
    the |count|-th from the right, 0 -> empty (Spark semantics)."""

    def __init__(self, child: Expression, delim: str, count: int):
        super().__init__(child)
        self.delim = delim
        self.count = count

    def fn(self, s):
        if self.count == 0 or not self.delim:
            return ""
        parts = s.split(self.delim)
        if self.count > 0:
            return self.delim.join(parts[:self.count])
        return self.delim.join(parts[self.count:])


_REGEX_METACHARS = set("\\^$.|?*+()[]{}")


class RegExpReplace(_StrUnary):
    """regexp_replace limited to regex-free search patterns — exactly the
    reference's constraint (GpuOverrides.scala:343-351
    isSupportedStringReplacePattern gates GpuRegExpReplace on patterns
    with no regex metacharacters); anything else falls back to the CPU
    engine, whose oracle implementation runs the full regex."""

    def __init__(self, child: Expression, pattern: str, replacement: str):
        super().__init__(child)
        self.pattern = pattern
        self.replacement = replacement

    def fn(self, s):
        return s.replace(self.pattern, self.replacement)

    def tag_self(self, meta, conf):
        if not self.pattern or \
                any(c in _REGEX_METACHARS for c in self.pattern):
            meta.will_not_work(
                "regexp_replace on the TPU requires a non-empty, "
                "regex-free pattern (GpuOverrides.scala:343-351)")
        if "\\" in self.replacement or "$" in self.replacement:
            meta.will_not_work(
                "regexp_replace replacement must not contain "
                "backreferences (GpuOverrides.scala:423-438)")


class StringRepeat(Expression):
    def __init__(self, child: Expression, times: int):
        super().__init__([child])
        self.times = times

    @property
    def dtype(self):
        return dt.STRING

    @property
    def device_only(self):
        return False

    def eval(self, ctx):
        return _eval_str_unary(self, ctx, lambda s: s * max(self.times, 0),
                               dt.STRING)


class _Pad(Expression):
    left = True

    def __init__(self, child: Expression, width: int, pad: str = " "):
        super().__init__([child])
        self.width = width
        self.pad = pad

    @property
    def dtype(self):
        return dt.STRING

    @property
    def device_only(self):
        return False

    def fn(self, s: str) -> str:
        w, p = self.width, self.pad
        if len(s) >= w:
            return s[:w]
        if not p:
            return s
        fill = (p * w)[: w - len(s)]
        return fill + s if type(self).left else s + fill

    def eval(self, ctx):
        return _eval_str_unary(self, ctx, self.fn, dt.STRING)


class StringLPad(_Pad):
    left = True


class StringRPad(_Pad):
    left = False


class _StrPredicate(Expression):
    """starts_with/ends_with/contains vs a literal needle."""

    def __init__(self, child: Expression, needle: str):
        super().__init__([child])
        self.needle = needle

    @property
    def dtype(self):
        return dt.BOOLEAN

    @property
    def device_only(self):
        return False

    # native-kernel route for this predicate ('starts'/'ends'/
    # 'contains'/'like'); None keeps the host path unconditionally
    _kernel_kind: Optional[str] = None

    def test(self, s: str) -> bool:  # pragma: no cover - abstract
        raise NotImplementedError

    def eval(self, ctx):
        v = self.children[0].eval(ctx)
        if isinstance(v, Scalar):
            if v.is_null:
                return Scalar(dt.BOOLEAN, None)
            return Scalar(dt.BOOLEAN, self.test(str(v.value)))
        if self._kernel_kind is not None:
            from spark_rapids_tpu.native.kernels import strings as nks

            out = nks.predicate_colv(v, self._kernel_kind, self.needle,
                                     getattr(self, "escape", None))
            if out is not None:
                return out
        return _dict_map_val(v, self.test, dt.BOOLEAN)


class StartsWith(_StrPredicate):
    _kernel_kind = "starts"

    def test(self, s):
        return s.startswith(self.needle)


class EndsWith(_StrPredicate):
    _kernel_kind = "ends"

    def test(self, s):
        return s.endswith(self.needle)


class Contains(_StrPredicate):
    _kernel_kind = "contains"

    def test(self, s):
        return self.needle in s


class Like(_StrPredicate):
    """SQL LIKE: % any-seq, _ any-char, escape supported."""

    _kernel_kind = "like"

    def __init__(self, child: Expression, pattern: str, escape: str = "\\"):
        super().__init__(child, pattern)
        self.pattern = pattern
        self.escape = escape
        regex = []
        i = 0
        while i < len(pattern):
            ch = pattern[i]
            if ch == escape and i + 1 < len(pattern):
                regex.append(re.escape(pattern[i + 1]))
                i += 2
                continue
            if ch == "%":
                regex.append(".*")
            elif ch == "_":
                regex.append(".")
            else:
                regex.append(re.escape(ch))
            i += 1
        self._re = re.compile("(?s)^" + "".join(regex) + "$")

    def test(self, s):
        return self._re.match(s) is not None


class StringLocate(Expression):
    """locate(needle, str, start=1): 1-based position, 0 if absent."""

    def __init__(self, needle: str, child: Expression, start: int = 1):
        super().__init__([child])
        self.needle = needle
        self.start = start

    @property
    def dtype(self):
        return dt.INT32

    @property
    def device_only(self):
        return False

    def eval(self, ctx):
        def f(s: str) -> int:
            return s.find(self.needle, max(self.start - 1, 0)) + 1

        return _eval_str_unary(self, ctx, f, dt.INT32)


class ConcatStrings(Expression):
    """concat of N string columns. Multi-column dictionary products can
    explode, so this materializes rows host-side — correct first; planner
    marks it high-cost. Null if any input null (Spark concat)."""

    def __init__(self, children: List[Expression]):
        super().__init__(children)

    @property
    def dtype(self):
        return dt.STRING

    @property
    def device_only(self):
        return False

    def eval(self, ctx):
        import jax

        parts = []
        validity = None
        for c in self.children:
            v = c.eval(ctx)
            if isinstance(v, Scalar):
                if v.is_null:
                    return Scalar(dt.STRING, None)
                parts.append([str(v.value)])
                continue
            scol = v.scol
            assert scol is not None
            codes = np.asarray(jax.device_get(v.data))
            dic = scol.dictionary
            vals = dic[np.clip(codes, 0, max(len(dic) - 1, 0))] \
                if len(dic) else np.full(len(codes), "", dtype=object)
            parts.append(vals)
            if v.validity is not None:
                vv = v.validity
                validity = vv if validity is None else (validity & vv)
        cap = ctx.capacity
        out = []
        for i in range(cap):
            out.append("".join(
                str(p[i] if len(p) > 1 else p[0]) for p in parts))
        sc = StringColumn.from_strings(out, capacity=cap)
        return ColV(dt.STRING, sc.data, validity, sc)


#: Spark's Concat over string children — same node (the reference
#: registers Concat, GpuOverrides.scala registry)
Concat = ConcatStrings
