"""Filter as masked stable compaction.

cuDF ``tbl.filter(mask)`` (reference basicPhysicalOperators.scala:100-130)
allocates an exact-sized output. Under XLA we keep the capacity static:
a stable argsort on the negated keep-mask moves kept rows to the front in
their original order, and the new row count travels as a device scalar —
no host sync, the whole scan->filter->... chain stays on device.
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column

ColPair = Tuple[jax.Array, Optional[jax.Array]]


@jax.jit
def _compact(datas, validities, keep: jax.Array, num_rows: jax.Array):
    capacity = keep.shape[0]
    live = jnp.arange(capacity, dtype=jnp.int32) < num_rows
    keep = keep & live
    # stable: kept rows first, original order preserved
    order = jnp.argsort(~keep, stable=True)
    new_count = jnp.sum(keep).astype(jnp.int32)
    out_datas = [jnp.take(d, order) for d in datas]
    out_validities = [None if v is None else jnp.take(v, order)
                      for v in validities]
    return out_datas, out_validities, new_count


def compact_batch(batch: ColumnarBatch, keep: jax.Array,
                  keep_validity: Optional[jax.Array] = None) -> ColumnarBatch:
    """Rows where keep is true AND valid survive (SQL WHERE drops
    null-predicate rows)."""
    if keep_validity is not None:
        keep = keep & keep_validity
    datas = [c.data for c in batch.columns]
    validities = [c.validity for c in batch.columns]
    out_d, out_v, new_count = _compact(datas, validities, keep,
                                       batch.num_rows_device())
    cols = [c._like(d, v)
            for c, d, v in zip(batch.columns, out_d, out_v)]
    return ColumnarBatch(cols, new_count)


@partial(jax.jit, static_argnames=("out_capacity",))
def shrink_to(datas, validities, num_rows: jax.Array, out_capacity: int):
    """Copy the live prefix into a smaller capacity (post-filter
    re-bucketing at coalesce boundaries)."""
    out_d = [d[:out_capacity] for d in datas]
    out_v = [None if v is None else v[:out_capacity] for v in validities]
    return out_d, out_v


def rebucket(batch: ColumnarBatch) -> ColumnarBatch:
    """Re-bucket a batch to the tightest capacity for its realized count
    (host-sync; used at materialization/shuffle boundaries)."""
    from spark_rapids_tpu.ops.buckets import bucket_capacity

    n = batch.realized_num_rows()
    cap = bucket_capacity(n)
    if cap >= batch.capacity:
        return batch
    datas = [c.data for c in batch.columns]
    validities = [c.validity for c in batch.columns]
    out_d, out_v = shrink_to(datas, validities, batch.num_rows_device(), cap)
    cols = [c._like(d, v) for c, d, v in zip(batch.columns, out_d, out_v)]
    return ColumnarBatch(cols, n)
