"""Kernel surface: the TPU-native replacement for the cuDF JNI surface the
reference consumes (SURVEY.md §2.9). Every relational kernel is a
jit-compiled XLA computation over bucketed-capacity columns.

Import submodules directly (spark_rapids_tpu.ops.groupby etc.) — this
package init stays empty to avoid columnar<->ops import cycles.
"""
