"""Batch concatenation (cuDF ``Table.concatenate`` analogue).

Feeds the coalescing engine (GpuCoalesceBatches.scala:129-490). Row counts
are realized host-side here — concatenation IS the batch boundary where the
reference also materializes sizes. Output capacity is the bucket of the total
row count; each input's live prefix is placed with ``dynamic_update_slice``.
String columns are first re-encoded onto a unified dictionary.
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, StringColumn, unify_dictionaries
from spark_rapids_tpu.ops.buckets import bucket_capacity


def concat_batches(batches: List[ColumnarBatch]) -> ColumnarBatch:
    batches = [b for b in batches if b is not None]
    assert batches, "concat of zero batches"
    if len(batches) == 1:
        return batches[0]
    ncols = batches[0].num_columns
    counts = ColumnarBatch.realize_counts(batches)  # one sync, not N
    total = sum(counts)
    out_cap = bucket_capacity(total)

    # strings first: dictionary unification is host-side and replaces
    # the code arrays the kernel consumes
    per_col_cols: List[List[Column]] = []
    dictionaries: List = []
    for ci in range(ncols):
        cols = [b.columns[ci] for b in batches]
        if isinstance(cols[0], StringColumn):
            cols = unify_dictionaries(cols)  # type: ignore[arg-type]
            dictionaries.append(cols[0].dictionary)
        else:
            dictionaries.append(None)
        per_col_cols.append(cols)

    # ONE jitted program assembles every column (the per-placement
    # eager dispatches - capacity slices + dynamic_update_slices - each
    # paid a device round trip; offsets/counts ride as traced scalars
    # so one compilation serves every count pattern at this signature)
    datas = tuple(tuple(c.data for c in cols) for cols in per_col_cols)
    valids = tuple(tuple(c.validity for c in cols)
                   for cols in per_col_cols)
    import numpy as np

    offs = np.zeros(len(batches), dtype=np.int64)
    np.cumsum(counts[:-1], out=offs[1:])
    out_d, out_v = _concat_kernel(datas, valids,
                                  jnp.asarray(offs),
                                  jnp.asarray(np.asarray(counts,
                                                         dtype=np.int64)),
                                  out_cap)
    out_cols: List[Column] = []
    for ci in range(ncols):
        if dictionaries[ci] is not None:
            out_cols.append(StringColumn(out_d[ci], dictionaries[ci],
                                         out_v[ci]))
        else:
            out_cols.append(Column(per_col_cols[ci][0].dtype, out_d[ci],
                                   out_v[ci]))
    return ColumnarBatch(out_cols, total)


def _fit(x: jax.Array, cap: int) -> jax.Array:
    """Static resize to ``cap`` inside a trace (slice or zero-pad)."""
    n = x.shape[0]
    if n == cap:
        return x
    if n > cap:
        return x[:cap]
    return jnp.concatenate([x, jnp.zeros(cap - n, dtype=x.dtype)])


@partial(jax.jit, static_argnames=("out_cap",))
def _concat_kernel(datas, valids, offs, ns, out_cap: int):
    out_d, out_v = [], []
    for col_datas, col_valids in zip(datas, valids):
        any_v = any(v is not None for v in col_valids)
        acc = jnp.zeros(out_cap, dtype=col_datas[0].dtype)
        accv = jnp.zeros(out_cap, dtype=bool) if any_v else None
        for bi, (d, v) in enumerate(zip(col_datas, col_valids)):
            acc = _place_traced(acc, _fit(d, out_cap), offs[bi], ns[bi])
            if any_v:
                vv = jnp.ones(out_cap, dtype=bool) if v is None \
                    else _fit(v, out_cap)
                accv = _place_traced(accv, vv, offs[bi], ns[bi])
        out_d.append(acc)
        out_v.append(accv)
    return out_d, out_v


def interleave_batches(batches: List[ColumnarBatch]) -> ColumnarBatch:
    """Row-major interleave of same-schema, same-num_rows batches: output
    row i*k+j comes from batches[j] row i. This is Spark's ExpandExec /
    explode emission order (one output row per (input row, projection)
    pair, projections adjacent). A stack+reshape keeps live rows in the
    prefix [0, n*k): with every input's live rows in [0, n), output slot
    i*k+j < n*k iff i < n."""
    assert batches, "interleave of zero batches"
    if len(batches) == 1:
        return batches[0]
    k = len(batches)
    ncols = batches[0].num_columns
    n = batches[0].realized_num_rows()
    assert all(b.realized_num_rows() == n for b in batches), \
        "interleave requires equal row counts"
    cap = max(b.capacity for b in batches)

    out_cols: List[Column] = []
    for ci in range(ncols):
        cols = [b.columns[ci].with_capacity(cap) for b in batches]
        if isinstance(cols[0], StringColumn):
            cols = unify_dictionaries(cols)  # type: ignore[arg-type]
            dictionary = cols[0].dictionary
        else:
            dictionary = None
        data = _interleave([c.data for c in cols])
        if any(c.validity is not None for c in cols):
            validity = _interleave(
                [c.validity if c.validity is not None
                 else jnp.ones(cap, dtype=bool) for c in cols])
        else:
            validity = None
        if dictionary is not None:
            out_cols.append(StringColumn(data, dictionary, validity))
        else:
            out_cols.append(Column(cols[0].dtype, data, validity))
    return ColumnarBatch(out_cols, n * k)


@jax.jit
def _interleave(arrs: List[jax.Array]) -> jax.Array:
    return jnp.stack(arrs, axis=1).reshape(-1)


def _place_traced(dst: jax.Array, src: jax.Array, offset, n):
    """Write src[0:n] into dst[offset:offset+n]. ``offset``/``n`` are traced
    scalars (a single shifted gather + select — no dynamic shapes);
    runs INSIDE _concat_kernel's trace."""
    cap = dst.shape[0]
    idx = jnp.arange(cap, dtype=jnp.int64) - offset
    vals = jnp.take(src, jnp.clip(idx, 0, cap - 1))
    mask = (idx >= 0) & (idx < n)
    return jnp.where(mask, vals, dst)
