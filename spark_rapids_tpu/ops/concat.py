"""Batch concatenation (cuDF ``Table.concatenate`` analogue).

Feeds the coalescing engine (GpuCoalesceBatches.scala:129-490). Row counts
are realized host-side here — concatenation IS the batch boundary where the
reference also materializes sizes. Output capacity is the bucket of the total
row count; each input's live prefix is placed with ``dynamic_update_slice``.
String columns are first re-encoded onto a unified dictionary.
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, StringColumn, unify_dictionaries
from spark_rapids_tpu.ops.buckets import bucket_capacity


def concat_batches(batches: List[ColumnarBatch]) -> ColumnarBatch:
    batches = [b for b in batches if b is not None]
    assert batches, "concat of zero batches"
    if len(batches) == 1:
        return batches[0]
    ncols = batches[0].num_columns
    counts = [b.realized_num_rows() for b in batches]
    total = sum(counts)
    out_cap = bucket_capacity(total)

    out_cols: List[Column] = []
    for ci in range(ncols):
        cols = [b.columns[ci] for b in batches]
        if isinstance(cols[0], StringColumn):
            cols = unify_dictionaries(cols)  # type: ignore[arg-type]
            dictionary = cols[0].dictionary
        else:
            dictionary = None
        any_validity = any(c.validity is not None for c in cols)
        data = jnp.zeros(out_cap, dtype=cols[0].data.dtype)
        validity = jnp.zeros(out_cap, dtype=bool) if any_validity else None
        off = 0
        for c, n in zip(cols, counts):
            if n == 0:
                continue
            src = c.with_capacity(out_cap)
            data = _place(data, src.data, off, n)
            if any_validity:
                v = src.validity if src.validity is not None else \
                    jnp.ones(out_cap, dtype=bool)
                validity = _place(validity, v, off, n)
            off += n
        if dictionary is not None:
            out_cols.append(StringColumn(data, dictionary, validity))
        else:
            out_cols.append(Column(cols[0].dtype, data, validity))
    return ColumnarBatch(out_cols, total)


def interleave_batches(batches: List[ColumnarBatch]) -> ColumnarBatch:
    """Row-major interleave of same-schema, same-num_rows batches: output
    row i*k+j comes from batches[j] row i. This is Spark's ExpandExec /
    explode emission order (one output row per (input row, projection)
    pair, projections adjacent). A stack+reshape keeps live rows in the
    prefix [0, n*k): with every input's live rows in [0, n), output slot
    i*k+j < n*k iff i < n."""
    assert batches, "interleave of zero batches"
    if len(batches) == 1:
        return batches[0]
    k = len(batches)
    ncols = batches[0].num_columns
    n = batches[0].realized_num_rows()
    assert all(b.realized_num_rows() == n for b in batches), \
        "interleave requires equal row counts"
    cap = max(b.capacity for b in batches)

    out_cols: List[Column] = []
    for ci in range(ncols):
        cols = [b.columns[ci].with_capacity(cap) for b in batches]
        if isinstance(cols[0], StringColumn):
            cols = unify_dictionaries(cols)  # type: ignore[arg-type]
            dictionary = cols[0].dictionary
        else:
            dictionary = None
        data = _interleave([c.data for c in cols])
        if any(c.validity is not None for c in cols):
            validity = _interleave(
                [c.validity if c.validity is not None
                 else jnp.ones(cap, dtype=bool) for c in cols])
        else:
            validity = None
        if dictionary is not None:
            out_cols.append(StringColumn(data, dictionary, validity))
        else:
            out_cols.append(Column(cols[0].dtype, data, validity))
    return ColumnarBatch(out_cols, n * k)


@jax.jit
def _interleave(arrs: List[jax.Array]) -> jax.Array:
    return jnp.stack(arrs, axis=1).reshape(-1)


@jax.jit
def _place(dst: jax.Array, src: jax.Array, offset, n):
    """Write src[0:n] into dst[offset:offset+n]. ``offset``/``n`` are traced
    scalars, so one compilation serves every placement at a given capacity
    (a single shifted gather + select — no dynamic shapes)."""
    cap = dst.shape[0]
    idx = jnp.arange(cap, dtype=jnp.int64) - offset
    vals = jnp.take(src, jnp.clip(idx, 0, cap - 1))
    mask = (idx >= 0) & (idx < n)
    return jnp.where(mask, vals, dst)
