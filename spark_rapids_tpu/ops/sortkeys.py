"""Sort/equality key machinery shared by sort, groupby, join and partition.

cuDF's ``Table.orderBy``/``groupBy`` handle null ordering, NaN and descending
natively (reference: SortUtils.scala, GpuSortExec.scala:104). On TPU we reduce
every key column to a small list of arrays fed to one stable ``lexsort`` —
XLA lowers that to the native variadic sort HLO.

TPU constraint worth recording: ``bitcast_convert`` on f64 is not supported
by XLA's X64-rewriting pass on TPU (f64 is emulated as a float pair), so the
classic "bitcast float to int, twist sign" total-order key is *not* used on
device. Instead:

- floats stay floats in the sort (jnp sort order places NaN last, which is
  exactly Spark's "NaN greatest" for ascending); descending negates the
  value and adds a small NaN-rank key (Spark: DESC puts NaN first);
  -0.0 is normalized to +0.0 and NaNs canonicalized first,
- equality (grouping/join) uses *component lists*: two rows are equal iff
  all components compare equal — floats contribute (value-with-NaN-zeroed,
  isnan) so NaN==NaN without any bitcast,
- strings are dictionary codes (sorted dicts => order-isomorphic),
- nulls get a leading rank key implementing NULLS FIRST/LAST,
- padding rows (index >= num_rows) always sort last.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt


@dataclasses.dataclass(frozen=True)
class SortKeySpec:
    """One ORDER BY term: column ordinal + direction + null ordering."""

    ordinal: int
    ascending: bool = True
    nulls_first: bool = True  # Spark default: NULLS FIRST for ASC

    @staticmethod
    def spark_default(ordinal: int, ascending: bool = True) -> "SortKeySpec":
        # Spark: ASC -> NULLS FIRST, DESC -> NULLS LAST
        return SortKeySpec(ordinal, ascending, nulls_first=ascending)


def canonicalize_floats(x: jax.Array) -> jax.Array:
    """-0.0 -> +0.0 and all NaNs -> one canonical quiet NaN
    (NormalizeFloatingNumbers analogue, reference
    sql-plugin/.../NormalizeFloatingNumbers.scala).

    NOT ``x + 0``: XLA's algebraic simplifier folds add-zero away inside
    larger fused programs (observed on the CPU backend), silently
    keeping -0.0's sign bit. The select below survives optimization
    because IEEE ``-0.0 == 0.0`` is true, so both zeros take the +0.0
    branch."""
    zero = jnp.zeros((), dtype=x.dtype)
    x = jnp.where(x == zero, zero, x)
    return jnp.where(jnp.isnan(x), jnp.asarray(jnp.nan, dtype=x.dtype), x)


def sort_key_arrays(data: jax.Array, validity: Optional[jax.Array],
                    dtype: dt.DType, spec: SortKeySpec) -> List[jax.Array]:
    """Key arrays for one ORDER BY term, most significant first."""
    keys: List[jax.Array] = []
    if validity is not None:
        # valid rows rank 1 when nulls first, rank 0 when nulls last
        rank = validity.astype(jnp.int32) if spec.nulls_first \
            else (~validity).astype(jnp.int32)
        keys.append(rank)
    if dtype.is_floating:
        x = canonicalize_floats(data)
        if validity is not None:
            x = jnp.where(validity, x, jnp.zeros((), x.dtype))
        if spec.ascending:
            # jnp/np sort order: NaN greatest — matches Spark ASC
            keys.append(x)
        else:
            # DESC: NaN first => NaN-rank key ahead of the negated value
            isn = jnp.isnan(x)
            keys.append((~isn).astype(jnp.int32))
            keys.append(jnp.where(isn, jnp.zeros((), x.dtype), -x))
        return keys
    if dtype is dt.BOOLEAN:
        k = data.astype(jnp.int8)
    else:
        k = data
    if validity is not None:
        k = jnp.where(validity, k, jnp.zeros((), k.dtype))
    if not spec.ascending:
        k = ~k if k.dtype != jnp.int8 else -k.astype(jnp.int32)
    keys.append(k)
    return keys


def order_key_arrays(cols: List[Tuple[jax.Array, Optional[jax.Array]]],
                     dtypes: List[dt.DType],
                     specs: List[SortKeySpec],
                     num_rows: jax.Array,
                     live_mask: Optional[jax.Array] = None
                     ) -> List[jax.Array]:
    """Sort keys MOST significant first: pad rank (padding and
    masked-out rows last — ``live_mask`` is the fused-filter liveness),
    then each spec's key arrays. One builder feeds both the
    permutation-producing lexsort and the payload-carrying variadic
    sort so pad/liveness semantics can't drift apart."""
    capacity = cols[0][0].shape[0]
    pad_rank = (jnp.arange(capacity, dtype=jnp.int32) >=
                num_rows).astype(jnp.int32)
    if live_mask is not None:
        pad_rank = jnp.maximum(pad_rank, (~live_mask).astype(jnp.int32))
    keys: List[jax.Array] = [pad_rank]
    for spec in specs:
        data, validity = cols[spec.ordinal]
        keys.extend(sort_key_arrays(data, validity,
                                    dtypes[spec.ordinal], spec))
    return keys


def lexsort_indices(cols: List[Tuple[jax.Array, Optional[jax.Array]]],
                    dtypes: List[dt.DType],
                    specs: List[SortKeySpec],
                    num_rows: jax.Array,
                    live_mask: Optional[jax.Array] = None) -> jax.Array:
    """Stable permutation ordering live rows by ``specs``; padding rows
    sort last. ``cols`` indexed by spec.ordinal."""
    order = _kernel_order(cols, dtypes, specs, num_rows, live_mask)
    if order is not None:
        return order
    keys = order_key_arrays(cols, dtypes, specs, num_rows, live_mask)
    # jnp.lexsort: LAST key is primary
    return jnp.lexsort(list(reversed(keys)))


def _kernel_order(cols, dtypes, specs, num_rows, live_mask):
    """Native radix-kernel permutation when the sort gate is on and
    every key is radixable (no float bitcasts); None = jnp path."""
    from spark_rapids_tpu.native import kernels as nkr

    if not nkr.enabled("sort"):
        return None
    from spark_rapids_tpu.native.kernels import sort as nsort

    return nsort.lexsort_order(cols, dtypes, specs, num_rows, live_mask)


def sort_with_payloads(cols: List[Tuple[jax.Array, Optional[jax.Array]]],
                       dtypes: List[dt.DType],
                       specs: List[SortKeySpec],
                       num_rows: jax.Array,
                       payloads: List[jax.Array],
                       live_mask: Optional[jax.Array] = None
                       ) -> List[jax.Array]:
    """ONE stable variadic sort ordering live rows by ``specs`` (padding
    and masked-out rows last) that carries ``payloads`` through the sort
    network — replacing argsort + per-column permutation gathers
    (~75-150 ms/column at 4M rows on a v5e). Returns the sorted payloads
    in order."""
    order = _kernel_order(cols, dtypes, specs, num_rows, live_mask)
    if order is not None:
        return [jnp.take(p, order) for p in payloads]
    keys = order_key_arrays(cols, dtypes, specs, num_rows, live_mask)
    out = jax.lax.sort(tuple(keys) + tuple(payloads),
                       num_keys=len(keys), is_stable=True)
    return list(out[len(keys):])


def equality_parts(data: jax.Array, validity: Optional[jax.Array],
                   dtype: dt.DType) -> Tuple[List[jax.Array], jax.Array]:
    """(components, valid): rows are grouping/join-equal iff their validity
    matches and, when valid, every component compares equal. Implements
    NaN == NaN and -0.0 == 0.0 (Spark grouping semantics) without f64
    bitcasts."""
    valid = validity if validity is not None else \
        jnp.ones(data.shape[0], dtype=bool)
    if dtype.is_floating:
        x = canonicalize_floats(data)
        isn = jnp.isnan(x)
        xz = jnp.where(isn | ~valid, jnp.zeros((), x.dtype), x)
        return [xz, isn & valid], valid
    z = jnp.where(valid, data, jnp.zeros((), data.dtype))
    return [z], valid
