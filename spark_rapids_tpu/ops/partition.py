"""Device-side partitioning + contiguous split.

Replaces the cuDF ``Table.partition``/``contiguousSplit`` pair driven by the
reference's partitioners (GpuPartitioning.scala:44-70, GpuHashPartitioning,
GpuRoundRobinPartitioning, GpuRangePartitioning, GpuSinglePartitioning).

The kernel: compute a partition id per row, stable-sort rows by it (one XLA
sort), and compute per-partition counts with one segment_sum. The sorted
batch plus host-realized offsets is the analogue of a contiguous split —
each partition is a contiguous row range ready for slicing/serialization.
Range partitioning samples bounds host-side exactly like the reference
(GpuRangePartitioner.scala:42-95: CPU-sampled bounds, then device slice).
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.ops import hashing, sortkeys
from spark_rapids_tpu.ops.sortkeys import SortKeySpec


def hash_partition(batch: ColumnarBatch, key_ordinals: List[int],
                   dtypes: List[dt.DType], num_partitions: int
                   ) -> Tuple[ColumnarBatch, np.ndarray]:
    """Returns (rows sorted by partition id, int64 counts[num_partitions])."""
    h = hashing.hash_columns(batch, key_ordinals, dtypes)
    pid = _pmod(h, num_partitions)
    return _split_by_pid(batch, pid, num_partitions)


def round_robin_partition(batch: ColumnarBatch, num_partitions: int,
                          start: int = 0) -> Tuple[ColumnarBatch, np.ndarray]:
    pid = (jnp.arange(batch.capacity, dtype=jnp.int32) + start) \
        % num_partitions
    return _split_by_pid(batch, pid, num_partitions)


def single_partition(batch: ColumnarBatch) -> Tuple[ColumnarBatch, np.ndarray]:
    return batch, np.array([batch.realized_num_rows()], dtype=np.int64)


def range_partition(batch: ColumnarBatch, specs: List[SortKeySpec],
                    dtypes: List[dt.DType], bounds_values: np.ndarray,
                    num_partitions: int) -> Tuple[ColumnarBatch, np.ndarray]:
    """``bounds_values``: (num_partitions-1,) boundary *values* in the key's
    own domain (strings as str), sampled host-side once per exchange —
    exactly the reference's CPU-sampled-bounds design
    (GpuRangePartitioner.scala:42-95). Single-key ranges; the planner falls
    back for multi-key range partitioning."""
    from spark_rapids_tpu.columnar.column import StringColumn

    spec = specs[0]
    col = batch.columns[spec.ordinal]
    t = dtypes[spec.ordinal]
    last = num_partitions - 1
    if isinstance(col, StringColumn):
        # map string bounds into this batch's code space
        code_bounds = np.searchsorted(
            col.dictionary.astype(str) if len(col.dictionary)
            else np.array([], dtype=str),
            np.asarray(bounds_values, dtype=str), side="left")
        key = col.data
        bounds = jnp.asarray(code_bounds.astype(np.int32))
        if not spec.ascending:
            key = -key
            bounds = -jnp.asarray(code_bounds[::-1].astype(np.int32))
        pid = jnp.searchsorted(bounds, key, side="right").astype(jnp.int32)
    else:
        vals = np.asarray(bounds_values, dtype=t.np_dtype)
        key = col.data
        if t.is_floating:
            key = sortkeys.canonicalize_floats(key)
        if not spec.ascending:
            key = -key if (t.is_floating or t.is_numeric) else ~key
            vals = -vals[::-1] if (t.is_floating or t.is_numeric) \
                else np.bitwise_not(vals[::-1])
        pid = jnp.searchsorted(jnp.asarray(vals), key,
                               side="right").astype(jnp.int32)
        if t.is_floating:
            # NaN compares false everywhere; route it like "greatest"
            nan_pid = last if spec.ascending else 0
            pid = jnp.where(jnp.isnan(key), nan_pid, pid)
    if col.validity is not None:
        null_pid = 0 if spec.nulls_first else last
        pid = jnp.where(col.validity, pid, null_pid)
    return _split_by_pid(batch, pid, num_partitions)


def sample_range_bounds(batch: ColumnarBatch, spec: SortKeySpec,
                        dtypes: List[dt.DType], num_partitions: int
                        ) -> np.ndarray:
    """Host-side bounds sampling (GpuRangePartitioner analogue). Returns
    boundary values in the key's own domain."""
    col = batch.columns[spec.ordinal]
    n = batch.realized_num_rows()
    values, validity = col.to_numpy(n)
    if validity is not None:
        values = values[validity]
    values = np.sort(values)
    if len(values) == 0 or num_partitions <= 1:
        return np.array([], dtype=object)
    qs = [int(len(values) * (i + 1) / num_partitions)
          for i in range(num_partitions - 1)]
    picks = values[np.clip(qs, 0, len(values) - 1)]
    return picks if spec.ascending else picks[::-1]


def sample_range_bounds_multi(staged, specs: List[SortKeySpec],
                              dtypes: List[dt.DType],
                              num_partitions: int,
                              max_sample: int = 100_000) -> np.ndarray:
    """Bounds from ALL staged (spillable) batches of an exchange input:
    sample up to ``max_sample`` key values across batches, sort, take
    equi-quantile cut points (the reference samples the child RDD the
    same way through Spark's RangePartitioner)."""
    spec = specs[0]
    t = dtypes[spec.ordinal]
    per_batch = max(max_sample // max(len(staged), 1), 1)
    samples = []
    rng = np.random.default_rng(0x5EED)
    for sb in staged:
        with sb.acquired() as b:
            col = b.columns[spec.ordinal]
            n = b.realized_num_rows()
            values, validity = col.to_numpy(n)
            values = np.asarray(values[:n])
            if validity is not None:
                values = values[np.asarray(validity[:n], dtype=bool)]
            if len(values) > per_batch:
                values = rng.choice(values, per_batch, replace=False)
            samples.append(values)
    if t is dt.STRING:
        values = np.concatenate([s.astype(object) for s in samples]) \
            if samples else np.array([], dtype=object)
        values = np.array(sorted(values, key=str), dtype=object)
    else:
        values = np.concatenate(samples) if samples else \
            np.array([], dtype=t.np_dtype)
        values = np.sort(values)
        if t.is_floating:
            # NaN sorts last in np.sort; keep them out of the cut points
            values = values[~np.isnan(values)]
    if len(values) == 0 or num_partitions <= 1:
        return np.array([], dtype=object)
    qs = [int(len(values) * (i + 1) / num_partitions)
          for i in range(num_partitions - 1)]
    picks = values[np.clip(qs, 0, len(values) - 1)]
    return picks if spec.ascending else picks[::-1]


def _pmod(h: jax.Array, n: int) -> jax.Array:
    m = h % jnp.int64(n)
    return jnp.where(m < 0, m + n, m).astype(jnp.int32)


def _split_by_pid(batch: ColumnarBatch, pid: jax.Array, num_partitions: int
                  ) -> Tuple[ColumnarBatch, np.ndarray]:
    datas = [c.data for c in batch.columns]
    validities = [c.validity for c in batch.columns]
    out_d, out_v, counts = _partition_kernel(
        datas, validities, pid, batch.num_rows_device(), num_partitions)
    cols = [c._like(d, v) for c, d, v in zip(batch.columns, out_d, out_v)]
    out = ColumnarBatch(cols, batch.num_rows)
    return out, np.asarray(jax.device_get(counts))


@partial(jax.jit, static_argnames=("num_partitions",))
def _partition_kernel(datas, validities, pid, num_rows, num_partitions: int):
    capacity = pid.shape[0]
    live = jnp.arange(capacity, dtype=jnp.int32) < num_rows
    # padding rows to a virtual partition that sorts last
    pid_l = jnp.where(live, pid, num_partitions)
    order = jnp.argsort(pid_l, stable=True)
    counts = jax.ops.segment_sum(live.astype(jnp.int64), pid_l,
                                 num_segments=num_partitions + 1)[:-1]
    out_d = [jnp.take(d, order) for d in datas]
    out_v = [None if v is None else jnp.take(v, order) for v in validities]
    return out_d, out_v, counts


def slice_partitions(batch: ColumnarBatch, counts: np.ndarray
                     ) -> List[Optional[ColumnarBatch]]:
    """Materialize each contiguous partition as its own (re-bucketed) batch;
    empty partitions yield None (the caching writer skips them,
    RapidsShuffleInternalManager.scala:120)."""
    offsets = np.concatenate([[0], np.cumsum(counts)])
    out: List[Optional[ColumnarBatch]] = []
    for p in range(len(counts)):
        n = int(counts[p])
        if n == 0:
            out.append(None)
            continue
        out.append(batch.slice(int(offsets[p]), n))
    return out
