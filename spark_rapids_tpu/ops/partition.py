"""Device-side partitioning + contiguous split.

Replaces the cuDF ``Table.partition``/``contiguousSplit`` pair driven by the
reference's partitioners (GpuPartitioning.scala:44-70, GpuHashPartitioning,
GpuRoundRobinPartitioning, GpuRangePartitioning, GpuSinglePartitioning).

The kernel: compute a partition id per row, stable-sort rows by it (one XLA
sort), and compute per-partition counts with one segment_sum. The sorted
batch plus host-realized offsets is the analogue of a contiguous split —
each partition is a contiguous row range ready for slicing/serialization.
Range partitioning samples bounds host-side exactly like the reference
(GpuRangePartitioner.scala:42-95: CPU-sampled bounds, then device slice).
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.ops import hashing, sortkeys
from spark_rapids_tpu.ops.sortkeys import SortKeySpec


def hash_partition(batch: ColumnarBatch, key_ordinals: List[int],
                   dtypes: List[dt.DType], num_partitions: int
                   ) -> Tuple[ColumnarBatch, np.ndarray]:
    """Returns (rows sorted by partition id, int64 counts[num_partitions])."""
    h = hashing.hash_columns(batch, key_ordinals, dtypes)
    pid = _pmod(h, num_partitions)
    return _split_by_pid(batch, pid, num_partitions)


def round_robin_partition(batch: ColumnarBatch, num_partitions: int,
                          start: int = 0) -> Tuple[ColumnarBatch, np.ndarray]:
    pid = (jnp.arange(batch.capacity, dtype=jnp.int32) + start) \
        % num_partitions
    return _split_by_pid(batch, pid, num_partitions)


def single_partition(batch: ColumnarBatch) -> Tuple[ColumnarBatch, np.ndarray]:
    return batch, np.array([batch.realized_num_rows()], dtype=np.int64)


def range_partition(batch: ColumnarBatch, specs: List[SortKeySpec],
                    dtypes: List[dt.DType], bounds_values: np.ndarray,
                    num_partitions: int) -> Tuple[ColumnarBatch, np.ndarray]:
    """``bounds_values``: (num_partitions-1,) boundary *values* in the key's
    own domain (strings as str), sampled host-side once per exchange —
    exactly the reference's CPU-sampled-bounds design
    (GpuRangePartitioner.scala:42-95). Single-key ranges; the planner falls
    back for multi-key range partitioning."""
    from spark_rapids_tpu.columnar.column import StringColumn

    spec = specs[0]
    col = batch.columns[spec.ordinal]
    t = dtypes[spec.ordinal]
    last = num_partitions - 1
    if isinstance(col, StringColumn):
        # map string bounds into this batch's code space
        code_bounds = np.searchsorted(
            col.dictionary.astype(str) if len(col.dictionary)
            else np.array([], dtype=str),
            np.asarray(bounds_values, dtype=str), side="left")
        key = col.data
        bounds = jnp.asarray(code_bounds.astype(np.int32))
        if not spec.ascending:
            key = -key
            bounds = -jnp.asarray(code_bounds[::-1].astype(np.int32))
        pid = jnp.searchsorted(bounds, key, side="right").astype(jnp.int32)
    else:
        vals = np.asarray(bounds_values, dtype=t.np_dtype)
        key = col.data
        if t.is_floating:
            key = sortkeys.canonicalize_floats(key)
        if not spec.ascending:
            key = -key if (t.is_floating or t.is_numeric) else ~key
            vals = -vals[::-1] if (t.is_floating or t.is_numeric) \
                else np.bitwise_not(vals[::-1])
        pid = jnp.searchsorted(jnp.asarray(vals), key,
                               side="right").astype(jnp.int32)
        if t.is_floating:
            # NaN compares false everywhere; route it like "greatest"
            nan_pid = last if spec.ascending else 0
            pid = jnp.where(jnp.isnan(key), nan_pid, pid)
    if col.validity is not None:
        null_pid = 0 if spec.nulls_first else last
        pid = jnp.where(col.validity, pid, null_pid)
    return _split_by_pid(batch, pid, num_partitions)


def _col_cmp_vs_bound(col, t: dt.DType, spec: SortKeySpec, bval):
    """(gt, lt) boolean arrays: each row's key vs one scalar bound under
    the spec's ordering (direction + null ordering + NaN-greatest +
    -0.0 == 0.0). ``bval`` None = null bound."""
    from spark_rapids_tpu.columnar.column import StringColumn

    cap = col.capacity
    valid = col.validity if col.validity is not None else \
        jnp.ones(cap, dtype=bool)
    zeros = jnp.zeros(cap, dtype=bool)
    if bval is None:
        # null bound: non-null rows compare after it under NULLS FIRST,
        # before it under NULLS LAST; null rows are equal to it
        if spec.nulls_first:
            return valid, zeros
        return zeros, valid
    if isinstance(col, StringColumn):
        d = col.dictionary.astype(str) if len(col.dictionary) else \
            np.array([], dtype=str)
        p = int(np.searchsorted(d, str(bval), side="left"))
        bound_present = p < len(d) and d[p] == str(bval)
        code = col.data
        raw_gt = (code > p) | ((code == p) & (not bound_present))
        raw_lt = code < p
    else:
        x = col.data
        isnan = zeros
        if t.is_floating:
            x = sortkeys.canonicalize_floats(x)
            isnan = jnp.isnan(x)
        b = t.np_dtype.type(bval)
        if t.is_floating and np.isnan(b):
            raw_gt = zeros
            raw_lt = ~isnan  # NaN == NaN; everything else < NaN
        else:
            raw_gt = (x > b) | isnan  # NaN greatest
            raw_lt = (x < b) & ~isnan
    if not spec.ascending:
        raw_gt, raw_lt = raw_lt, raw_gt
    # null rows: before any non-null bound under NULLS FIRST, after
    # under NULLS LAST
    null_lt = jnp.where(valid, raw_lt, spec.nulls_first)
    null_gt = jnp.where(valid, raw_gt, not spec.nulls_first)
    return null_gt, null_lt


def range_partition_multi(batch: ColumnarBatch,
                          specs: List[SortKeySpec],
                          dtypes: List[dt.DType],
                          bounds: List[tuple], num_partitions: int
                          ) -> Tuple[ColumnarBatch, np.ndarray]:
    """Multi-key range partitioning: ``bounds`` is a sorted list of row
    tuples (one value-or-None per sort spec); each row's partition is
    the count of bounds <= its key tuple (lexicographic, the same
    searchsorted-right convention as the single-key path). Bounds is
    small (num_partitions - 1), so the comparison loop is
    O(num_partitions * num_keys) fused element-wise ops."""
    cap = batch.capacity
    pid = jnp.zeros(cap, dtype=jnp.int32)
    for bound in bounds:
        gt = jnp.zeros(cap, dtype=bool)
        eq = jnp.ones(cap, dtype=bool)
        for spec, bval in zip(specs, bound):
            g, l = _col_cmp_vs_bound(batch.columns[spec.ordinal],
                                     dtypes[spec.ordinal], spec, bval)
            gt = gt | (eq & g)
            eq = eq & ~(g | l)
        pid = pid + (gt | eq).astype(jnp.int32)
    return _split_by_pid(batch, pid, num_partitions)


def sample_range_bounds_rows(staged, specs: List[SortKeySpec],
                             dtypes: List[dt.DType],
                             num_partitions: int,
                             max_sample: int = 100_000) -> List[tuple]:
    """Multi-key bounds: sample whole key ROWS across the staged input,
    sort them host-side under the spec ordering, take equi-quantile rows
    as bound tuples (value or None per key)."""
    per_batch = max(max_sample // max(len(staged), 1), 1)
    rng = np.random.default_rng(0x5EED)
    col_samples = [[] for _ in specs]
    valid_samples = [[] for _ in specs]
    for sb in staged:
        with sb.acquired() as b:
            n = b.realized_num_rows()
            idx = np.arange(n) if n <= per_batch else \
                rng.choice(n, per_batch, replace=False)
            for j, spec in enumerate(specs):
                values, validity = b.columns[spec.ordinal].to_numpy(n)
                values = np.asarray(values)[:n][idx]
                v = np.ones(len(idx), dtype=bool) if validity is None \
                    else np.asarray(validity)[:n][idx]
                col_samples[j].append(values)
                valid_samples[j].append(v)
    cols = [np.concatenate(s) if s else np.array([])
            for s in col_samples]
    valids = [np.concatenate(s) if s else np.array([], dtype=bool)
              for s in valid_samples]
    total = len(cols[0]) if cols else 0
    if total == 0 or num_partitions <= 1:
        return []
    # host lexsort under spec semantics (mirrors cpu engine rank arrays)
    keys: List[np.ndarray] = []
    for j in reversed(range(len(specs))):
        spec = specs[j]
        t = dtypes[spec.ordinal]
        vals = cols[j]
        valid = valids[j]
        if t is dt.STRING:
            filled = np.array([x if x is not None else ""
                               for x in vals], dtype=object)
            _, codes = np.unique(filled, return_inverse=True)
            ranked = codes.astype(np.int64)
            nan_rank = np.zeros(total, dtype=np.int8)
        elif t.is_floating:
            f = vals.astype(np.float64)
            nan_rank = np.isnan(f).astype(np.int8)
            ranked = np.where(np.isnan(f), 0.0, f + 0.0)
        else:
            ranked = vals.astype(np.int64)
            nan_rank = np.zeros(total, dtype=np.int8)
        ranked = np.where(valid, ranked, ranked.dtype.type(0))
        nan_rank = np.where(valid, nan_rank, np.int8(0))
        null_rank = np.where(valid, 1, 0) if spec.nulls_first else \
            np.where(valid, 0, 1)
        if not spec.ascending:
            ranked = -ranked if t.is_floating else np.invert(ranked)
            nan_rank = -nan_rank
        keys.extend([ranked, nan_rank, null_rank])
    order = np.lexsort(keys)
    qs = [int(total * (i + 1) / num_partitions)
          for i in range(num_partitions - 1)]
    bounds = []
    for q in np.clip(qs, 0, total - 1):
        row = order[q]
        bound = []
        for j in range(len(specs)):
            if not valids[j][row]:
                bound.append(None)
            else:
                v = cols[j][row]
                bound.append(v if isinstance(v, str) or v is None
                             else v.item() if hasattr(v, "item") else v)
        bounds.append(tuple(bound))
    return bounds


def sample_range_bounds(batch: ColumnarBatch, spec: SortKeySpec,
                        dtypes: List[dt.DType], num_partitions: int
                        ) -> np.ndarray:
    """Host-side bounds sampling (GpuRangePartitioner analogue). Returns
    boundary values in the key's own domain."""
    col = batch.columns[spec.ordinal]
    n = batch.realized_num_rows()
    values, validity = col.to_numpy(n)
    if validity is not None:
        values = values[validity]
    values = np.sort(values)
    if len(values) == 0 or num_partitions <= 1:
        return np.array([], dtype=object)
    qs = [int(len(values) * (i + 1) / num_partitions)
          for i in range(num_partitions - 1)]
    picks = values[np.clip(qs, 0, len(values) - 1)]
    return picks if spec.ascending else picks[::-1]


def sample_range_bounds_multi(staged, specs: List[SortKeySpec],
                              dtypes: List[dt.DType],
                              num_partitions: int,
                              max_sample: int = 100_000) -> np.ndarray:
    """Bounds from ALL staged (spillable) batches of an exchange input:
    sample up to ``max_sample`` key values across batches, sort, take
    equi-quantile cut points (the reference samples the child RDD the
    same way through Spark's RangePartitioner)."""
    spec = specs[0]
    t = dtypes[spec.ordinal]
    per_batch = max(max_sample // max(len(staged), 1), 1)
    samples = []
    rng = np.random.default_rng(0x5EED)
    for sb in staged:
        with sb.acquired() as b:
            col = b.columns[spec.ordinal]
            n = b.realized_num_rows()
            values, validity = col.to_numpy(n)
            values = np.asarray(values[:n])
            if validity is not None:
                values = values[np.asarray(validity[:n], dtype=bool)]
            if len(values) > per_batch:
                values = rng.choice(values, per_batch, replace=False)
            samples.append(values)
    if t is dt.STRING:
        values = np.concatenate([s.astype(object) for s in samples]) \
            if samples else np.array([], dtype=object)
        values = np.array(sorted(values, key=str), dtype=object)
    else:
        values = np.concatenate(samples) if samples else \
            np.array([], dtype=t.np_dtype)
        values = np.sort(values)
        if t.is_floating:
            # NaN sorts last in np.sort; keep them out of the cut points
            values = values[~np.isnan(values)]
    if len(values) == 0 or num_partitions <= 1:
        return np.array([], dtype=object)
    qs = [int(len(values) * (i + 1) / num_partitions)
          for i in range(num_partitions - 1)]
    picks = values[np.clip(qs, 0, len(values) - 1)]
    return picks if spec.ascending else picks[::-1]


def _pmod(h: jax.Array, n: int) -> jax.Array:
    m = h % jnp.int64(n)
    return jnp.where(m < 0, m + n, m).astype(jnp.int32)


def _split_by_pid(batch: ColumnarBatch, pid: jax.Array, num_partitions: int
                  ) -> Tuple[ColumnarBatch, np.ndarray]:
    datas = [c.data for c in batch.columns]
    validities = [c.validity for c in batch.columns]
    out_d, out_v, counts = _partition_kernel(
        datas, validities, pid, batch.num_rows_device(), num_partitions)
    cols = [c._like(d, v) for c, d, v in zip(batch.columns, out_d, out_v)]
    out = ColumnarBatch(cols, batch.num_rows)
    return out, np.asarray(jax.device_get(counts))


@partial(jax.jit, static_argnames=("num_partitions",))
def _partition_kernel(datas, validities, pid, num_rows, num_partitions: int):
    """Contiguous-split by partition id: ONE variadic sort carries every
    column (no per-column permutation gathers), per-partition counts come
    from binary searches over the sorted ids (no segment_sum scatter)."""
    capacity = pid.shape[0]
    live = jnp.arange(capacity, dtype=jnp.int32) < num_rows
    # padding rows to a virtual partition that sorts last
    pid_l = jnp.where(live, pid, num_partitions)
    payloads = tuple(datas) + tuple(v for v in validities if v is not None)
    sorted_all = jax.lax.sort((pid_l,) + payloads, num_keys=1,
                              is_stable=True)
    pid_s = sorted_all[0]
    bounds = jnp.searchsorted(
        pid_s, jnp.arange(num_partitions + 1, dtype=pid_s.dtype))
    counts = (bounds[1:] - bounds[:-1]).astype(jnp.int64)
    rest = list(sorted_all[1:])
    out_d = rest[:len(datas)]
    vrest = rest[len(datas):]
    out_v = []
    for v in validities:
        out_v.append(vrest.pop(0) if v is not None else None)
    return out_d, out_v, counts


def slice_partitions(batch: ColumnarBatch, counts: np.ndarray
                     ) -> List[Optional[ColumnarBatch]]:
    """Materialize each contiguous partition as its own (re-bucketed) batch;
    empty partitions yield None (the caching writer skips them,
    RapidsShuffleInternalManager.scala:120)."""
    offsets = np.concatenate([[0], np.cumsum(counts)])
    out: List[Optional[ColumnarBatch]] = []
    for p in range(len(counts)):
        n = int(counts[p])
        if n == 0:
            out.append(None)
            continue
        out.append(batch.slice(int(offsets[p]), n))
    return out
