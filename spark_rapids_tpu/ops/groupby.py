"""Group-by aggregation: sort-based segmented reduction, gather-free.

cuDF gives the reference a hash-based ``groupBy.aggregate``
(aggregate.scala:810-890). TPUs have no device hash tables, but XLA's sort
is fast, so the TPU-native plan is:

  1. ONE stable variadic sort clusters equal keys (nulls group; NaN==NaN
     and -0.0==0.0 per Spark grouping semantics). When every key's value
     range is host-known (string dictionaries always are; numeric columns
     via footer/upload stats) all keys PACK into a single int32/int64 sort
     lane — measured 37 ms vs 52 ms for the multi-lane layout at 4M rows
     on a v5e,
  2. boundaries where any key lane differs from the previous row,
  3. per-aggregate ROW-SPACE lanes: prefix sums for sum/count (cumsum
     diffs at segment edges — exact for ints even across wrap), segmented
     scans for min/max, shifted lanes for first/last,
  4. ONE more stable sort keyed on ~boundary compacts every per-group
     output lane to a group prefix. This replaces the per-output
     ``jnp.take`` gathers of the round-1 kernel — a single 4M-row f64
     gather measured ~100 ms on a v5e while a whole extra sort pass is
     ~25-35 ms, and ALL outputs ride one pass,
  5. segment aggregates become roll/subtract arithmetic on the compacted
     lanes; the group count stays a device scalar (no host sync).

Float sums always use the per-segment scan (never global cumsum diffs):
a global prefix sum's diffs carry rounding error that scales with the
running prefix of OTHER groups — catastrophic cancellation when a huge
group precedes a tiny one — and Inf/NaN inputs poison every later
segment. The segmented scan confines both error and poison to the group
they belong to, matching the reference's per-group hash aggregation
error behavior (cuDF groupBy.aggregate). Integer sums and counts keep
exact cumsum diffs (wrap-exact for ints). Keeping one unconditional tail
(no lax.cond) also halves the compiled program vs a dual-branch design —
compile time over the tunnel is a first-class cost.

TPU scatter (segment_sum et al.) measured ~30x slower than cumsum at 4M
rows — no scatters appear anywhere on this path.

Both halves of the reference's CudfAggregate split (update-from-raw and
merge-of-partials, AggregateFunctions.scala) map onto the same kernel with
different op lists — partial results are just another batch.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, StringColumn

# Aggregate op names understood by the kernel. ``m2`` is the exact
# per-group centered second moment sum((x - group_mean)^2) — computed
# shifted by the group's first value so no large-magnitude cancellation
# occurs (variance/stddev building block; Spark's CentralMomentAgg /
# cuDF variance role). ``rterm`` is the Konig merge-correction term
# (sum x)^2 / n that lets m2 partials merge by plain addition.
AGG_OPS = ("sum", "min", "max", "count", "count_star", "first", "last",
           "any_valid", "sum_of_squares", "m2", "rterm")


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregation: op name + input ordinal (ignored for count_star).
    ``count`` counts valid rows of the input; ``first``/``last`` take the
    boundary row of each run (Spark first/last with ignoreNulls=False)."""

    op: str
    ordinal: int = -1


def quantize_range(lo: int, hi: int) -> Tuple[int, int]:
    """Widen a (lo, hi) key range to a power-of-two span on an aligned
    base. ``key_ranges`` is a STATIC jit argument — raw per-batch
    min/max would compile a fresh kernel per distinct pair (a
    compilation storm with per-row-group footer stats); quantized
    ranges bound the distinct signatures to O(log(range) * alignments).
    Correctness only needs a SUPERSET of the true range."""
    span = max(hi - lo + 1, 1)
    grid = 1 << (span - 1).bit_length()
    qlo = (lo // grid) * grid          # base on a span-scale grid
    need = hi - qlo + 1
    p = 1 << (need - 1).bit_length()   # pow2 span covering [qlo, hi]
    return (qlo, qlo + p - 1)


def key_range_of(col: Column, dtype: dt.DType) -> Optional[Tuple[int, int]]:
    """Host-known closed value range for packed-key grouping, if any
    (quantized — see quantize_range). String dictionaries and booleans
    always have one; numerics only when the column carries stats."""
    if isinstance(col, StringColumn):
        return quantize_range(0, max(len(col.dictionary) - 1, 0))
    if dtype is dt.BOOLEAN:
        return (0, 1)
    if dtype.is_integral or dtype in (dt.DATE, dt.TIMESTAMP):
        s = getattr(col, "stats", None)
        if s is not None:
            return quantize_range(int(s[0]), int(s[1]))
    return None


# libtpu AOT workaround (2026-07, v5e remote compile): the composite
# groupby program SEGFAULTS the tpu_compile_helper when it carries >= 7
# aggregate columns at capacities >= 32768 (the variadic sort and the
# segmented reductions each compile fine in isolation — only the fused
# module trips the compiler). Wide aggregate lists split into chunks of
# <= 6 below this shape boundary; chunks re-sort but are deterministic,
# so every chunk produces identical group order and the outputs zip.
# ``single_pass=True`` (the default, knob
# rapids.tpu.sql.groupby.singlePass.enabled) bypasses the chunk loop:
# on backends without the compiler defect one wide launch costs half
# the dispatches of two chunked ones, and the chunks' extra sorts were
# pure waste. The chunked path stays reachable (single_pass=False) as
# the v5e escape hatch.
_AOT_MAX_AGGS = 6
_AOT_CHUNK_MIN_CAP = 1 << 15


def groupby_aggregate(batch: ColumnarBatch, key_ordinals: List[int],
                      aggs: List[AggSpec], dtypes: List[dt.DType],
                      live_mask=None, dense_ok: bool = True,
                      single_pass: bool = True
                      ) -> Tuple[ColumnarBatch, List[dt.DType]]:
    """Returns (result batch [keys..., agg results...], result dtypes).
    ``live_mask`` fuses an upstream filter into the sort pass.
    ``dense_ok`` False forces the sort path even for tiny key spaces:
    grouping-set (ROLLUP/CUBE) aggregates need it, because the expand
    step places each level's copy of the same rows at different
    positions and the dense sweep's reduction tree is position-
    dependent — levels summing the SAME value set would differ in the
    last ulp, splitting rank()-over-sum ties the sort path (segment-
    relative scan order) keeps exact. ``single_pass`` False restores
    the chunked AOT-workaround loop for wide aggregate lists."""
    cols = [(c.data, c.validity) for c in batch.columns]
    key_ranges = tuple(key_range_of(batch.columns[o], dtypes[o])
                       for o in key_ordinals)
    key_has_v = tuple(batch.columns[o].validity is not None
                      for o in key_ordinals)
    # dense_ok=False only needs to suppress ORDER-SENSITIVE float
    # reductions; integer sums/counts/min/max are exact regardless of
    # reduction-tree shape, so a grouping-set aggregate over those
    # keeps the dense path
    if not dense_ok and not any(
            spec.op in ("sum_of_squares", "m2", "rterm") or
            (spec.op == "sum" and spec.ordinal >= 0 and
             dtypes[spec.ordinal].is_floating)
            for spec in aggs):
        dense_ok = True
    # the dense path never builds the fused sort module the AOT
    # segfault workaround guards against — wide agg lists stay whole
    will_dense = dense_ok and _dense_layout(
        list(dtypes), key_ordinals, key_ranges, key_has_v) is not None
    if not single_pass and len(aggs) > _AOT_MAX_AGGS and \
            not will_dense and batch.capacity >= _AOT_CHUNK_MIN_CAP:
        agg_d, agg_v = [], []
        key_d = key_v = num_groups = None
        for lo in range(0, len(aggs), _AOT_MAX_AGGS):
            chunk = tuple(aggs[lo:lo + _AOT_MAX_AGGS])
            out = _groupby(cols, tuple(dtypes), tuple(key_ordinals),
                           chunk, batch.num_rows_device(),
                           live_mask=live_mask, key_ranges=key_ranges,
                           dense_ok=dense_ok)
            (ck_d, ck_v), (ca_d, ca_v), ng = out
            if key_d is None:
                key_d, key_v, num_groups = ck_d, ck_v, ng
            agg_d.extend(ca_d)
            agg_v.extend(ca_v)
    else:
        out = _groupby(cols, tuple(dtypes), tuple(key_ordinals),
                       tuple(aggs), batch.num_rows_device(),
                       live_mask=live_mask, key_ranges=key_ranges,
                       dense_ok=dense_ok)
        (key_d, key_v), (agg_d, agg_v), num_groups = out
    out_cols: List[Column] = []
    out_types: List[dt.DType] = []
    for i, ord_ in enumerate(key_ordinals):
        src = batch.columns[ord_]
        out_cols.append(src._like(key_d[i], key_v[i]))
        out_types.append(dtypes[ord_])
    for i, spec in enumerate(aggs):
        rtype = agg_result_dtype(spec, dtypes)
        if rtype is dt.STRING and spec.ordinal >= 0 and \
                isinstance(batch.columns[spec.ordinal], StringColumn):
            # preserve the dictionary: codes order == string order, so
            # min/max/first/last on codes are min/max/first/last on strings
            out_cols.append(
                batch.columns[spec.ordinal]._like(agg_d[i], agg_v[i]))
        else:
            out_cols.append(Column(rtype, agg_d[i], agg_v[i]))
        out_types.append(rtype)
    return ColumnarBatch(out_cols, num_groups), out_types


def agg_result_dtype(spec: AggSpec, dtypes: List[dt.DType]) -> dt.DType:
    if spec.op in ("count", "count_star"):
        return dt.INT64
    in_t = dtypes[spec.ordinal]
    if spec.op == "sum":
        # Spark: sum over integrals -> bigint, over fractionals -> double
        return dt.INT64 if in_t.is_integral or in_t is dt.BOOLEAN \
            else dt.FLOAT64
    if spec.op == "sum_of_squares":
        return dt.FLOAT64
    return in_t  # min/max/first/last/any_valid preserve type


# ---------------------------------------------------------------------------
# sort-lane construction
# ---------------------------------------------------------------------------


def _pack_plan(dtypes, key_ordinals, key_ranges):
    """Static decision: MAY every key pack into one integer lane?
    Returns the validated per-key ranges (all present, all discrete
    types) or None. The caller derives cards/strides/lane width from
    them — and still falls back to the generic lanes if the cardinality
    product overflows int64."""
    if key_ranges is None or len(key_ranges) != len(key_ordinals):
        return None
    if not key_ordinals:
        return None
    for r, o in zip(key_ranges, key_ordinals):
        if r is None:
            return None
        if not (dtypes[o].is_integral or dtypes[o] in
                (dt.DATE, dt.TIMESTAMP, dt.BOOLEAN, dt.STRING)):
            return None
    return key_ranges


# ---------------------------------------------------------------------------
# dense path: tiny host-known key spaces need no sort at all
# ---------------------------------------------------------------------------

# Above this slot count the masked-reduction sweep (total x capacity work
# per aggregate lane) loses to the sort kernel; below it the sweep wins
# by a wide margin — it deletes BOTH variadic sorts and every cumsum.
_DENSE_MAX_GROUPS = 128


def _dense_layout(dtypes, key_ordinals, key_ranges, key_has_v):
    """Static layout for the sort-free dense groupby: validated ranges
    plus cards/strides/total when every key packs into at most
    ``_DENSE_MAX_GROUPS`` slots (the TPC-H q1 returnflag x linestatus
    shape). None when the packed space is too large or unpackable."""
    ranges = _pack_plan(dtypes, key_ordinals, key_ranges)
    if ranges is None:
        return None
    cards = []
    for (lo, hi), hv in zip(ranges, key_has_v):
        cards.append((hi - lo + 1) + (1 if hv else 0))
    total = 1
    for card in cards:
        total *= max(card, 1)
    if total > _DENSE_MAX_GROUPS:
        return None
    strides = []
    s = 1
    for card in reversed(cards):
        strides.append(s)
        s *= max(card, 1)
    strides.reverse()
    return ranges, tuple(cards), tuple(strides), total


def _dense_groupby(cols, dtypes, key_ordinals, aggs, live, layout):
    """Sort-free groupby for tiny host-known key spaces: rows map to a
    packed slot code, and each aggregate is ONE masked reduction over a
    [slots, capacity] broadcast compare that XLA fuses into a single
    sweep — no variadic sort, no cumsum, and no AOT-segfault chunking
    (the >= 7-agg boundary above applies to the fused sort module, which
    this path never builds). The slot axis compacts with an argsort over
    <= 128 elements. Matches the semantics of the sort path exactly:
    same null-first slot encoding, same validity rules per op.

    The reference reaches the same shapes through cuDF's hash groupby
    (aggregate.scala:810-890); a TPU has no device hash table, but for a
    known-tiny key space the dense sweep is the natural MXU/VPU-friendly
    replacement — pure vectorized compare+reduce, no data movement."""
    ranges, cards, strides, total = layout
    capacity = cols[0][0].shape[0]
    iota = jnp.arange(capacity, dtype=jnp.int32)

    pack = jnp.zeros(capacity, dtype=jnp.int32)
    for (lo, hi), strd, o in zip(ranges, strides, key_ordinals):
        d, v = cols[o]
        dd = d.astype(jnp.int32) if dtypes[o] is dt.BOOLEAN else d
        code = (dd - jnp.asarray(lo, dd.dtype)).astype(jnp.int32)
        if v is not None:
            code = jnp.where(v, code + 1, jnp.zeros((), jnp.int32))
        pack = pack + code * jnp.int32(strd)
    codes = jnp.where(live, pack, jnp.int32(total))  # dead -> sentinel

    slots = jnp.arange(total, dtype=jnp.int32)
    eq = codes[None, :] == slots[:, None]            # [total, capacity]
    sizes = jnp.sum(eq, axis=1).astype(jnp.int32)
    exists = sizes > 0

    def rowmask(o):
        v = cols[o][1]
        return live if v is None else (v & live)

    def nvalid_of(o):
        if cols[o][1] is None:
            return sizes
        return jnp.sum(eq & cols[o][1][None, :], axis=1).astype(jnp.int32)

    def first_idx(mask):
        return jnp.min(jnp.where(mask, iota[None, :], capacity), axis=1)

    agg_d, agg_v = [], []
    for spec in aggs:
        if spec.op == "count_star":
            agg_d.append(sizes.astype(jnp.int64))
            agg_v.append(exists)
            continue
        o = spec.ordinal
        d, v = cols[o]
        if spec.op == "count":
            agg_d.append(nvalid_of(o).astype(jnp.int64))
            agg_v.append(exists)
        elif spec.op == "sum" and not dtypes[o].is_floating:
            x = jnp.where(rowmask(o), d.astype(jnp.int64),
                          jnp.zeros((), jnp.int64))
            agg_d.append(jnp.sum(jnp.where(eq, x[None, :],
                                           jnp.zeros((), jnp.int64)),
                                 axis=1))
            agg_v.append(nvalid_of(o) > 0)
        elif spec.op in ("sum", "sum_of_squares"):
            x = d.astype(jnp.float64)
            if spec.op == "sum_of_squares":
                x = x * x
            xm = jnp.where(rowmask(o), x, 0.0)
            agg_d.append(jnp.sum(jnp.where(eq, xm[None, :], 0.0), axis=1))
            agg_v.append(nvalid_of(o) > 0)
        elif spec.op == "rterm":
            xm = jnp.where(rowmask(o), d.astype(jnp.float64), 0.0)
            s = jnp.sum(jnp.where(eq, xm[None, :], 0.0), axis=1)
            nf = jnp.maximum(nvalid_of(o), 1).astype(jnp.float64)
            agg_d.append((s * s) / nf)
            agg_v.append(nvalid_of(o) > 0)
        elif spec.op == "m2":
            x = d.astype(jnp.float64)
            contrib = rowmask(o)
            m = eq & contrib[None, :]
            fi = jnp.clip(first_idx(m), 0, capacity - 1)
            xf_row = jnp.take(jnp.take(x, fi),
                              jnp.clip(codes, 0, total - 1))
            dd = jnp.where(contrib, x - xf_row, 0.0)
            sd = jnp.sum(jnp.where(eq, dd[None, :], 0.0), axis=1)
            sd2 = jnp.sum(jnp.where(eq, (dd * dd)[None, :], 0.0), axis=1)
            n = nvalid_of(o)
            nf = jnp.maximum(n, 1).astype(jnp.float64)
            agg_d.append(jnp.maximum(sd2 - (sd * sd) / nf, 0.0))
            agg_v.append(n > 0)
        elif spec.op in ("min", "max"):
            in_t = dtypes[o]
            dd = d.astype(jnp.int8) if in_t is dt.BOOLEAN else d
            kd = dd.dtype
            if in_t.is_floating:
                big = jnp.asarray(jnp.inf, kd)
                small = jnp.asarray(-jnp.inf, kd)
            elif in_t is dt.BOOLEAN:
                big, small = jnp.asarray(1, kd), jnp.asarray(0, kd)
            else:
                big = jnp.asarray(jnp.iinfo(kd).max, kd)
                small = jnp.asarray(jnp.iinfo(kd).min, kd)
            fill = big if spec.op == "min" else small
            xm = jnp.where(rowmask(o), dd, fill)
            red = jnp.min if spec.op == "min" else jnp.max
            vals = red(jnp.where(eq, xm[None, :], fill), axis=1)
            if in_t is dt.BOOLEAN:
                vals = vals.astype(jnp.bool_)
            agg_d.append(vals)
            agg_v.append(nvalid_of(o) > 0)
        elif spec.op in ("first", "any_valid"):
            m = eq & rowmask(o)[None, :] if spec.op == "any_valid" else eq
            fi = jnp.clip(first_idx(m), 0, capacity - 1)
            agg_d.append(jnp.take(d, fi))
            if spec.op == "any_valid":
                agg_v.append(nvalid_of(o) > 0)
            else:
                agg_v.append(exists if v is None
                             else (jnp.take(v, fi) & exists))
        elif spec.op == "last":
            li = jnp.clip(jnp.max(jnp.where(eq, iota[None, :], -1),
                                  axis=1), 0, capacity - 1)
            agg_d.append(jnp.take(d, li))
            agg_v.append(exists if v is None
                         else (jnp.take(v, li) & exists))
        else:
            raise ValueError(f"unknown aggregate op {spec.op}")

    key_d, key_v_arr = [], []
    for ki, o in enumerate(key_ordinals):
        card = max(cards[ki], 1)
        code = (slots // jnp.int32(strides[ki])) % jnp.int32(card)
        wide = jnp.int32 if dtypes[o] is dt.BOOLEAN else cols[o][0].dtype
        if cols[o][1] is not None:
            kv = (code > 0) & exists
            kd = (code - 1).astype(wide) + jnp.asarray(ranges[ki][0], wide)
        else:
            kv = exists
            kd = code.astype(wide) + jnp.asarray(ranges[ki][0], wide)
        if dtypes[o] is dt.BOOLEAN:
            kd = kd.astype(jnp.bool_)
        key_d.append(kd)
        key_v_arr.append(kv)

    order = jnp.argsort(~exists, stable=True)
    num_groups = jnp.sum(exists).astype(jnp.int32)

    def take(x):
        return jnp.take(x, order)

    key_has_v = tuple(cols[o][1] is not None for o in key_ordinals)
    key_v = [take(key_v_arr[i]) if key_has_v[i] else None
             for i in range(len(key_ordinals))]
    agg_vo = [None if spec.op in ("count", "count_star")
              else take(agg_v[i]) for i, spec in enumerate(aggs)]
    return ([take(x) for x in key_d], key_v), \
        ([take(x) for x in agg_d], agg_vo), num_groups


def _equality_lanes(d, v, dtype):
    """Sort-key lanes for one UNPACKED key column, every lane directly
    equality-comparable row-to-row (floats contribute a NaN-zeroed value
    plus an isnan flag so NaN==NaN without bitcasts)."""
    valid = v if v is not None else None
    if dtype.is_floating:
        # NOT d + 0: XLA folds add-zero inside fused programs, keeping
        # -0.0's sign (see sortkeys.canonicalize_floats)
        zero = jnp.zeros((), d.dtype)
        x = jnp.where(d == zero, zero, d)
        isn = jnp.isnan(x)
        if valid is not None:
            isn = isn & valid
            x = jnp.where(valid, x, jnp.zeros((), x.dtype))
        x = jnp.where(isn, jnp.zeros((), x.dtype), x)
        return [x, isn]
    k = d.astype(jnp.int8) if dtype is dt.BOOLEAN else d
    if valid is not None:
        k = jnp.where(valid, k, jnp.zeros((), k.dtype))
    return [k]


def _shift1(x):
    """x shifted down one row: out[i] = x[i-1], out[0] = 0."""
    z = jnp.zeros((1,), x.dtype)
    return jnp.concatenate([z, x[:-1]])


def _cumsum_isolated(x):
    """cumsum fenced from fusion: the TPU reduce-window lowering of a
    wide (i64/f64 = 32-bit pair) prefix sum exceeds the 16 MiB scoped
    VMEM limit when neighbouring ops fuse into it at multi-million-row
    shapes. Standalone it compiles and runs fine (~30-44 ms at 4M rows on
    a v5e), so barrier it off instead of lowering the whole program's
    fusion level."""
    x = jax.lax.optimization_barrier(x)
    return jax.lax.optimization_barrier(jnp.cumsum(x))


@partial(jax.jit, static_argnames=("dtypes", "key_ordinals", "aggs",
                                   "key_ranges", "dense_ok"))
def _groupby(cols, dtypes, key_ordinals, aggs, num_rows,
             live_mask=None, key_ranges=None, dense_ok=True):
    """``live_mask``: optional fused filter — masked-out rows are dead
    (they sort last with the padding and never reach a segment)."""
    capacity = cols[0][0].shape[0]
    iota = jnp.arange(capacity, dtype=jnp.int32)
    live = iota < num_rows
    if live_mask is not None:
        live = live & live_mask
        num_rows = jnp.sum(live).astype(jnp.int32)

    key_has_v = tuple(cols[o][1] is not None for o in key_ordinals)
    dense = _dense_layout(dtypes, key_ordinals, key_ranges, key_has_v) \
        if dense_ok else None
    if dense is not None:
        return _dense_groupby(cols, dtypes, key_ordinals, aggs, live,
                              dense)

    ranges = _pack_plan(dtypes, key_ordinals, key_ranges)

    # ---- 1. sort-key lanes ------------------------------------------------
    packed = None
    key_lane_slices = []  # per key: (start, count) into sort_keys
    if ranges is not None:
        cards = []
        for (lo, hi), has_v in zip(ranges, key_has_v):
            cards.append((hi - lo + 1) + (1 if has_v else 0))
        total = 1
        for c in cards:
            total *= max(c, 1)
        if total + 1 <= 0x7FFFFFFF:
            lane_dt = jnp.int32
        elif total + 1 <= (1 << 62):
            lane_dt = jnp.int64
        else:
            ranges = None
    if ranges is not None:
        pack = jnp.zeros(capacity, dtype=lane_dt)
        strides = []
        stride = 1
        for card in reversed(cards):
            strides.append(stride)
            stride *= max(card, 1)
        strides.reverse()
        for (lo, hi), has_v, strd, o in zip(ranges, key_has_v, strides,
                                            key_ordinals):
            d, v = cols[o]
            # subtract the range base BEFORE narrowing: int64 keys with a
            # small span but large magnitude must not wrap
            dd = d.astype(jnp.int32) if dtypes[o] is dt.BOOLEAN else d
            code = (dd - jnp.asarray(lo, dd.dtype)).astype(lane_dt)
            if has_v:
                code = jnp.where(v, code + 1, jnp.zeros((), lane_dt))
            pack = pack + code * lane_dt(strd)
        sentinel = lane_dt(total)
        packed = jnp.where(live, pack, sentinel)
        sort_keys = [packed]
    else:
        rank = (~live).astype(jnp.int32)
        for o, has_v in zip(key_ordinals, key_has_v):
            if has_v:
                # valid rows rank 1: nulls group FIRST (matching the
                # packed path's reserved 0 slot and Spark's ASC default)
                rank = (rank << 1) | cols[o][1].astype(jnp.int32)
        sort_keys = [rank]
        for o in key_ordinals:
            d, v = cols[o]
            lanes = _equality_lanes(d, v, dtypes[o])
            key_lane_slices.append((len(sort_keys), len(lanes)))
            sort_keys.extend(lanes)

    # ---- 2. payload lanes: agg-input columns not derivable from keys ------
    key_set = set(key_ordinals)
    needed = []
    for spec in aggs:
        if spec.ordinal >= 0 and spec.ordinal not in key_set and \
                spec.ordinal not in needed:
            needed.append(spec.ordinal)
    payloads = []
    for o in needed:
        d, v = cols[o]
        payloads.append(d)
        if v is not None:
            payloads.append(v)

    out = jax.lax.sort(tuple(sort_keys) + tuple(payloads),
                       num_keys=len(sort_keys), is_stable=True)
    s_keys = out[:len(sort_keys)]
    rest = list(out[len(sort_keys):])
    sorted_cols = {}
    for o in needed:
        d = rest.pop(0)
        v = rest.pop(0) if cols[o][1] is not None else None
        sorted_cols[o] = (d, v)

    # reconstruct key columns (data, validity) in sorted order from the
    # sort lanes themselves — key columns never ride as payloads
    if ranges is not None:
        sp = s_keys[0]
        for ki, o in enumerate(key_ordinals):
            code = (sp // lane_dt(strides[ki])) % lane_dt(
                max(cards[ki], 1))
            # widen to the column dtype BEFORE adding the range base:
            # int64/TIMESTAMP keys with magnitude above the lane dtype's
            # range (small span, large base) must not wrap in lane_dt
            wide = jnp.int32 if dtypes[o] is dt.BOOLEAN else \
                cols[o][0].dtype
            if key_has_v[ki]:
                kv = code > 0
                kd = (code - 1).astype(wide) + jnp.asarray(
                    ranges[ki][0], wide)
            else:
                kv = None
                kd = code.astype(wide) + jnp.asarray(ranges[ki][0], wide)
            if dtypes[o] is dt.BOOLEAN:
                kd = kd.astype(jnp.bool_)
            sorted_cols[o] = (kd, kv)
    else:
        s_rank = s_keys[0]
        nbits = sum(1 for h in key_has_v if h)
        bit = nbits
        for ki, o in enumerate(key_ordinals):
            start, cnt = key_lane_slices[ki]
            if dtypes[o].is_floating:
                val, isn = s_keys[start], s_keys[start + 1]
                kd = jnp.where(isn, jnp.asarray(jnp.nan, val.dtype), val)
            else:
                kd = s_keys[start]
                if dtypes[o] is dt.BOOLEAN:
                    kd = kd.astype(jnp.bool_)
            if key_has_v[ki]:
                bit -= 1
                kv = ((s_rank >> bit) & 1) == 1
            else:
                kv = None
            sorted_cols[o] = (kd, kv)

    live_sorted = iota < num_rows

    # ---- 3. boundaries ----------------------------------------------------
    def lane_diff(lane):
        return jnp.concatenate(
            [jnp.ones(1, dtype=bool), lane[1:] != lane[:-1]])

    boundary = jnp.zeros(capacity, dtype=bool).at[0].set(True)
    if ranges is not None:
        boundary = boundary | lane_diff(s_keys[0])
    else:
        for lane in s_keys:
            boundary = boundary | lane_diff(lane)
    boundary = boundary & live_sorted
    num_groups = jnp.sum(boundary).astype(jnp.int32)

    # ---- 4. aggregate tail ------------------------------------------------
    key_d, key_v_arr, agg_d, agg_v_arr = _segments_tail(
        sorted_cols, dtypes, key_ordinals, aggs, boundary,
        live_sorted, num_rows, num_groups, capacity)

    key_v = [key_v_arr[i] if key_has_v[i] else None
             for i in range(len(key_ordinals))]
    # counts are never null (reference: CudfCount merges to 0, not null)
    agg_v = [None if spec.op in ("count", "count_star") else agg_v_arr[i]
             for i, spec in enumerate(aggs)]
    return (list(key_d), key_v), (list(agg_d), agg_v), num_groups


def _segments_tail(sorted_cols, dtypes, key_ordinals, aggs, boundary,
                   live_sorted, num_rows, num_groups, capacity):
    """Row-space lanes -> ONE compaction sort -> group-space arithmetic.
    Returns (key_d, key_v_arrays, agg_d, agg_v_arrays) with validity as
    plain bool arrays (the caller maps Nones back)."""
    iota = jnp.arange(capacity, dtype=jnp.int32)

    # ---- row-space lanes per aggregate
    # each entry: (kind, lanes...) consumed positionally after compaction
    lane_specs = []   # static description
    lanes = []        # arrays riding the compaction sort

    def add_lane(x):
        lanes.append(x)
        return len(lanes) - 1

    def contrib_of(o):
        d, v = sorted_cols[o]
        return live_sorted if v is None else (v & live_sorted)

    count_lane_of = {}

    def ensure_count_lane(o):
        """Segment valid-count via i32 cumsum (exact: counts <= capacity
        < 2^31). Returns (lane index, grand total) — or (None, None) when
        the column has no validity: live rows are a prefix after the sort,
        so the valid count IS the segment size (no cumsum, no lane)."""
        if sorted_cols[o][1] is None:
            return (None, None)
        if o not in count_lane_of:
            cs = _cumsum_isolated(contrib_of(o).astype(jnp.int32))
            count_lane_of[o] = (add_lane(_shift1(cs)), cs[-1])
        return count_lane_of[o]

    for si, spec in enumerate(aggs):
        if spec.op == "count_star":
            lane_specs.append(("sizes",))
            continue
        o = spec.ordinal
        d, v = sorted_cols[o]
        contrib = contrib_of(o)
        valid_arr = v if v is not None else live_sorted
        if spec.op == "count":
            idx, tot = ensure_count_lane(o)
            if idx is None:
                lane_specs.append(("sizes",))
            else:
                lane_specs.append(("count", idx, tot))
        elif spec.op == "sum" and not dtypes[o].is_floating:
            x = jnp.where(contrib, d.astype(jnp.int64),
                          jnp.zeros((), jnp.int64))
            cs = _cumsum_isolated(x)
            idx = add_lane(_shift1(cs))
            cidx, ctot = ensure_count_lane(o)
            lane_specs.append(("isum", idx, cs[-1], cidx, ctot))
        elif spec.op in ("sum", "sum_of_squares"):
            # per-segment inclusive scan, never global cumsum diffs:
            # confines rounding error AND Inf/NaN poison to each group
            # (a global prefix's diffs carry error scaling with the
            # running prefix of OTHER groups)
            x = d.astype(jnp.float64)
            if spec.op == "sum_of_squares":
                x = x * x
            xm = jnp.where(contrib, x, 0.0)
            scan = _seg_scan(xm, boundary, jnp.add)
            sidx = add_lane(_shift1(scan))
            last = jax.lax.dynamic_index_in_dim(
                scan, jnp.maximum(num_rows - 1, 0), keepdims=False)
            cidx, ctot = ensure_count_lane(o)
            lane_specs.append(("scan", sidx, last, cidx, ctot, False))
        elif spec.op == "rterm":
            # (sum x)^2 / n per group: rides the same xm seg scan shape
            # as a float sum; squared/divided in group space
            x = d.astype(jnp.float64)
            xm = jnp.where(contrib, x, 0.0)
            scan = _seg_scan(xm, boundary, jnp.add)
            sidx = add_lane(_shift1(scan))
            last = jax.lax.dynamic_index_in_dim(
                scan, jnp.maximum(num_rows - 1, 0), keepdims=False)
            cidx, ctot = ensure_count_lane(o)
            lane_specs.append(("rterm", sidx, last, cidx, ctot))
        elif spec.op == "m2":
            # exact per-group centered second moment: shift every row by
            # the group's FIRST valid value (a segmented first-valid
            # scan), then m2 = sum(d^2) - (sum d)^2 / n — algebraically
            # identical to sum((x - mean)^2) and free of the
            # large-magnitude cancellation of the raw sum-of-squares
            # formula (r3 advisor finding)
            x = d.astype(jnp.float64)
            xf = _seg_first_valid(x, contrib, boundary)
            dd = jnp.where(contrib, x - xf, 0.0)
            scan_d = _seg_scan(dd, boundary, jnp.add)
            scan_d2 = _seg_scan(dd * dd, boundary, jnp.add)
            sidx_d = add_lane(_shift1(scan_d))
            last_d = jax.lax.dynamic_index_in_dim(
                scan_d, jnp.maximum(num_rows - 1, 0), keepdims=False)
            sidx_d2 = add_lane(_shift1(scan_d2))
            last_d2 = jax.lax.dynamic_index_in_dim(
                scan_d2, jnp.maximum(num_rows - 1, 0), keepdims=False)
            cidx, ctot = ensure_count_lane(o)
            lane_specs.append(("m2", sidx_d, last_d, sidx_d2, last_d2,
                               cidx, ctot))
        elif spec.op in ("min", "max"):
            in_t = dtypes[o]
            kd = d.dtype
            dd = d
            if in_t is dt.BOOLEAN:
                dd = d.astype(jnp.int8)
                kd = jnp.int8
            if in_t.is_floating:
                big = jnp.asarray(jnp.inf, kd)
                small = jnp.asarray(-jnp.inf, kd)
            elif in_t is dt.BOOLEAN:
                big, small = jnp.asarray(1, kd), jnp.asarray(0, kd)
            else:
                big = jnp.asarray(jnp.iinfo(kd).max, kd)
                small = jnp.asarray(jnp.iinfo(kd).min, kd)
            if spec.op == "min":
                x = jnp.where(contrib, dd, big)
                scan = _seg_scan(x, boundary, jnp.minimum)
            else:
                x = jnp.where(contrib, dd, small)
                scan = _seg_scan(x, boundary, jnp.maximum)
            sidx = add_lane(_shift1(scan))
            last = jax.lax.dynamic_index_in_dim(
                scan, jnp.maximum(num_rows - 1, 0), keepdims=False)
            cidx, ctot = ensure_count_lane(o)
            lane_specs.append(("scan", sidx, last, cidx, ctot,
                               dtypes[o] is dt.BOOLEAN))
        elif spec.op == "first":
            didx = add_lane(d)
            vidx = add_lane(valid_arr)
            lane_specs.append(("first", didx, vidx, "first", None, None))
        elif spec.op == "any_valid":
            # first VALID value per group (Spark first(ignoreNulls=true);
            # the CPU oracle takes rows[valid] — cpu/engine.py:384-389).
            # The boundary row's raw value is NOT it when that row is
            # null, so ride the segmented first-valid scan and read it at
            # each segment's LAST row (the scan-decode shape min/max use)
            fv = _seg_first_valid(d, contrib, boundary)
            sidx = add_lane(_shift1(fv))
            last = jax.lax.dynamic_index_in_dim(
                fv, jnp.maximum(num_rows - 1, 0), keepdims=False)
            cidx, ctot = ensure_count_lane(o)
            lane_specs.append(("anyv", sidx, last, cidx, ctot))
        elif spec.op == "last":
            didx = add_lane(_shift1(d))
            vidx = add_lane(_shift1(valid_arr))
            dlast = jax.lax.dynamic_index_in_dim(
                d, jnp.maximum(num_rows - 1, 0), keepdims=False)
            vlast = jax.lax.dynamic_index_in_dim(
                valid_arr, jnp.maximum(num_rows - 1, 0), keepdims=False)
            lane_specs.append(("last", didx, vidx, dlast, vlast))
        else:
            raise ValueError(f"unknown aggregate op {spec.op}")

    # key output lanes
    key_lane_idx = []
    for o in key_ordinals:
        d, v = sorted_cols[o]
        di = add_lane(d)
        vi = add_lane(v) if v is not None else None
        key_lane_idx.append((di, vi))

    # ---- ONE compaction sort: boundary rows to a group prefix
    packed = jax.lax.sort(
        ((~boundary),) + (iota,) + tuple(lanes), num_keys=1,
        is_stable=True)
    first_idx = packed[1]
    c = list(packed[2:])  # compacted lanes, group g at row g

    giota = iota
    glive = giota < num_groups
    is_last_group = giota == (num_groups - 1)

    def roll_next(x, last_value):
        """x[g+1] for g < ng-1; ``last_value`` for the final group."""
        nxt = jnp.roll(x, -1)
        return jnp.where(is_last_group,
                         jnp.asarray(last_value, x.dtype), nxt)

    next_first = roll_next(first_idx, num_rows)
    seg_sizes = jnp.where(glive, next_first - first_idx, 0)

    def nvalid_of(cidx, ctot):
        """Per-group valid count: cumsum-lane diff, or the segment size
        when the input had no validity lane."""
        if cidx is None:
            return seg_sizes
        clo = c[cidx]
        return roll_next(clo, ctot) - clo

    # ---- group-space decode
    agg_d, agg_v = [], []
    for ls in lane_specs:
        kind = ls[0]
        if kind == "sizes":
            agg_d.append(seg_sizes.astype(jnp.int64))
            agg_v.append(glive)
            continue
        if kind == "count":
            _, idx, tot = ls
            lo = c[idx]
            n = roll_next(lo, tot) - lo
            agg_d.append(n.astype(jnp.int64))
            agg_v.append(glive)
            continue
        if kind == "isum":
            _, idx, tot, cidx, ctot = ls
            lo = c[idx]
            s = roll_next(lo, tot) - lo
            nvalid = nvalid_of(cidx, ctot)
            agg_d.append(s)
            agg_v.append(glive & (nvalid > 0))
            continue
        if kind == "scan":
            _, sidx, last, cidx, ctot, was_bool = ls
            vals = roll_next(c[sidx], last)
            if was_bool:
                vals = vals.astype(jnp.bool_)
            nvalid = nvalid_of(cidx, ctot)
            agg_d.append(vals)
            agg_v.append(glive & (nvalid > 0))
            continue
        if kind == "rterm":
            _, sidx, last, cidx, ctot = ls
            s = roll_next(c[sidx], last)
            nvalid = nvalid_of(cidx, ctot)
            nf = jnp.maximum(nvalid, 1).astype(jnp.float64)
            agg_d.append((s * s) / nf)
            agg_v.append(glive & (nvalid > 0))
            continue
        if kind == "m2":
            _, sidx_d, last_d, sidx_d2, last_d2, cidx, ctot = ls
            sd = roll_next(c[sidx_d], last_d)
            sd2 = roll_next(c[sidx_d2], last_d2)
            nvalid = nvalid_of(cidx, ctot)
            nf = jnp.maximum(nvalid, 1).astype(jnp.float64)
            m2 = sd2 - (sd * sd) / nf
            agg_d.append(jnp.maximum(m2, 0.0))
            agg_v.append(glive & (nvalid > 0))
            continue
        if kind == "first":
            _, didx, vidx, op, cidx, ctot = ls
            agg_d.append(c[didx])
            agg_v.append(glive & c[vidx] & (seg_sizes > 0))
            continue
        if kind == "anyv":
            _, sidx, last, cidx, ctot = ls
            nvalid = nvalid_of(cidx, ctot)
            agg_d.append(roll_next(c[sidx], last))
            agg_v.append(glive & (nvalid > 0))
            continue
        if kind == "last":
            _, didx, vidx, dlast, vlast = ls
            agg_d.append(roll_next(c[didx], dlast))
            agg_v.append(glive & roll_next(c[vidx], vlast) &
                         (seg_sizes > 0))
            continue

    key_d, key_v = [], []
    for (di, vi) in key_lane_idx:
        key_d.append(c[di])
        key_v.append((c[vi] & glive) if vi is not None else glive)
    return tuple(key_d), tuple(key_v), tuple(agg_d), tuple(agg_v)


def _seg_scan(x: jax.Array, boundary: jax.Array, op) -> jax.Array:
    """Segmented inclusive scan: row i = op-reduce over [seg_start..i]."""
    def combine(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, op(av, bv)), af | bf
    v, _ = jax.lax.associative_scan(combine, (x, boundary))
    return v


def _seg_first_valid(x: jax.Array, valid: jax.Array,
                     boundary: jax.Array) -> jax.Array:
    """Row i = first VALID x in [seg_start..i] (i's own value when it is
    the first). Rows before their segment's first valid value get 0 —
    callers mask those rows out anyway."""
    xm = jnp.where(valid, x, jnp.zeros((), x.dtype))

    def combine(a, b):
        av, aseen, af = a
        bv, bseen, bf = b
        v = jnp.where(bf, bv, jnp.where(aseen, av, bv))
        seen = jnp.where(bf, bseen, aseen | bseen)
        return v, seen, af | bf

    v, _, _ = jax.lax.associative_scan(combine, (xm, valid, boundary))
    return v


# ---------------------------------------------------------------------------
# whole-batch reductions (no keys)
# ---------------------------------------------------------------------------


def reduce_aggregate(batch: ColumnarBatch, aggs: List[AggSpec],
                     dtypes: List[dt.DType], live_mask=None
                     ) -> Tuple[ColumnarBatch, List[dt.DType]]:
    """Whole-batch reduction (no keys): grand aggregates
    (aggregate.scala:488-501 reduction path). Returns a 1-row batch."""
    if not batch.columns:
        # rows-only batch: only count(*) is expressible. A fused filter
        # mask still applies — count the LIVE rows.
        if live_mask is not None:
            iota = jnp.arange(live_mask.shape[0], dtype=jnp.int32)
            n = int(jax.device_get(jnp.sum(
                live_mask & (iota < batch.num_rows_device()))))
        else:
            n = batch.realized_num_rows()
        out_cols = [Column(dt.INT64,
                           jnp.full(128, n, dtype=jnp.int64))
                    for spec in aggs]
        return ColumnarBatch(out_cols, 1), [dt.INT64] * len(aggs)
    cols = [(c.data, c.validity) for c in batch.columns]
    agg_d, agg_v = _reduce(cols, tuple(dtypes), tuple(aggs),
                           batch.num_rows_device(), live_mask)
    out_cols, out_types = [], []
    for i, spec in enumerate(aggs):
        rtype = agg_result_dtype(spec, dtypes)
        out_cols.append(Column(rtype, agg_d[i], agg_v[i]))
        out_types.append(rtype)
    return ColumnarBatch(out_cols, 1), out_types


@partial(jax.jit, static_argnames=("dtypes", "aggs"))
def _reduce(cols, dtypes, aggs, num_rows, live_mask=None):
    """Direct whole-array reductions — no sort, no segments. IEEE
    semantics (Inf/NaN) come straight from jnp reductions."""
    capacity = cols[0][0].shape[0] if cols else 128
    iota = jnp.arange(capacity, dtype=jnp.int32)
    live = iota < num_rows
    if live_mask is not None:
        live = live & live_mask
    n_live = jnp.sum(live.astype(jnp.int32))
    any_live = n_live > 0
    first_live = jnp.where(any_live, jnp.argmax(live).astype(jnp.int32), 0)
    last_live = jnp.where(
        any_live,
        (capacity - 1 - jnp.argmax(live[::-1])).astype(jnp.int32), 0)

    def full(x):
        return jnp.full(capacity, x)

    agg_d, agg_v = [], []
    for spec in aggs:
        if spec.op == "count_star":
            agg_d.append(full(n_live.astype(jnp.int64)))
            agg_v.append(None)
            continue
        d, v = cols[spec.ordinal]
        valid = v if v is not None else jnp.ones(capacity, dtype=bool)
        contrib = valid & live
        n_valid = jnp.sum(contrib.astype(jnp.int64))
        out_valid = full(n_valid > 0)
        in_t = dtypes[spec.ordinal]
        if spec.op == "count":
            agg_d.append(full(n_valid))
            agg_v.append(None)
        elif spec.op == "sum":
            if in_t.is_integral or in_t is dt.BOOLEAN:
                x = jnp.where(contrib, d.astype(jnp.int64),
                              jnp.zeros((), jnp.int64))
                agg_d.append(full(jnp.sum(x)))
            else:
                x = jnp.where(contrib, d.astype(jnp.float64), 0.0)
                agg_d.append(full(jnp.sum(x)))
            agg_v.append(out_valid)
        elif spec.op == "sum_of_squares":
            x = d.astype(jnp.float64)
            x = jnp.where(contrib, x * x, 0.0)
            agg_d.append(full(jnp.sum(x)))
            agg_v.append(out_valid)
        elif spec.op in ("m2", "rterm"):
            x = jnp.where(contrib, d.astype(jnp.float64), 0.0)
            s = jnp.sum(x)
            nf = jnp.maximum(n_valid, 1).astype(jnp.float64)
            if spec.op == "rterm":
                agg_d.append(full((s * s) / nf))
            else:
                # exact whole-batch second moment: mean available in one
                # program, no shift trick needed
                mean = s / nf
                dd = jnp.where(contrib,
                               d.astype(jnp.float64) - mean, 0.0)
                agg_d.append(full(jnp.maximum(jnp.sum(dd * dd), 0.0)))
            agg_v.append(out_valid)
        elif spec.op in ("min", "max"):
            kd = d.dtype
            dd = d
            if in_t is dt.BOOLEAN:
                dd = d.astype(jnp.int8)
                kd = jnp.int8
            if in_t.is_floating:
                big = jnp.asarray(jnp.inf, kd)
            elif in_t is dt.BOOLEAN:
                big = jnp.asarray(1, kd)
            else:
                big = jnp.asarray(jnp.iinfo(kd).max, kd)
            if spec.op == "min":
                r = jnp.min(jnp.where(contrib, dd, big))
            else:
                small = -big if in_t.is_floating else \
                    jnp.asarray(0, kd) if in_t is dt.BOOLEAN else \
                    jnp.asarray(jnp.iinfo(kd).min, kd)
                r = jnp.max(jnp.where(contrib, dd, small))
            if in_t is dt.BOOLEAN:
                r = r.astype(jnp.bool_)
            agg_d.append(full(r))
            agg_v.append(out_valid)
        elif spec.op in ("first", "any_valid"):
            val = jax.lax.dynamic_index_in_dim(d, first_live,
                                               keepdims=False)
            agg_d.append(full(val))
            if spec.op == "any_valid":
                agg_v.append(out_valid)
            else:
                fv = jax.lax.dynamic_index_in_dim(valid, first_live,
                                                  keepdims=False)
                agg_v.append(full(fv & any_live))
        elif spec.op == "last":
            val = jax.lax.dynamic_index_in_dim(d, last_live,
                                               keepdims=False)
            lv = jax.lax.dynamic_index_in_dim(valid, last_live,
                                              keepdims=False)
            agg_d.append(full(val))
            agg_v.append(full(lv & any_live))
        else:
            raise ValueError(f"unknown aggregate op {spec.op}")
    return agg_d, agg_v
