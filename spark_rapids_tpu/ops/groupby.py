"""Group-by aggregation: sort-based segmented reduction.

cuDF gives the reference a hash-based ``groupBy.aggregate``
(aggregate.scala:810-890). TPUs have no device hash tables, but XLA's sort is
fast, so the TPU-native plan is:

  1. stable lexsort rows by group keys (nulls group together; NaN==NaN and
     -0.0==0.0 per Spark grouping semantics — sortkeys.equality_normalize),
  2. mark segment boundaries where any key differs from the previous row,
  3. ``segment_id = cumsum(boundary)-1``; padding rows park in a reserved
     segment that is never emitted,
  4. every aggregate becomes one ``jax.ops.segment_{sum,min,max}`` — XLA
     fuses all of them over a single pass,
  5. group keys gather from each segment's first row; the group count is a
     device scalar (no host sync until the consumer needs it).

Both halves of the reference's CudfAggregate split (update-from-raw and
merge-of-partials, AggregateFunctions.scala) map onto the same kernel with
different op lists — partial results are just another batch.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, StringColumn
from spark_rapids_tpu.ops import sortkeys
from spark_rapids_tpu.ops.sortkeys import SortKeySpec

# Aggregate op names understood by the kernel.
AGG_OPS = ("sum", "min", "max", "count", "count_star", "first", "last",
           "any_valid", "sum_of_squares")


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregation: op name + input ordinal (ignored for count_star).
    ``count`` counts valid rows of the input; ``first``/``last`` take the
    boundary row of each run (Spark first/last with ignoreNulls=False)."""

    op: str
    ordinal: int = -1


def groupby_aggregate(batch: ColumnarBatch, key_ordinals: List[int],
                      aggs: List[AggSpec], dtypes: List[dt.DType]
                      ) -> Tuple[ColumnarBatch, List[dt.DType]]:
    """Returns (result batch [keys..., agg results...], result dtypes)."""
    cols = [(c.data, c.validity) for c in batch.columns]
    out = _groupby(cols, tuple(dtypes), tuple(key_ordinals), tuple(aggs),
                   batch.num_rows_device())
    (key_d, key_v), (agg_d, agg_v), num_groups = out
    out_cols: List[Column] = []
    out_types: List[dt.DType] = []
    for i, ord_ in enumerate(key_ordinals):
        src = batch.columns[ord_]
        out_cols.append(src._like(key_d[i], key_v[i]))
        out_types.append(dtypes[ord_])
    for i, spec in enumerate(aggs):
        rtype = agg_result_dtype(spec, dtypes)
        if rtype is dt.STRING and spec.ordinal >= 0 and \
                isinstance(batch.columns[spec.ordinal], StringColumn):
            # preserve the dictionary: codes order == string order, so
            # min/max/first/last on codes are min/max/first/last on strings
            out_cols.append(
                batch.columns[spec.ordinal]._like(agg_d[i], agg_v[i]))
        else:
            out_cols.append(Column(rtype, agg_d[i], agg_v[i]))
        out_types.append(rtype)
    return ColumnarBatch(out_cols, num_groups), out_types


def agg_result_dtype(spec: AggSpec, dtypes: List[dt.DType]) -> dt.DType:
    if spec.op in ("count", "count_star"):
        return dt.INT64
    in_t = dtypes[spec.ordinal]
    if spec.op == "sum":
        # Spark: sum over integrals -> bigint, over fractionals -> double
        return dt.INT64 if in_t.is_integral or in_t is dt.BOOLEAN \
            else dt.FLOAT64
    if spec.op == "sum_of_squares":
        return dt.FLOAT64
    return in_t  # min/max/first/last/any_valid preserve type


@partial(jax.jit, static_argnames=("dtypes", "key_ordinals", "aggs"))
def _groupby(cols, dtypes, key_ordinals, aggs, num_rows):
    capacity = cols[0][0].shape[0]
    live = jnp.arange(capacity, dtype=jnp.int32) < num_rows

    # 1. sort by keys (ascending, nulls first — any consistent order works)
    specs = [SortKeySpec(o, True, True) for o in key_ordinals]
    order = sortkeys.lexsort_indices(list(cols), list(dtypes), specs,
                                     num_rows)
    sorted_cols = [(jnp.take(d, order),
                    None if v is None else jnp.take(v, order))
                   for d, v in cols]
    live_sorted = live  # live rows are a prefix after the pad-last sort

    # 2. boundaries: any normalized key differs from previous row
    boundary = jnp.zeros(capacity, dtype=bool).at[0].set(True)
    for o in key_ordinals:
        d, v = sorted_cols[o]
        comps, valid = sortkeys.equality_parts(d, v, dtypes[o])
        for comp in comps:
            boundary = boundary | jnp.concatenate(
                [jnp.ones(1, dtype=bool), comp[1:] != comp[:-1]])
        boundary = boundary | jnp.concatenate(
            [jnp.ones(1, dtype=bool), valid[1:] != valid[:-1]])
    boundary = boundary & live_sorted

    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    num_groups = jnp.sum(boundary).astype(jnp.int32)
    # park padding rows in the last segment slot; since num_groups <=
    # num_rows < capacity whenever padding exists, slot capacity-1 is free
    seg = jnp.where(live_sorted, seg, capacity - 1)

    # boundary row index of each segment (for keys / first), and segment
    # end row (for last)
    first_idx = jnp.nonzero(boundary, size=capacity, fill_value=0)[0]
    seg_sizes = jax.ops.segment_sum(live_sorted.astype(jnp.int32), seg,
                                    num_segments=capacity)
    last_idx = first_idx + jnp.maximum(seg_sizes, 1) - 1

    # 3. keys: gather first row of each segment
    key_d, key_v = [], []
    group_live = jnp.arange(capacity, dtype=jnp.int32) < num_groups
    for o in key_ordinals:
        d, v = sorted_cols[o]
        key_d.append(jnp.take(d, first_idx))
        if v is None:
            key_v.append(None)
        else:
            key_v.append(jnp.take(v, first_idx) & group_live)

    # 4. aggregates
    agg_d, agg_v = [], []
    for spec in aggs:
        d_out, v_out = _one_agg(spec, sorted_cols, dtypes, seg, live_sorted,
                                first_idx, last_idx, seg_sizes, capacity)
        agg_d.append(d_out)
        agg_v.append(None if v_out is None else v_out & group_live)
    return (key_d, key_v), (agg_d, agg_v), num_groups


def _one_agg(spec: AggSpec, sorted_cols, dtypes, seg, live, first_idx,
             last_idx, seg_sizes, capacity):
    if spec.op == "count_star":
        return seg_sizes.astype(jnp.int64), None

    d, v = sorted_cols[spec.ordinal]
    valid = v if v is not None else jnp.ones(capacity, dtype=bool)
    contrib = valid & live
    n_valid = jax.ops.segment_sum(contrib.astype(jnp.int64), seg,
                                  num_segments=capacity)

    if spec.op == "count":
        return n_valid, None
    # first/last over an empty segment (reduction over 0 rows) must be NULL,
    # so validity is always materialized and ANDed with segment non-emptiness
    if spec.op == "first":
        out = jnp.take(d, first_idx)
        ov = jnp.take(valid, first_idx) if v is not None \
            else jnp.ones(capacity, dtype=bool)
        return out, ov & (seg_sizes > 0)
    if spec.op == "last":
        out = jnp.take(d, last_idx)
        ov = jnp.take(valid, last_idx) if v is not None \
            else jnp.ones(capacity, dtype=bool)
        return out, ov & (seg_sizes > 0)

    out_valid = n_valid > 0
    in_t = dtypes[spec.ordinal]
    if spec.op == "sum":
        acc_t = jnp.int64 if (in_t.is_integral or in_t is dt.BOOLEAN) \
            else jnp.float64
        x = jnp.where(contrib, d.astype(acc_t), jnp.zeros((), acc_t))
        return jax.ops.segment_sum(x, seg, num_segments=capacity), out_valid
    if spec.op == "sum_of_squares":
        x = d.astype(jnp.float64)
        x = jnp.where(contrib, x * x, 0.0)
        return jax.ops.segment_sum(x, seg, num_segments=capacity), out_valid
    if spec.op in ("min", "max"):
        kd = d.dtype
        if in_t.is_floating:
            big = jnp.asarray(jnp.inf, kd)
        elif in_t is dt.BOOLEAN:
            d = d.astype(jnp.int8)
            kd = jnp.int8
            big = jnp.asarray(1, kd)
        else:
            big = jnp.asarray(jnp.iinfo(kd).max, kd)
        if spec.op == "min":
            x = jnp.where(contrib, d, big)
            r = jax.ops.segment_min(x, seg, num_segments=capacity)
        else:
            small = -big if in_t.is_floating else \
                jnp.asarray(0, kd) if in_t is dt.BOOLEAN else \
                jnp.asarray(jnp.iinfo(kd).min, kd)
            x = jnp.where(contrib, d, small)
            r = jax.ops.segment_max(x, seg, num_segments=capacity)
        if in_t is dt.BOOLEAN:
            r = r.astype(jnp.bool_)
        return r, out_valid
    if spec.op == "any_valid":
        out = jnp.take(d, first_idx)
        return out, out_valid
    raise ValueError(f"unknown aggregate op {spec.op}")


def reduce_aggregate(batch: ColumnarBatch, aggs: List[AggSpec],
                     dtypes: List[dt.DType]) -> Tuple[ColumnarBatch, List[dt.DType]]:
    """Whole-batch reduction (no keys): grand aggregates
    (aggregate.scala:488-501 reduction path). Returns a 1-row batch."""
    if not batch.columns:
        # rows-only batch: only count(*) is expressible
        n = batch.realized_num_rows()
        out_cols = [Column(dt.INT64,
                           jnp.full(128, n, dtype=jnp.int64))
                    for spec in aggs]
        return ColumnarBatch(out_cols, 1), [dt.INT64] * len(aggs)
    cols = [(c.data, c.validity) for c in batch.columns]
    agg_d, agg_v = _reduce(cols, tuple(dtypes), tuple(aggs),
                           batch.num_rows_device())
    out_cols, out_types = [], []
    for i, spec in enumerate(aggs):
        rtype = agg_result_dtype(spec, dtypes)
        out_cols.append(Column(rtype, agg_d[i], agg_v[i]))
        out_types.append(rtype)
    return ColumnarBatch(out_cols, 1), out_types


@partial(jax.jit, static_argnames=("dtypes", "aggs"))
def _reduce(cols, dtypes, aggs, num_rows):
    capacity = cols[0][0].shape[0] if cols else 128
    live = jnp.arange(capacity, dtype=jnp.int32) < num_rows
    seg = jnp.where(live, 0, 1).astype(jnp.int32)
    # reuse the segmented kernel with a single segment
    boundary_first = jnp.zeros(capacity, dtype=jnp.int32)
    n_live = jnp.sum(live.astype(jnp.int32)).astype(jnp.int32)
    first_idx = boundary_first  # all zeros: segment 0 starts at row 0
    last_idx = jnp.maximum(n_live - 1, 0) * jnp.ones(capacity, jnp.int32)
    seg_sizes = jnp.zeros(capacity, jnp.int32).at[0].set(n_live)
    agg_d, agg_v = [], []
    for spec in aggs:
        d_out, v_out = _one_agg(spec, list(cols), dtypes, seg, live,
                                first_idx, last_idx, seg_sizes, capacity)
        # only slot 0 is meaningful; broadcast capacity stays bucketed
        agg_d.append(d_out)
        agg_v.append(v_out)
    return agg_d, agg_v
