"""Group-by aggregation: sort-based segmented reduction.

cuDF gives the reference a hash-based ``groupBy.aggregate``
(aggregate.scala:810-890). TPUs have no device hash tables, but XLA's sort is
fast, so the TPU-native plan is:

  1. stable lexsort rows by group keys (nulls group together; NaN==NaN and
     -0.0==0.0 per Spark grouping semantics — sortkeys.equality_normalize),
  2. mark segment boundaries where any key differs from the previous row,
  3. ``segment_id = cumsum(boundary)-1``; padding rows park in a reserved
     segment that is never emitted,
  4. every aggregate becomes a prefix-scan + boundary gather over the
     CONTIGUOUS runs: sums/counts are cumsum differences at segment edges
     (exact for ints even across wrap; float error bounded like any
     reordered sum), min/max are segmented associative scans. TPU scatter
     (segment_sum et al.) measured ~30x slower than cumsum at 4M rows, so
     no scatters appear anywhere on this path,
  5. group keys gather from each segment's first row; the group count is a
     device scalar (no host sync until the consumer needs it).

Both halves of the reference's CudfAggregate split (update-from-raw and
merge-of-partials, AggregateFunctions.scala) map onto the same kernel with
different op lists — partial results are just another batch.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, StringColumn
from spark_rapids_tpu.ops import sortkeys
from spark_rapids_tpu.ops.sortkeys import SortKeySpec

# Aggregate op names understood by the kernel.
AGG_OPS = ("sum", "min", "max", "count", "count_star", "first", "last",
           "any_valid", "sum_of_squares")


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregation: op name + input ordinal (ignored for count_star).
    ``count`` counts valid rows of the input; ``first``/``last`` take the
    boundary row of each run (Spark first/last with ignoreNulls=False)."""

    op: str
    ordinal: int = -1


def groupby_aggregate(batch: ColumnarBatch, key_ordinals: List[int],
                      aggs: List[AggSpec], dtypes: List[dt.DType],
                      live_mask=None
                      ) -> Tuple[ColumnarBatch, List[dt.DType]]:
    """Returns (result batch [keys..., agg results...], result dtypes).
    ``live_mask`` fuses an upstream filter into the sort pass."""
    cols = [(c.data, c.validity) for c in batch.columns]
    out = _groupby(cols, tuple(dtypes), tuple(key_ordinals), tuple(aggs),
                   batch.num_rows_device(), live_mask=live_mask)
    (key_d, key_v), (agg_d, agg_v), num_groups = out
    out_cols: List[Column] = []
    out_types: List[dt.DType] = []
    for i, ord_ in enumerate(key_ordinals):
        src = batch.columns[ord_]
        out_cols.append(src._like(key_d[i], key_v[i]))
        out_types.append(dtypes[ord_])
    for i, spec in enumerate(aggs):
        rtype = agg_result_dtype(spec, dtypes)
        if rtype is dt.STRING and spec.ordinal >= 0 and \
                isinstance(batch.columns[spec.ordinal], StringColumn):
            # preserve the dictionary: codes order == string order, so
            # min/max/first/last on codes are min/max/first/last on strings
            out_cols.append(
                batch.columns[spec.ordinal]._like(agg_d[i], agg_v[i]))
        else:
            out_cols.append(Column(rtype, agg_d[i], agg_v[i]))
        out_types.append(rtype)
    return ColumnarBatch(out_cols, num_groups), out_types


def agg_result_dtype(spec: AggSpec, dtypes: List[dt.DType]) -> dt.DType:
    if spec.op in ("count", "count_star"):
        return dt.INT64
    in_t = dtypes[spec.ordinal]
    if spec.op == "sum":
        # Spark: sum over integrals -> bigint, over fractionals -> double
        return dt.INT64 if in_t.is_integral or in_t is dt.BOOLEAN \
            else dt.FLOAT64
    if spec.op == "sum_of_squares":
        return dt.FLOAT64
    return in_t  # min/max/first/last/any_valid preserve type


@partial(jax.jit, static_argnames=("dtypes", "key_ordinals", "aggs"))
def _groupby(cols, dtypes, key_ordinals, aggs, num_rows,
             live_mask=None):
    """``live_mask``: optional fused filter — masked-out rows are dead
    (they sort last with the padding and never reach a segment)."""
    capacity = cols[0][0].shape[0]
    live = jnp.arange(capacity, dtype=jnp.int32) < num_rows
    prefix_rows = num_rows  # PRE-mask count: the sort pads positionally
    if live_mask is not None:
        live = live & live_mask
        num_rows = jnp.sum(live).astype(jnp.int32)

    # 1. sort by keys (ascending, nulls first — any consistent order
    # works); every column's data+validity rides THROUGH the variadic
    # sort as payload lanes, so there are no per-column permutation
    # gathers afterwards
    specs = [SortKeySpec(o, True, True) for o in key_ordinals]
    payloads = [d for d, _ in cols] + \
               [v for _, v in cols if v is not None]
    sorted_flat = sortkeys.sort_with_payloads(
        list(cols), list(dtypes), specs, prefix_rows, payloads,
        live_mask=live_mask)
    sorted_d = sorted_flat[:len(cols)]
    rest = sorted_flat[len(cols):]
    sorted_cols = []
    for i, (_, v) in enumerate(cols):
        sv = rest.pop(0) if v is not None else None
        sorted_cols.append((sorted_d[i], sv))
    # live rows are a prefix after the pad-last sort
    live_sorted = jnp.arange(capacity, dtype=jnp.int32) < num_rows

    # 2. boundaries: any normalized key differs from previous row
    boundary = jnp.zeros(capacity, dtype=bool).at[0].set(True)
    for o in key_ordinals:
        d, v = sorted_cols[o]
        comps, valid = sortkeys.equality_parts(d, v, dtypes[o])
        for comp in comps:
            boundary = boundary | jnp.concatenate(
                [jnp.ones(1, dtype=bool), comp[1:] != comp[:-1]])
        boundary = boundary | jnp.concatenate(
            [jnp.ones(1, dtype=bool), valid[1:] != valid[:-1]])
    boundary = boundary & live_sorted

    num_groups = jnp.sum(boundary).astype(jnp.int32)

    # boundary row index of each segment: stable argsort of ~boundary is
    # exactly nonzero-in-order, without the scatter nonzero() lowers to
    first_idx = jnp.argsort(~boundary, stable=True).astype(jnp.int32)
    giota = jnp.arange(capacity, dtype=jnp.int32)
    group_live_ = giota < num_groups
    next_first = jnp.where(giota < num_groups - 1,
                           jnp.roll(first_idx, -1), num_rows)
    seg_sizes = jnp.where(group_live_,
                          next_first.astype(jnp.int32) - first_idx, 0)
    last_idx = first_idx + jnp.maximum(seg_sizes, 1) - 1

    # 3. keys: gather first row of each segment
    key_d, key_v = [], []
    group_live = jnp.arange(capacity, dtype=jnp.int32) < num_groups
    for o in key_ordinals:
        d, v = sorted_cols[o]
        key_d.append(jnp.take(d, first_idx))
        if v is None:
            key_v.append(None)
        else:
            key_v.append(jnp.take(v, first_idx) & group_live)

    # 4. aggregates
    agg_d, agg_v = [], []
    for spec in aggs:
        d_out, v_out = _one_agg(spec, sorted_cols, dtypes, boundary,
                                live_sorted, first_idx, last_idx,
                                seg_sizes, capacity)
        agg_d.append(d_out)
        agg_v.append(None if v_out is None else v_out & group_live)
    return (key_d, key_v), (agg_d, agg_v), num_groups


def _seg_sum_by_bounds(x: jax.Array, first_idx: jax.Array,
                       last_idx: jax.Array) -> jax.Array:
    """Per-segment sum over contiguous runs as cumsum differences — exact
    for integers even through wrap-around; float results are an ordinary
    reordered sum."""
    cs = jnp.cumsum(x)
    hi = jnp.take(cs, last_idx)
    lo = jnp.where(first_idx > 0,
                   jnp.take(cs, jnp.maximum(first_idx - 1, 0)),
                   jnp.zeros((), cs.dtype))
    return hi - lo


def _seg_scan(x: jax.Array, boundary: jax.Array, op) -> jax.Array:
    """Segmented inclusive scan: row i = op-reduce over [seg_start..i]."""
    def combine(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, op(av, bv)), af | bf
    v, _ = jax.lax.associative_scan(combine, (x, boundary))
    return v


def _one_agg(spec: AggSpec, sorted_cols, dtypes, boundary, live,
             first_idx, last_idx, seg_sizes, capacity):
    if spec.op == "count_star":
        return seg_sizes.astype(jnp.int64), None

    d, v = sorted_cols[spec.ordinal]
    valid = v if v is not None else jnp.ones(capacity, dtype=bool)
    contrib = valid & live
    n_valid = _seg_sum_by_bounds(contrib.astype(jnp.int64), first_idx,
                                 last_idx)

    if spec.op == "count":
        return n_valid, None
    # first/last over an empty segment (reduction over 0 rows) must be NULL,
    # so validity is always materialized and ANDed with segment non-emptiness
    if spec.op == "first":
        out = jnp.take(d, first_idx)
        ov = jnp.take(valid, first_idx) if v is not None \
            else jnp.ones(capacity, dtype=bool)
        return out, ov & (seg_sizes > 0)
    if spec.op == "last":
        out = jnp.take(d, last_idx)
        ov = jnp.take(valid, last_idx) if v is not None \
            else jnp.ones(capacity, dtype=bool)
        return out, ov & (seg_sizes > 0)

    out_valid = n_valid > 0
    in_t = dtypes[spec.ordinal]
    if spec.op == "sum":
        if in_t.is_integral or in_t is dt.BOOLEAN:
            x = jnp.where(contrib, d.astype(jnp.int64),
                          jnp.zeros((), jnp.int64))
            return _seg_sum_by_bounds(x, first_idx, last_idx), out_valid
        # floats: cumsum differences would poison later segments with
        # NaN once any segment holds ±Inf (Inf - Inf); the segmented
        # scan keeps Inf/NaN confined to their own segment
        x = jnp.where(contrib, d.astype(jnp.float64), 0.0)
        scan = _seg_scan(x, boundary, jnp.add)
        return jnp.take(scan, last_idx), out_valid
    if spec.op == "sum_of_squares":
        x = d.astype(jnp.float64)
        x = jnp.where(contrib, x * x, 0.0)
        scan = _seg_scan(x, boundary, jnp.add)
        return jnp.take(scan, last_idx), out_valid
    if spec.op in ("min", "max"):
        kd = d.dtype
        if in_t.is_floating:
            big = jnp.asarray(jnp.inf, kd)
        elif in_t is dt.BOOLEAN:
            d = d.astype(jnp.int8)
            kd = jnp.int8
            big = jnp.asarray(1, kd)
        else:
            big = jnp.asarray(jnp.iinfo(kd).max, kd)
        if spec.op == "min":
            x = jnp.where(contrib, d, big)
            scan = _seg_scan(x, boundary, jnp.minimum)
        else:
            small = -big if in_t.is_floating else \
                jnp.asarray(0, kd) if in_t is dt.BOOLEAN else \
                jnp.asarray(jnp.iinfo(kd).min, kd)
            x = jnp.where(contrib, d, small)
            scan = _seg_scan(x, boundary, jnp.maximum)
        r = jnp.take(scan, last_idx)
        if in_t is dt.BOOLEAN:
            r = r.astype(jnp.bool_)
        return r, out_valid
    if spec.op == "any_valid":
        out = jnp.take(d, first_idx)
        return out, out_valid
    raise ValueError(f"unknown aggregate op {spec.op}")


def reduce_aggregate(batch: ColumnarBatch, aggs: List[AggSpec],
                     dtypes: List[dt.DType], live_mask=None
                     ) -> Tuple[ColumnarBatch, List[dt.DType]]:
    """Whole-batch reduction (no keys): grand aggregates
    (aggregate.scala:488-501 reduction path). Returns a 1-row batch."""
    if not batch.columns:
        # rows-only batch: only count(*) is expressible. A fused filter
        # mask still applies — count the LIVE rows.
        if live_mask is not None:
            iota = jnp.arange(live_mask.shape[0], dtype=jnp.int32)
            n = int(jax.device_get(jnp.sum(
                live_mask & (iota < batch.num_rows_device()))))
        else:
            n = batch.realized_num_rows()
        out_cols = [Column(dt.INT64,
                           jnp.full(128, n, dtype=jnp.int64))
                    for spec in aggs]
        return ColumnarBatch(out_cols, 1), [dt.INT64] * len(aggs)
    cols = [(c.data, c.validity) for c in batch.columns]
    agg_d, agg_v = _reduce(cols, tuple(dtypes), tuple(aggs),
                           batch.num_rows_device(), live_mask)
    out_cols, out_types = [], []
    for i, spec in enumerate(aggs):
        rtype = agg_result_dtype(spec, dtypes)
        out_cols.append(Column(rtype, agg_d[i], agg_v[i]))
        out_types.append(rtype)
    return ColumnarBatch(out_cols, 1), out_types


@partial(jax.jit, static_argnames=("dtypes", "aggs"))
def _reduce(cols, dtypes, aggs, num_rows, live_mask=None):
    capacity = cols[0][0].shape[0] if cols else 128
    iota = jnp.arange(capacity, dtype=jnp.int32)
    live = iota < num_rows
    if live_mask is not None:
        live = live & live_mask
    # reuse the segmented kernel with a single segment starting at row 0.
    # With a fused live_mask the live rows need not be a prefix, so the
    # boundary rows are the first/last LIVE positions.
    boundary = iota == 0
    n_live = jnp.sum(live.astype(jnp.int32)).astype(jnp.int32)
    first_live = jnp.argmax(live).astype(jnp.int32)
    last_live = (capacity - 1 -
                 jnp.argmax(live[::-1])).astype(jnp.int32)
    any_live = n_live > 0
    first_idx = jnp.where(any_live, first_live, 0) * \
        jnp.ones(capacity, jnp.int32)
    last_idx = jnp.where(any_live, last_live, 0) * \
        jnp.ones(capacity, jnp.int32)
    seg_sizes = jnp.zeros(capacity, jnp.int32).at[0].set(n_live)
    agg_d, agg_v = [], []
    for spec in aggs:
        d_out, v_out = _one_agg(spec, list(cols), dtypes, boundary, live,
                                first_idx, last_idx, seg_sizes, capacity)
        # only slot 0 is meaningful; broadcast capacity stays bucketed
        agg_d.append(d_out)
        agg_v.append(v_out)
    return agg_d, agg_v
