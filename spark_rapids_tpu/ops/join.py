"""Equi-joins: sorted-hash probe with exact verification.

The reference drives cuDF's hash joins (GpuHashJoin.scala:302-318:
inner/left/leftSemi/leftAnti/full). TPUs have no device hash tables; the
TPU-native design uses *sorted hashes + searchsorted*:

  build:  h_b = hash64(keys);  sort build rows by h_b           (one sort)
  probe:  h_p = hash64(keys);  lo/hi = searchsorted(h_b, h_p)   (binary search)
  expand: pair k -> (probe_row i, build_row lo[i] + k-offset[i]) via one
          searchsorted over the match-count prefix sum
  verify: exact key equality per pair kills hash collisions; compaction
          drops dead pairs.

The expansion capacity is data-dependent: the only host sync in the kernel
realizes the total match count, mirroring where cuDF also sizes its output.
Null join keys never match (SQL equi-join semantics); the reference filters
them too (GpuHashJoin.scala:134-193) — here build/probe nulls get disjoint
hash sentinels so they cannot collide with anything.

Join conditions beyond the equi-keys are applied by the exec layer as a
post-join filter, same as the reference (GpuHashJoin.scala:285-291).
"""
from __future__ import annotations

from functools import partial
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, StringColumn, unify_dictionaries
from spark_rapids_tpu.native import kernels as nkr
from spark_rapids_tpu.ops import hashing, sortkeys
from spark_rapids_tpu.ops.buckets import bucket_capacity

_BUILD_NULL = jnp.int64(-0x6789ABCDEF01)
_PROBE_NULL = jnp.int64(0x13579BDF2468)

JOIN_TYPES = ("inner", "left", "right", "leftsemi", "leftanti", "full",
              "cross")


def common_key_type(a: dt.DType, b: dt.DType) -> Optional[dt.DType]:
    """Comparison type for a mixed-type equi-key pair (Spark's implicit
    cast: bigint = double compares as double). None = no numeric
    common type (date/timestamp/string mixes stay unsupported)."""
    if a is b:
        return a
    def _num(t):
        return t.is_floating or t.is_integral or t is dt.BOOLEAN
    if _num(a) and _num(b):
        return dt.FLOAT64 if (a.is_floating or b.is_floating) \
            else dt.INT64
    return None


def _key_hashes(batch: ColumnarBatch, ordinals: List[int],
                dtypes: List[dt.DType], null_sentinel,
                target_types: Optional[List[dt.DType]] = None
                ) -> jax.Array:
    """``target_types``: per-key comparison type — mismatched sides are
    cast so both sides hash identical values identically."""
    if target_types is not None and any(
            t is not dtypes[o] for t, o in zip(target_types, ordinals)):
        cols = list(batch.columns)
        for t, o in zip(target_types, ordinals):
            if t is not dtypes[o] and not isinstance(cols[o], StringColumn):
                cols[o] = Column(t, cols[o].data.astype(t.kernel_dtype),
                                 cols[o].validity)
        batch = ColumnarBatch(cols, batch.num_rows)
        dtypes = list(dtypes)
        for t, o in zip(target_types, ordinals):
            dtypes[o] = t
    h = hashing.hash_columns(batch, ordinals, dtypes)
    any_null = None
    for o in ordinals:
        v = batch.columns[o].validity
        if v is not None:
            nn = ~v
            any_null = nn if any_null is None else (any_null | nn)
    if any_null is not None:
        h = jnp.where(any_null, null_sentinel, h)
    return h


def unify_join_strings(left: ColumnarBatch, right: ColumnarBatch,
                       left_keys: List[int], right_keys: List[int]
                       ) -> Tuple[ColumnarBatch, ColumnarBatch]:
    """String key columns must share dictionaries so code equality means
    string equality."""
    lcols, rcols = list(left.columns), list(right.columns)
    for lo, ro in zip(left_keys, right_keys):
        lc, rc = lcols[lo], rcols[ro]
        if isinstance(lc, StringColumn) and isinstance(rc, StringColumn):
            u = unify_dictionaries([lc, rc])
            lcols[lo], rcols[ro] = u[0], u[1]
    return (ColumnarBatch(lcols, left.num_rows),
            ColumnarBatch(rcols, right.num_rows))


class PreparedBuild(NamedTuple):
    """Build side prepared once and probed across every stream batch:
    the hash-sorted build plus (join kernel on) the device-resident
    bucket table. Only valid when no JOIN KEY is a string column —
    string keys re-unify dictionaries per stream batch, changing the
    build hashes (non-key string columns are fine)."""

    sorted_build: ColumnarBatch
    sb_h: jax.Array
    table: Optional[object]  # native.kernels.join.ProbeTable


def prepare_build(build: ColumnarBatch, build_keys: List[int],
                  build_types: List[dt.DType],
                  stream_types_for_keys: List[dt.DType]
                  ) -> Optional[PreparedBuild]:
    """Hash + sort (+ table-build, kernel on) the build side once for
    reuse across stream batches. Returns None when a join key is a
    string column (per-batch dictionary unification makes the build
    hash stream-dependent)."""
    if any(isinstance(build.columns[o], StringColumn) for o in build_keys):
        return None
    commons = [common_key_type(st, build_types[bo])
               for st, bo in zip(stream_types_for_keys, build_keys)]
    if any(c is None for c in commons):
        return None
    h_b = _key_hashes(build, build_keys, build_types, _BUILD_NULL,
                      target_types=commons)
    sb_h, sb_datas, sb_vals, table = _build_sorted(
        [c.data for c in build.columns],
        [c.validity for c in build.columns], h_b,
        build.num_rows_device(), use_kernel=nkr.enabled("join"))
    cols = [c._like(d, v) for c, d, v in
            zip(build.columns, sb_datas, sb_vals)]
    return PreparedBuild(ColumnarBatch(cols, build.num_rows), sb_h, table)


class DensePreparedBuild(NamedTuple):
    """Dense-probe build (AQE hash->dense strategy switch): when the
    measured build key range is narrow, the probe is a direct table
    lookup instead of a binary search. ``start`` holds run offsets of
    the slot-sorted build — slot s's rows sit at
    ``sorted_build[start[s]:start[s+1]]`` — so DUPLICATE keys work (the
    fused broadcast path's inverse table is one-row-per-slot and bails
    on dups). Probe-row match runs come out in original build order
    (stable slot sort), exactly like the hash path's stable hash sort,
    so matched-pair output is bit-identical to the hash probe."""

    sorted_build: ColumnarBatch
    start: jax.Array  # int32[table_span + 1] slot run offsets
    kmin: np.int64
    span: np.int64
    table_span: int  # static padded slot count (>= span + 1)


def measure_key_range(col: Column, rows) -> Tuple[int, int, int]:
    """(min, max, valid-row count) of a numeric key column — the one
    device round trip of the dense-probe decision. Count 0 means no
    measurable rows (all-null or empty)."""
    kmin, kmax, n = jax.device_get(
        _key_range(col.data, col.validity, rows))
    return int(kmin), int(kmax), int(n)


@jax.jit
def _key_range(data, valid, rows):
    cap = data.shape[0]
    live = jnp.arange(cap, dtype=jnp.int32) < rows
    ok = live if valid is None else (live & valid)
    big = jnp.int64(1) << 62
    k = data.astype(jnp.int64)
    return (jnp.min(jnp.where(ok, k, big)),
            jnp.max(jnp.where(ok, k, -big)),
            jnp.sum(ok.astype(jnp.int64)))


def prepare_build_dense(build: ColumnarBatch, build_keys: List[int],
                        build_types: List[dt.DType],
                        stream_types_for_keys: List[dt.DType],
                        kmin: int, span: int
                        ) -> Optional[DensePreparedBuild]:
    """Slot-sort the build for dense probing. None when the shape does
    not qualify (only single integral non-string keys slot densely);
    the caller decides WHETHER dense pays (density/span policy) from
    :func:`measure_key_range` before building."""
    if len(build_keys) != 1 or span <= 0:
        return None
    o = build_keys[0]
    if isinstance(build.columns[o], StringColumn):
        return None
    common = common_key_type(stream_types_for_keys[0], build_types[o])
    if common is None or not common.is_integral:
        return None
    table_span = bucket_capacity(span + 1)
    sb_datas, sb_vals, start = _build_dense(
        [c.data for c in build.columns],
        [c.validity for c in build.columns],
        build.num_rows_device(), np.int64(kmin),
        key_ord=o, table_span=table_span)
    cols = [c._like(d, v) for c, d, v in
            zip(build.columns, sb_datas, sb_vals)]
    return DensePreparedBuild(ColumnarBatch(cols, build.num_rows),
                              start, np.int64(kmin), np.int64(span),
                              table_span)


@partial(jax.jit, static_argnames=("key_ord", "table_span"))
def _build_dense(b_datas, b_vals, b_rows, kmin, key_ord: int,
                 table_span: int):
    """Stable slot sort + run-offset table. kmin rides as a TRACED
    operand so every partition's build shares one compiled program."""
    cap = b_datas[key_ord].shape[0]
    live = jnp.arange(cap, dtype=jnp.int32) < b_rows
    valid = b_vals[key_ord]
    ok = live if valid is None else (live & valid)
    slot64 = b_datas[key_ord].astype(jnp.int64) - kmin
    ok = ok & (slot64 >= 0) & (slot64 < jnp.int64(table_span))
    # nulls/padding park at table_span: past every probed slot, so they
    # can never enter a run ([start[s], start[s+1]) with s < table_span)
    slot = jnp.where(ok, slot64, jnp.int64(table_span)).astype(jnp.int32)
    order = jnp.argsort(slot, stable=True)
    s_slot = jnp.take(slot, order)
    sb_datas = [jnp.take(d, order) for d in b_datas]
    sb_vals = [None if v is None else jnp.take(v, order) for v in b_vals]
    start = jnp.searchsorted(
        s_slot,
        jnp.arange(table_span + 1, dtype=jnp.int32)).astype(jnp.int32)
    return sb_datas, sb_vals, start


@partial(jax.jit, static_argnames=("table_span",))
def _probe_dense(start, kmin, span, p_key, p_valid, s_rows,
                 table_span: int):
    """Dense probe: two gathers replace two binary searches. Same
    (lo, hi, counts, total) contract as :func:`_hash_probe`, feeding
    the unchanged expand/verify/emit tail."""
    s_cap = p_key.shape[0]
    live_p = jnp.arange(s_cap, dtype=jnp.int32) < s_rows
    slot64 = p_key.astype(jnp.int64) - kmin
    ok = live_p & (slot64 >= 0) & (slot64 < span)
    if p_valid is not None:
        ok = ok & p_valid
    slot = jnp.where(ok, slot64, 0).astype(jnp.int32)
    lo = jnp.take(start, slot)
    hi = jnp.take(start, slot + 1)
    counts = jnp.where(ok, hi - lo, 0).astype(jnp.int64)
    total = jnp.sum(counts)
    return lo, hi, counts, total


def equi_join(stream: ColumnarBatch, build: ColumnarBatch,
              stream_keys: List[int], build_keys: List[int],
              stream_types: List[dt.DType], build_types: List[dt.DType],
              join_type: str = "inner",
              prepared: Optional[PreparedBuild] = None
              ) -> Tuple[ColumnarBatch, List[dt.DType]]:
    """Join ``stream`` (probe/left) against ``build`` (right). Output columns:
    stream columns then build columns (semi/anti: stream only). ``right``
    joins are planned as flipped ``left`` by the exec layer.
    ``prepared`` reuses a :func:`prepare_build` result across stream
    batches (the exec layer's build-once/probe-many seam)."""
    assert join_type in ("inner", "left", "leftsemi", "leftanti", "full")
    if prepared is None:
        stream, build = unify_join_strings(stream, build, stream_keys,
                                           build_keys)

    commons = [common_key_type(stream_types[so], build_types[bo])
               for so, bo in zip(stream_keys, build_keys)]
    assert all(c is not None for c in commons), (
        "no common comparison type for join keys",
        [stream_types[o] for o in stream_keys],
        [build_types[o] for o in build_keys])
    use_kernel = nkr.enabled("join")
    if isinstance(prepared, DensePreparedBuild):
        # ---- phase 1 (device), dense: direct slot lookup, no hashing
        # of either side at all
        sorted_build = prepared.sorted_build
        so = stream.columns[stream_keys[0]]
        lo, hi, counts, total = _probe_dense(
            prepared.start, prepared.kmin, prepared.span,
            so.data, so.validity, stream.num_rows_device(),
            prepared.table_span)
    elif prepared is not None:
        # ---- phase 1 (device), amortized: probe the prepared table
        h_p = _key_hashes(stream, stream_keys, stream_types, _PROBE_NULL,
                          target_types=commons)
        sorted_build = prepared.sorted_build
        lo, hi, counts, total = _probe_sorted(
            prepared.sb_h, prepared.table, h_p,
            stream.num_rows_device(),
            use_kernel=use_kernel and prepared.table is not None)
    else:
        h_p = _key_hashes(stream, stream_keys, stream_types, _PROBE_NULL,
                          target_types=commons)
        # ---- phase 1 (device): sort build, probe, count matches
        b_datas = [c.data for c in build.columns]
        b_vals = [c.validity for c in build.columns]
        (sb_h, sb_datas, sb_vals, lo, hi, counts, total) = _probe_counts(
            b_datas, b_vals, h_b := _key_hashes(
                build, build_keys, build_types, _BUILD_NULL,
                target_types=commons),
            build.num_rows_device(), h_p, stream.num_rows_device(),
            use_kernel=use_kernel)
        sorted_build_cols = [c._like(d, v) for c, d, v in
                             zip(build.columns, sb_datas, sb_vals)]
        sorted_build = ColumnarBatch(sorted_build_cols, build.num_rows)

    # ---- the one host sync: candidate-pair count -> output capacity
    total_i = int(jax.device_get(total))
    out_cap = bucket_capacity(max(total_i, 1))

    # ---- phase 2 (device): expand pairs, verify exact equality (on the
    # per-pair common comparison type)
    def _cast(d, t, c):
        return d if t is c else d.astype(c.kernel_dtype)

    key_pairs = tuple(
        (_cast(stream.columns[so].data, stream_types[so], c),
         stream.columns[so].validity,
         _cast(sorted_build.columns[bo].data, build_types[bo], c),
         sorted_build.columns[bo].validity)
        for so, bo, c in zip(stream_keys, build_keys, commons))
    key_types = tuple(commons)
    pi, bi, match = _expand_verify(lo, hi, counts, total, key_pairs,
                                   key_types, out_cap)

    return _emit(stream, sorted_build, stream_types, build_types,
                 pi, bi, match, counts, total, join_type, out_cap)


def _sort_build(b_datas, b_vals, h_b, b_rows):
    b_cap = h_b.shape[0]
    live_b = jnp.arange(b_cap, dtype=jnp.int32) < b_rows
    # Push padding rows to the top of the sort with int64 max. Real hashes
    # span the full int64 range, so any smaller sentinel can sort BELOW a
    # real row and break the "positions [0, b_rows) are real" invariant
    # _emit's full-join path relies on. If a real hash ties the sentinel,
    # stable argsort still orders it first (pads have the highest indices),
    # and the exact-key verification kills any pad candidate pairs.
    h_b_l = jnp.where(live_b, h_b, jnp.iinfo(jnp.int64).max)
    order = jnp.argsort(h_b_l, stable=True)
    sb_h = jnp.take(h_b_l, order)
    sb_datas = [jnp.take(d, order) for d in b_datas]
    sb_vals = [None if v is None else jnp.take(v, order) for v in b_vals]
    return sb_h, sb_datas, sb_vals


def _hash_probe(sb_h, table, h_p, s_rows, use_kernel: bool):
    """Leftmost hash-match position + run length per probe row: the
    bucket-table kernel and the two searchsorted calls share this exact
    contract (tests/test_kernels.py holds them bit-equal)."""
    s_cap = h_p.shape[0]
    live_p = jnp.arange(s_cap, dtype=jnp.int32) < s_rows
    if use_kernel:
        from spark_rapids_tpu.native.kernels import join as njoin

        lo, cnt = njoin.probe(table, h_p)
        hi = lo + cnt
    else:
        lo = jnp.searchsorted(sb_h, h_p, side="left")
        hi = jnp.searchsorted(sb_h, h_p, side="right")
    # clamp hi to live build rows (padding key int64-max never matches a
    # real hash, but belt-and-braces if a hash equals the sentinel)
    counts = jnp.where(live_p, hi - lo, 0).astype(jnp.int64)
    total = jnp.sum(counts)
    return lo, hi, counts, total


@partial(jax.jit, static_argnames=("use_kernel",))
def _probe_counts(b_datas, b_vals, h_b, b_rows, h_p, s_rows,
                  use_kernel: bool = False):
    sb_h, sb_datas, sb_vals = _sort_build(b_datas, b_vals, h_b, b_rows)
    table = None
    if use_kernel:
        from spark_rapids_tpu.native.kernels import join as njoin

        table = njoin.build_table(sb_h, b_rows,
                                  njoin.table_bits_for(sb_h.shape[0]))
    lo, hi, counts, total = _hash_probe(sb_h, table, h_p, s_rows,
                                        use_kernel)
    return sb_h, sb_datas, sb_vals, lo, hi, counts, total


@partial(jax.jit, static_argnames=("use_kernel",))
def _build_sorted(b_datas, b_vals, h_b, b_rows, use_kernel: bool = False):
    """Build-once half of the prepared path: one program sorts the build
    and (kernel on) derives the bucket table that stays HBM-resident
    across every stream batch."""
    sb_h, sb_datas, sb_vals = _sort_build(b_datas, b_vals, h_b, b_rows)
    table = None
    if use_kernel:
        from spark_rapids_tpu.native.kernels import join as njoin

        table = njoin.build_table(sb_h, b_rows,
                                  njoin.table_bits_for(sb_h.shape[0]))
    return sb_h, sb_datas, sb_vals, table


@partial(jax.jit, static_argnames=("use_kernel",))
def _probe_sorted(sb_h, table, h_p, s_rows, use_kernel: bool = False):
    """Probe-many half of the prepared path (one program per stream
    batch, no build work)."""
    return _hash_probe(sb_h, table, h_p, s_rows, use_kernel)


@partial(jax.jit, static_argnames=("key_types", "out_cap"))
def _expand_verify(lo, hi, counts, total, key_pairs, key_types,
                   out_cap: int):
    """pair k in [0,out_cap): probe row pi[k], build row bi[k], and whether
    the pair is live and exactly key-equal."""
    offsets = jnp.cumsum(counts)  # inclusive
    k = jnp.arange(out_cap, dtype=jnp.int64)
    pi = jnp.searchsorted(offsets, k, side="right").astype(jnp.int32)
    pi_c = jnp.clip(pi, 0, lo.shape[0] - 1)
    excl = offsets - counts  # exclusive prefix
    bi = (jnp.take(lo, pi_c) + (k - jnp.take(excl, pi_c))).astype(jnp.int32)
    live_pair = k < total
    match = live_pair
    for (sd, sv, bd, bv), t in zip(key_pairs, key_types):
        s_comps, s_valid = sortkeys.equality_parts(sd, sv, t)
        b_comps, b_valid = sortkeys.equality_parts(bd, bv, t)
        bi_c = jnp.clip(bi, 0, bd.shape[0] - 1)
        match = match & jnp.take(s_valid, pi_c) & jnp.take(b_valid, bi_c)
        for sc, bc in zip(s_comps, b_comps):
            match = match & (jnp.take(sc, pi_c) == jnp.take(bc, bi_c))
    return pi_c, bi, match


def _emit(stream: ColumnarBatch, build: ColumnarBatch,
          stream_types, build_types, pi, bi, match, counts, total,
          join_type: str, out_cap: int
          ) -> Tuple[ColumnarBatch, List[dt.DType]]:
    s_rows = stream.num_rows_device()
    s_cap = stream.capacity

    if join_type in ("leftsemi", "leftanti"):
        matched = _probe_matched(counts, match, s_cap)
        live_s = jnp.arange(s_cap, dtype=jnp.int32) < s_rows
        keep = (matched if join_type == "leftsemi" else ~matched) & live_s
        from spark_rapids_tpu.ops.filter import compact_batch
        out = compact_batch(stream, keep)
        return out, list(stream_types)

    # matched pairs, compacted (the partition kernel computes the same
    # stable permutation with one prefix scan instead of a sort network)
    if nkr.enabled("sort"):
        from spark_rapids_tpu.native.kernels import sort as nsort

        order = nsort.partition_order(match)
    else:
        order = jnp.argsort(~match, stable=True)
    n_match = jnp.sum(match).astype(jnp.int32)
    pi_s = jnp.take(pi, order)
    bi_s = jnp.take(bi, order)
    pair_live = jnp.arange(out_cap, dtype=jnp.int32) < n_match

    cols: List[Column] = []
    for c in stream.columns:
        cols.append(c.gather(pi_s, in_bounds_mask=None))
    for c in build.columns:
        cols.append(c.gather(bi_s, in_bounds_mask=None))
    inner = ColumnarBatch(cols, n_match)
    out_types = list(stream_types) + list(build_types)

    if join_type == "inner":
        return inner, out_types

    # left/full: append unmatched stream rows with null build side
    matched = _probe_matched(counts, match, s_cap)
    live_s = jnp.arange(s_cap, dtype=jnp.int32) < s_rows
    from spark_rapids_tpu.ops.concat import concat_batches
    from spark_rapids_tpu.ops.filter import compact_batch

    unmatched_keep = (~matched) & live_s
    un_stream = compact_batch(stream, unmatched_keep)
    null_build = [Column.all_null(t, un_stream.capacity)
                  for t in build_types]
    left_extra = ColumnarBatch(list(un_stream.columns) + null_build,
                               un_stream.num_rows)
    pieces = [inner, left_extra]

    if join_type == "full":
        b_rows = build.num_rows_device()
        b_cap = build.capacity
        bmatched = _build_matched(bi, match, b_cap)
        live_b = jnp.arange(b_cap, dtype=jnp.int32) < b_rows
        un_build = compact_batch(build, (~bmatched) & live_b)
        null_stream = [Column.all_null(t, un_build.capacity)
                       for t in stream_types]
        pieces.append(ColumnarBatch(null_stream + list(un_build.columns),
                                    un_build.num_rows))
    return concat_batches(pieces), out_types


@partial(jax.jit, static_argnames=("s_cap",))
def _probe_matched(counts, match, s_cap: int):
    """Per-probe-row "has a match": pairs are laid out in ascending probe
    order, so each row's pairs are the contiguous run
    [offsets[r]-counts[r], offsets[r]) — a cumsum difference answers
    "any match in the run" with gathers only (the segment_max scatter
    this replaces measured ~30x a cumsum on TPU)."""
    offsets = jnp.cumsum(counts)  # inclusive
    cs = jnp.cumsum(match.astype(jnp.int64))
    pair_cap = cs.shape[0]
    hi_idx = jnp.clip(offsets - 1, 0, pair_cap - 1).astype(jnp.int32)
    excl = offsets - counts
    lo_gate = excl > 0
    lo_idx = jnp.clip(excl - 1, 0, pair_cap - 1).astype(jnp.int32)
    hi = jnp.take(cs, hi_idx)
    lo = jnp.where(lo_gate, jnp.take(cs, lo_idx), 0)
    got = jnp.where(counts > 0, hi - lo, 0)
    out = got > 0
    # counts has stream-capacity length == s_cap
    return out[:s_cap]


@partial(jax.jit, static_argnames=("b_cap",))
def _build_matched(bi, match, b_cap: int):
    bi_c = jnp.where(match, bi, b_cap)  # dead pairs park out of range
    return jax.ops.segment_max(
        match.astype(jnp.int32), jnp.clip(bi_c, 0, b_cap),
        num_segments=b_cap + 1)[:b_cap] > 0


def cross_join(stream: ColumnarBatch, build: ColumnarBatch,
               stream_types, build_types
               ) -> Tuple[ColumnarBatch, List[dt.DType]]:
    """Brute-force cartesian product (GpuCartesianProductExec analogue —
    disabled by default at the planner, GpuOverrides.scala:1841-1856)."""
    n_s = stream.realized_num_rows()
    n_b = build.realized_num_rows()
    total = n_s * n_b
    out_cap = bucket_capacity(max(total, 1))
    k = jnp.arange(out_cap, dtype=jnp.int64)
    pi = (k // max(n_b, 1)).astype(jnp.int32)
    bi = (k % max(n_b, 1)).astype(jnp.int32)
    cols = [c.gather(pi) for c in stream.columns] + \
           [c.gather(bi) for c in build.columns]
    return (ColumnarBatch(cols, total),
            list(stream_types) + list(build_types))


def nested_loop_join(stream: ColumnarBatch, build: ColumnarBatch,
                     stream_types, build_types, cond_mask,
                     referenced: List[int]
                     ) -> Tuple[ColumnarBatch, List[dt.DType]]:
    """Cross product with the residual condition fused into pair expansion
    (GpuBroadcastNestedLoopJoinExec analogue, sql-plugin/.../execution/
    GpuBroadcastNestedLoopJoinExec.scala — the reference materializes the
    full product then filters; here only the columns the condition actually
    reads are gathered at full n_s*n_b width, all remaining columns are
    gathered once at the compacted match count).

    ``cond_mask`` is a CompiledFilter.mask-style callable batch->bool[cap];
    ``referenced`` lists the joined-schema ordinals the condition reads."""
    n_s = stream.realized_num_rows()
    n_b = build.realized_num_rows()
    total = n_s * n_b
    pair_cap = bucket_capacity(max(total, 1))
    pi, bi, live = _pair_grid(pair_cap, max(n_b, 1), total)

    refset = set(referenced)
    n_left = len(stream.columns)
    pair_cols: List[Column] = []
    for o, (c, t) in enumerate(zip(stream.columns, stream_types)):
        pair_cols.append(c.gather(pi) if o in refset
                         else Column.all_null(t, pair_cap))
    for o, (c, t) in enumerate(zip(build.columns, build_types)):
        pair_cols.append(c.gather(bi) if (n_left + o) in refset
                         else Column.all_null(t, pair_cap))
    keep = cond_mask(ColumnarBatch(pair_cols, total))

    pi_s, bi_s, n_match = _compact_pairs(pi, bi, keep & live,
                                         use_kernel=nkr.enabled("sort"))
    n_match_i = int(jax.device_get(n_match))  # the one host sync
    out_cap = bucket_capacity(max(n_match_i, 1))
    pi_s, bi_s = pi_s[:out_cap], bi_s[:out_cap]

    cols = [c.gather(pi_s) for c in stream.columns] + \
           [c.gather(bi_s) for c in build.columns]
    return (ColumnarBatch(cols, n_match_i),
            list(stream_types) + list(build_types))


@partial(jax.jit, static_argnames=("pair_cap",))
def _pair_grid(pair_cap: int, n_b, total):
    k = jnp.arange(pair_cap, dtype=jnp.int64)
    pi = (k // n_b).astype(jnp.int32)
    bi = (k % n_b).astype(jnp.int32)
    return pi, bi, k < total


@partial(jax.jit, static_argnames=("use_kernel",))
def _compact_pairs(pi, bi, match, use_kernel: bool = False):
    if use_kernel:
        from spark_rapids_tpu.native.kernels import sort as nsort

        order = nsort.partition_order(match)
    else:
        order = jnp.argsort(~match, stable=True)
    return (jnp.take(pi, order), jnp.take(bi, order),
            jnp.sum(match).astype(jnp.int32))
