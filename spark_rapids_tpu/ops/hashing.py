"""64-bit hashing of key columns.

Used by hash partitioning (GpuHashPartitioning analogue) and the
sort-of-hashes equi-join. Requirements:

- deterministic across processes and batches (shuffle routes rows of the
  same key to the same partition regardless of which host hashed them),
- dictionary-independent for strings: we hash string *content* host-side
  once per dictionary entry (dictionaries are tiny vs rows) and gather by
  code on device — the device never touches variable-length bytes,
- NaN == NaN and -0.0 == 0.0 hash equal (grouping semantics).

Mixing is splitmix64, a well-known public-domain finalizer.
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import StringColumn
from spark_rapids_tpu.ops import sortkeys

_NULL_HASH = np.int64(42)  # Spark HashPartitioning leaves the seed for nulls

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3


def fnv1a64(s: str) -> int:
    """Deterministic string hash (host-side, per dictionary entry)."""
    h = _FNV_OFFSET
    for b in s.encode("utf-8"):
        h = ((h ^ b) * _FNV_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h - (1 << 64) if h >= (1 << 63) else h


def dict_hashes(col: StringColumn) -> np.ndarray:
    """int64 content-hash per dictionary entry (cached on the column)."""
    cached = getattr(col, "_dict_hashes", None)
    if cached is not None and len(cached) == len(col.dictionary):
        return cached
    h = np.array([fnv1a64(str(s)) for s in col.dictionary], dtype=np.int64) \
        if len(col.dictionary) else np.zeros(1, dtype=np.int64)
    col._dict_hashes = h
    return h


def _splitmix64(x: jax.Array) -> jax.Array:
    # logical shifts require unsigned; int ops wrap two's-complement either way
    z = x.astype(jnp.uint64) + jnp.uint64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    z = z ^ (z >> jnp.uint64(31))
    return z.astype(jnp.int64)


def _numeric_to_int64(data: jax.Array, dtype: dt.DType) -> jax.Array:
    """Deterministic int64 image of a value with NaN==NaN, -0.0==0.0.

    f64 cannot be bitcast on TPU (X64 rewrite limitation); instead split it
    into (f32 head, f32 residual) — an exact, deterministic decomposition —
    and bitcast each half as 32-bit."""
    if dtype is dt.FLOAT64:
        x = sortkeys.canonicalize_floats(data)
        hi = x.astype(jnp.float32)
        lo = (x - hi.astype(jnp.float64)).astype(jnp.float32)
        lo = sortkeys.canonicalize_floats(lo)  # NaN residue canonical too
        hi_i = jax.lax.bitcast_convert_type(hi, jnp.int32).astype(jnp.int64)
        lo_i = jax.lax.bitcast_convert_type(lo, jnp.int32).astype(jnp.int64)
        return (hi_i << 32) | (lo_i & jnp.int64(0xFFFFFFFF))
    if dtype is dt.FLOAT32:
        x = sortkeys.canonicalize_floats(data)
        return jax.lax.bitcast_convert_type(x, jnp.int32).astype(jnp.int64)
    return data.astype(jnp.int64)


def hash_columns(batch: ColumnarBatch, key_ordinals: List[int],
                 dtypes: List[dt.DType]) -> jax.Array:
    """int64 combined hash of the key columns for every row."""
    normalized: List[Tuple[jax.Array, jax.Array]] = []
    for o in key_ordinals:
        c = batch.columns[o]
        if isinstance(c, StringColumn):
            h_tab = jnp.asarray(dict_hashes(c))
            val = jnp.take(h_tab, c.data, mode="clip")
        else:
            val = _numeric_to_int64(c.data, dtypes[o])
        valid = c.validity
        if valid is None:
            valid = jnp.ones(c.capacity, dtype=bool)
        normalized.append((jnp.where(valid, val, jnp.int64(_NULL_HASH)),
                           valid))
    vals = tuple(v for v, _ in normalized)
    return _combine(vals)


@jax.jit
def _combine(vals: Tuple[jax.Array, ...]) -> jax.Array:
    h = jnp.full(vals[0].shape, jnp.int64(0x2545F491), dtype=jnp.int64) \
        if vals else None
    for v in vals:
        h = _splitmix64(h ^ v)
    return h


# ---------------------------------------------------------------------------
# host (numpy) mirror — AQE skew detection over gathered exchange input
# ---------------------------------------------------------------------------
#
# The in-program exchange already holds the full input host-side (one
# device_get gathers it before the collective); mirroring the partition
# hash in numpy lets skew detection run without any extra device work.
# Routing always uses the DEVICE hash, so a mirror divergence could only
# mis-detect skew (a performance decision), never misplace a row — but
# tests/test_aqe_replan.py pins the mirror bit-equal anyway.


def _host_splitmix64(x: np.ndarray) -> np.ndarray:
    z = (x.astype(np.uint64) + np.uint64(0x9E3779B97F4A7C15))
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    z = z ^ (z >> np.uint64(31))
    return z.astype(np.int64)


def _host_canonicalize_floats(x: np.ndarray) -> np.ndarray:
    zero = np.zeros((), dtype=x.dtype)
    nan = np.full((), np.nan, dtype=x.dtype)
    x = np.where(x == zero, zero, x)
    return np.where(np.isnan(x), nan, x)


def host_numeric_to_int64(data: np.ndarray, dtype: dt.DType) -> np.ndarray:
    """numpy twin of :func:`_numeric_to_int64` — same (hi, residual)
    float split, same bitcasts, so a value hashes identically on host
    and device."""
    if dtype is dt.FLOAT64:
        x = _host_canonicalize_floats(data.astype(np.float64))
        hi = x.astype(np.float32)
        lo = (x - hi.astype(np.float64)).astype(np.float32)
        lo = _host_canonicalize_floats(lo)
        hi_i = hi.view(np.int32).astype(np.int64)
        lo_i = lo.view(np.int32).astype(np.int64)
        return (hi_i << 32) | (lo_i & np.int64(0xFFFFFFFF))
    if dtype is dt.FLOAT32:
        x = _host_canonicalize_floats(data.astype(np.float32))
        return x.view(np.int32).astype(np.int64)
    return data.astype(np.int64)


def host_partition_ids(datas: List[np.ndarray],
                       valids: List[Optional[np.ndarray]],
                       dtypes: List[dt.DType], key_ordinals: List[int],
                       num_out: int) -> np.ndarray:
    """Per-row reduce-partition id, bit-equal to the device shuffle
    step's pid column (parallel.shuffle.DistributedShuffleStep). String
    keys never reach here — in-program exchanges are gated to
    non-string schemas at the planner."""
    with np.errstate(over="ignore"):
        vals = []
        for o in key_ordinals:
            img = host_numeric_to_int64(datas[o], dtypes[o])
            if valids[o] is not None:
                img = np.where(valids[o], img, np.int64(_NULL_HASH))
            vals.append(img)
        n = len(datas[key_ordinals[0]]) if key_ordinals else 0
        h = np.full(n, np.int64(0x2545F491), dtype=np.int64)
        for v in vals:
            h = _host_splitmix64(h ^ v)
    m = h % np.int64(num_out)
    return np.where(m < 0, m + num_out, m).astype(np.int32)
