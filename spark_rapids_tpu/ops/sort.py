"""Sort kernels (cuDF ``Table.orderBy`` analogue, GpuSortExec.scala:104).

One stable lexsort over int64 total-order keys (ops/sortkeys.py), then a
gather of every payload column. XLA lowers to the TPU-native variadic sort.
"""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.ops import sortkeys
from spark_rapids_tpu.ops.sortkeys import SortKeySpec


@jax.jit
def _gather_all(datas, validities, order):
    out_d = [jnp.take(d, order) for d in datas]
    out_v = [None if v is None else jnp.take(v, order) for v in validities]
    return out_d, out_v


def sort_batch(batch: ColumnarBatch, specs: List[SortKeySpec],
               dtypes) -> ColumnarBatch:
    cols = [(c.data, c.validity) for c in batch.columns]
    order = _sort_indices(cols, tuple(dtypes), tuple(specs),
                          batch.num_rows_device())
    datas = [c.data for c in batch.columns]
    validities = [c.validity for c in batch.columns]
    out_d, out_v = _gather_all(datas, validities, order)
    out_cols = [c._like(d, v)
                for c, d, v in zip(batch.columns, out_d, out_v)]
    return ColumnarBatch(out_cols, batch.num_rows)


from functools import partial  # noqa: E402


@partial(jax.jit, static_argnames=("dtypes", "specs"))
def _sort_indices(cols, dtypes, specs, num_rows):
    return sortkeys.lexsort_indices(list(cols), list(dtypes), list(specs),
                                    num_rows)


def sort_indices(batch: ColumnarBatch, specs: List[SortKeySpec],
                 dtypes) -> jax.Array:
    cols = [(c.data, c.validity) for c in batch.columns]
    return _sort_indices(cols, tuple(dtypes), tuple(specs),
                         batch.num_rows_device())
