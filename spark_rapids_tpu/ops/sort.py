"""Sort kernels (cuDF ``Table.orderBy`` analogue, GpuSortExec.scala:104).

Payload columns ride THROUGH the variadic sort (``lax.sort`` operands
past ``num_keys``): the TPU sort network moves key and payload lanes
together, so no per-column permutation gathers happen afterwards — the
measured gather cost is ~75-150 ms/column at 4M rows vs a single variadic
sort pass. ``sort_indices`` keeps the permutation-producing path for
callers that need the order itself.
"""
from __future__ import annotations

from functools import partial
from typing import List

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.ops import sortkeys
from spark_rapids_tpu.ops.sortkeys import SortKeySpec


# Above this many payload lanes the variadic sort switches to
# argsort + per-column gathers: XLA's compile time for a sort network
# carrying many 64-bit (= emulated 32-bit-pair) operands explodes —
# measured: TPCx-BB q26's ORDER BY at 131k rows with 9 int64 + 8 bool
# payload lanes sat in XLA for >20 MINUTES, while the gathers it avoids
# cost ~75-150 ms/column only at multi-million-row widths.
_CARRY_MAX_LANES = 6


@partial(jax.jit, static_argnames=("dtypes", "specs", "kernel_token"))
def _sort_carry(datas, validities, dtypes, specs, num_rows,
                kernel_token=()):
    # kernel_token: native-kernel gate state — the trace routes through
    # the radix kernel or lax.sort at trace time, so a knob flip must
    # miss this cache
    """One stable variadic sort: [pad_rank, spec keys..., payloads...].
    Wide payload sets sort an iota lane instead and gather."""
    payloads = list(datas) + [v for v in validities if v is not None]
    if len(payloads) > _CARRY_MAX_LANES:
        cap = datas[0].shape[0] if datas else 0
        iota = jnp.arange(cap, dtype=jnp.int32)
        (order,) = sortkeys.sort_with_payloads(
            list(zip(datas, validities)), list(dtypes), list(specs),
            num_rows, [iota])
        out_d = [jnp.take(d, order) for d in datas]
        out_v = [None if v is None else jnp.take(v, order)
                 for v in validities]
        return out_d, out_v
    out = sortkeys.sort_with_payloads(
        list(zip(datas, validities)), list(dtypes), list(specs),
        num_rows, payloads)
    out_d = list(out[:len(datas)])
    rest = list(out[len(datas):])
    out_v = []
    for v in validities:
        out_v.append(None if v is None else rest.pop(0))
    return out_d, out_v


def sort_batch(batch: ColumnarBatch, specs: List[SortKeySpec],
               dtypes) -> ColumnarBatch:
    datas = [c.data for c in batch.columns]
    validities = [c.validity for c in batch.columns]
    from spark_rapids_tpu.native import kernels as nkr

    out_d, out_v = _sort_carry(datas, validities, tuple(dtypes),
                               tuple(specs), batch.num_rows_device(),
                               kernel_token=nkr.cache_token())
    out_cols = [c._like(d, v)
                for c, d, v in zip(batch.columns, out_d, out_v)]
    return ColumnarBatch(out_cols, batch.num_rows)


@partial(jax.jit, static_argnames=("dtypes", "specs", "kernel_token"))
def _sort_indices(cols, dtypes, specs, num_rows, kernel_token=()):
    return sortkeys.lexsort_indices(list(cols), list(dtypes), list(specs),
                                    num_rows)


def sort_indices(batch: ColumnarBatch, specs: List[SortKeySpec],
                 dtypes) -> jax.Array:
    from spark_rapids_tpu.native import kernels as nkr

    cols = [(c.data, c.validity) for c in batch.columns]
    return _sort_indices(cols, tuple(dtypes), tuple(specs),
                         batch.num_rows_device(),
                         kernel_token=nkr.cache_token())
