"""Capacity bucketing: the TPU-specific shape discipline.

Everything under ``jax.jit`` is traced once per distinct input shape. cuDF
allocates exact dynamically-sized buffers per kernel call (the reference
leans on that everywhere); replaying that on XLA would recompile per batch
size. Instead every device column is padded to a *bucketed capacity* — a
small, fixed menu of sizes — and kernels carry the true row count as a
device scalar, masking padding lanes. This bounds compilation to
O(log(max_rows)) variants per kernel and keeps the last-dim/lane layout
friendly (multiples of 128).

The menu is a GEOMETRIC LADDER: rungs grow by a configurable factor
(default 2.0 = the classic power-of-two buckets), each rounded up to a
multiple of the 128-lane width. The serving layer
(service/batching) tunes the factor as the sharing-vs-padding knob:
a coarser ladder (e.g. 4.0) funnels more concurrent tenants onto the
same compiled executables at the cost of more padding lanes; a finer
one (e.g. 1.5) wastes less HBM but fragments the executable space.

Reference contrast: SURVEY.md §7 "Dynamic shapes vs XLA".
"""
from __future__ import annotations

from typing import List

# TPU lane width; also keeps tiny arrays out of degenerate layouts.
MIN_CAPACITY = 128

#: ladder growth factor; 2.0 = power-of-two buckets (the historical
#: behavior and the fast path below). Configured process-wide via
#: set_ladder_growth (rapids.tpu.service.batching.bucketGrowth).
_LADDER_GROWTH = 2.0

#: floor on the growth factor: below ~1.13 the next 128-aligned rung
#: above MIN_CAPACITY would equal the current one and the ladder
#: could stall (rung *must* strictly increase)
_MIN_GROWTH = 1.125


def set_ladder_growth(growth: float) -> float:
    """Install the process-wide ladder growth factor; returns the value
    actually installed (clamped to the stall floor). One ladder per
    process: capacities are compared across every subsystem (concat,
    slice, shuffle), so two coexisting ladders would break the
    all-columns-share-one-capacity batch invariant."""
    global _LADDER_GROWTH
    _LADDER_GROWTH = max(float(growth), _MIN_GROWTH)
    return _LADDER_GROWTH


def ladder_growth() -> float:
    return _LADDER_GROWTH


def _next_rung(cap: int) -> int:
    """Smallest 128-aligned rung strictly above ``cap``."""
    grown = int(cap * _LADDER_GROWTH)
    aligned = -(-grown // MIN_CAPACITY) * MIN_CAPACITY
    return max(aligned, cap + MIN_CAPACITY)


def bucket_capacity(n: int) -> int:
    """Smallest ladder capacity >= n (>= MIN_CAPACITY)."""
    if n <= MIN_CAPACITY:
        return MIN_CAPACITY
    if _LADDER_GROWTH == 2.0:
        # fast path: power-of-two ladder (every rung is 128 * 2^i)
        return 1 << (int(n - 1).bit_length())
    cap = MIN_CAPACITY
    while cap < n:
        cap = _next_rung(cap)
    return cap


def ladder_rungs(max_capacity: int) -> List[int]:
    """Every ladder rung from MIN_CAPACITY up to and including the
    bucket of ``max_capacity`` — the shapes a warmed service
    pre-compiles its stage programs over (service/batching)."""
    top = bucket_capacity(max(max_capacity, 1))
    rungs = [MIN_CAPACITY]
    while rungs[-1] < top:
        if _LADDER_GROWTH == 2.0:
            rungs.append(rungs[-1] * 2)
        else:
            rungs.append(_next_rung(rungs[-1]))
    return rungs


def is_bucketed(capacity: int) -> bool:
    if capacity < MIN_CAPACITY:
        return False
    if _LADDER_GROWTH == 2.0:
        return (capacity & (capacity - 1)) == 0
    return capacity == bucket_capacity(capacity)
