"""Capacity bucketing: the TPU-specific shape discipline.

Everything under ``jax.jit`` is traced once per distinct input shape. cuDF
allocates exact dynamically-sized buffers per kernel call (the reference
leans on that everywhere); replaying that on XLA would recompile per batch
size. Instead every device column is padded to a *bucketed capacity* — a
small, fixed menu of sizes — and kernels carry the true row count as a
device scalar, masking padding lanes. This bounds compilation to
O(log(max_rows)) variants per kernel and keeps the last-dim/lane layout
friendly (multiples of 128).

Reference contrast: SURVEY.md §7 "Dynamic shapes vs XLA".
"""
from __future__ import annotations

# TPU lane width; also keeps tiny arrays out of degenerate layouts.
MIN_CAPACITY = 128


def bucket_capacity(n: int) -> int:
    """Smallest power-of-two capacity >= n (>= MIN_CAPACITY)."""
    if n <= MIN_CAPACITY:
        return MIN_CAPACITY
    return 1 << (int(n - 1).bit_length())


def is_bucketed(capacity: int) -> bool:
    return capacity >= MIN_CAPACITY and (capacity & (capacity - 1)) == 0
