"""TPC-H-like table generators (the CSV->parquet converter role of the
reference's integration_tests tpch/ConvertFiles, but generated directly:
no dbgen in the image). Row counts scale with ``sf`` like TPC-H
(lineitem ~ 6M rows/SF)."""
from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

EPOCH_1992 = (np.datetime64("1992-01-01") -
              np.datetime64("1970-01-01")).astype(int)

RETURN_FLAGS = np.array(["A", "N", "R"], dtype=object)
LINE_STATUS = np.array(["F", "O"], dtype=object)
SEGMENTS = np.array(["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD",
                     "MACHINERY"], dtype=object)
PRIORITIES = np.array(["1-URGENT", "2-HIGH", "3-MEDIUM",
                       "4-NOT SPECIFIED", "5-LOW"], dtype=object)
SHIP_MODES = np.array(["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP",
                       "TRUCK"], dtype=object)
NATIONS = np.array(
    ["ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "EGYPT", "ETHIOPIA",
     "FRANCE", "GERMANY", "INDIA", "INDONESIA", "IRAN", "IRAQ", "JAPAN",
     "JORDAN", "KENYA", "MOROCCO", "MOZAMBIQUE", "PERU", "CHINA",
     "ROMANIA", "SAUDI ARABIA", "VIETNAM", "RUSSIA", "UNITED KINGDOM",
     "UNITED STATES"], dtype=object)
# TPC-H nation -> region mapping (nation.tbl column 2)
NATION_REGION = np.array([0, 1, 1, 1, 4, 0, 3, 3, 2, 2, 4, 4, 2, 4, 0, 0,
                          0, 1, 2, 3, 4, 2, 3, 3, 1], dtype=np.int64)
REGIONS = np.array(["AFRICA", "AMERICA", "ASIA", "EUROPE",
                    "MIDDLE EAST"], dtype=object)
P_TYPES_1 = np.array(["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY",
                      "PROMO"], dtype=object)
P_TYPES_2 = np.array(["ANODIZED", "BURNISHED", "PLATED", "POLISHED",
                      "BRUSHED"], dtype=object)
P_TYPES_3 = np.array(["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"],
                     dtype=object)
P_CONTAINERS_1 = np.array(["SM", "MED", "LG", "JUMBO", "WRAP"],
                          dtype=object)
P_CONTAINERS_2 = np.array(["CASE", "BOX", "BAG", "JAR", "PKG", "PACK",
                           "CAN", "DRUM"], dtype=object)


def _dates(rng, n, lo_year=1992, hi_year=1998):
    lo = (np.datetime64(f"{lo_year}-01-01") -
          np.datetime64("1970-01-01")).astype(int)
    hi = (np.datetime64(f"{hi_year}-12-31") -
          np.datetime64("1970-01-01")).astype(int)
    days = rng.integers(lo, hi + 1, n)
    return days.astype("datetime64[D]")


#: hot ranks of the skewed generator: rank j (1-based) draws a
#: ``skew / j**2`` fraction of lineitem rows onto one orderkey — a
#: truncated Zipf(s=2) head over real o_orderkey values (multiples of
#: 4), so skewed joins still match orders rows
SKEW_RANKS = 4


def _skewed_orderkeys(rng, orderkey: np.ndarray, skew: float
                      ) -> np.ndarray:
    """Overwrite a ``skew/j**2`` fraction of rows per hot rank j with
    the key ``4*j``; rank 1 carries exactly ``skew`` of all rows (the
    aqe_check fence: --skew 0.5 puts half of lineitem on one key)."""
    n = len(orderkey)
    u = rng.random(n)
    lo = 0.0
    out = orderkey.copy()
    for j in range(1, SKEW_RANKS + 1):
        hi = lo + skew / j ** 2
        out[(u >= lo) & (u < hi)] = 4 * j
        lo = hi
    return out


def gen_lineitem(sf: float, seed: int = 11, skew: float = 0.0
                 ) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(6_000_000 * sf), 100)
    orderkey = rng.integers(1, max(int(1_500_000 * sf), 25) * 4, n)
    if skew:
        # cap so the rank fractions sum below 1 (sum(1/j^2) < 1.645)
        orderkey = _skewed_orderkeys(rng, orderkey, min(skew, 0.6))
    shipdate = _dates(rng, n)
    commit_delta = rng.integers(-30, 61, n)
    receipt_delta = rng.integers(1, 31, n)
    return pa.table({
        "l_orderkey": orderkey.astype(np.int64),
        "l_partkey": rng.integers(1, max(int(200_000 * sf), 10), n
                                  ).astype(np.int64),
        "l_suppkey": rng.integers(1, max(int(10_000 * sf), 5), n
                                  ).astype(np.int64),
        "l_quantity": rng.integers(1, 51, n).astype(np.float64),
        "l_extendedprice": np.round(rng.random(n) * 100_000 + 900, 2),
        "l_discount": np.round(rng.integers(0, 11, n) / 100.0, 2),
        "l_tax": np.round(rng.integers(0, 9, n) / 100.0, 2),
        "l_returnflag": RETURN_FLAGS[rng.integers(0, 3, n)],
        "l_linestatus": LINE_STATUS[rng.integers(0, 2, n)],
        "l_shipdate": shipdate,
        "l_commitdate": shipdate + commit_delta,
        "l_receiptdate": shipdate + receipt_delta,
        "l_shipmode": SHIP_MODES[rng.integers(0, 7, n)],
        "l_shipinstruct": np.array(
            ["DELIVER IN PERSON", "COLLECT COD", "NONE",
             "TAKE BACK RETURN"], dtype=object)[rng.integers(0, 4, n)],
    })


def gen_orders(sf: float, seed: int = 12) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(1_500_000 * sf), 25)
    return pa.table({
        "o_orderkey": np.arange(1, n + 1, dtype=np.int64) * 4,
        "o_custkey": rng.integers(1, max(int(150_000 * sf), 10), n
                                  ).astype(np.int64),
        "o_totalprice": np.round(rng.random(n) * 400_000 + 800, 2),
        "o_orderdate": _dates(rng, n),
        "o_orderpriority": PRIORITIES[rng.integers(0, 5, n)],
        "o_orderstatus": np.array(["F", "O", "P"], dtype=object)[
            rng.integers(0, 3, n)],
        "o_shippriority": np.zeros(n, dtype=np.int32),
    })


def gen_customer(sf: float, seed: int = 13) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(150_000 * sf), 10)
    return pa.table({
        "c_custkey": np.arange(1, n + 1, dtype=np.int64),
        "c_mktsegment": SEGMENTS[rng.integers(0, 5, n)],
        "c_acctbal": np.round(rng.random(n) * 11_000 - 1_000, 2),
        "c_nationkey": rng.integers(0, 25, n).astype(np.int64),
        "c_name": np.array([f"Customer#{i:09d}" for i in range(1, n + 1)],
                           dtype=object),
        "c_phone": np.array(
            [f"{rng.integers(10, 35)}-{i % 900 + 100}-{i % 9000 + 1000}"
             for i in range(n)], dtype=object),
    })


def gen_supplier(sf: float, seed: int = 14) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(10_000 * sf), 5)
    return pa.table({
        "s_suppkey": np.arange(1, n + 1, dtype=np.int64),
        "s_nationkey": rng.integers(0, 25, n).astype(np.int64),
        "s_acctbal": np.round(rng.random(n) * 11_000 - 1_000, 2),
    })


def gen_nation(sf: float, seed: int = 15) -> pa.Table:
    return pa.table({
        "n_nationkey": np.arange(25, dtype=np.int64),
        "n_name": NATIONS,
        "n_regionkey": NATION_REGION,
    })


def gen_region(sf: float, seed: int = 16) -> pa.Table:
    return pa.table({
        "r_regionkey": np.arange(5, dtype=np.int64),
        "r_name": REGIONS,
    })


def gen_part(sf: float, seed: int = 17) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(200_000 * sf), 10)
    t1 = P_TYPES_1[rng.integers(0, 6, n)]
    t2 = P_TYPES_2[rng.integers(0, 5, n)]
    t3 = P_TYPES_3[rng.integers(0, 5, n)]
    c1 = P_CONTAINERS_1[rng.integers(0, 5, n)]
    c2 = P_CONTAINERS_2[rng.integers(0, 8, n)]
    return pa.table({
        "p_partkey": np.arange(1, n + 1, dtype=np.int64),
        "p_brand": np.array(
            [f"Brand#{b}" for b in rng.integers(11, 56, n)], dtype=object),
        "p_type": np.array([f"{a} {b} {c}" for a, b, c in
                            zip(t1, t2, t3)], dtype=object),
        "p_size": rng.integers(1, 51, n).astype(np.int32),
        "p_container": np.array([f"{a} {b}" for a, b in zip(c1, c2)],
                                dtype=object),
    })


def gen_partsupp(sf: float, seed: int = 18) -> pa.Table:
    rng = np.random.default_rng(seed)
    n_part = max(int(200_000 * sf), 10)
    n_supp = max(int(10_000 * sf), 5)
    # 4 suppliers per part (TPC-H shape)
    partkey = np.repeat(np.arange(1, n_part + 1, dtype=np.int64), 4)
    suppkey = rng.integers(1, n_supp + 1, n_part * 4).astype(np.int64)
    return pa.table({
        "ps_partkey": partkey,
        "ps_suppkey": suppkey,
        "ps_availqty": rng.integers(1, 10_000, n_part * 4
                                    ).astype(np.int32),
        "ps_supplycost": np.round(rng.random(n_part * 4) * 1_000 + 1, 2),
    })


GENERATORS = {
    "lineitem": gen_lineitem,
    "orders": gen_orders,
    "customer": gen_customer,
    "supplier": gen_supplier,
    "nation": gen_nation,
    "region": gen_region,
    "part": gen_part,
    "partsupp": gen_partsupp,
}


def write_tables(data_dir: str, sf: float, tables=None,
                 files_per_table: int = 4, skew: float = 0.0) -> None:
    """Generate and write parquet (multi-file: scan splits become TPU scan
    partitions, like the reference's multi-file parquet layout).
    ``skew`` > 0 concentrates lineitem's l_orderkey onto a few hot keys
    (see :func:`_skewed_orderkeys`); other tables are unaffected."""
    os.makedirs(data_dir, exist_ok=True)
    for name in tables or GENERATORS:
        if name == "lineitem" and skew:
            table = gen_lineitem(sf, skew=skew)
        else:
            table = GENERATORS[name](sf)
        tdir = os.path.join(data_dir, name)
        os.makedirs(tdir, exist_ok=True)
        n = table.num_rows
        per = -(-n // files_per_table)
        for i in range(files_per_table):
            chunk = table.slice(i * per, per)
            if chunk.num_rows:
                pq.write_table(chunk,
                               os.path.join(tdir, f"part-{i:03d}.parquet"))
