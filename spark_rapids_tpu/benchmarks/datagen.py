"""TPC-H-like table generators (the CSV->parquet converter role of the
reference's integration_tests tpch/ConvertFiles, but generated directly:
no dbgen in the image). Row counts scale with ``sf`` like TPC-H
(lineitem ~ 6M rows/SF)."""
from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

EPOCH_1992 = (np.datetime64("1992-01-01") -
              np.datetime64("1970-01-01")).astype(int)

RETURN_FLAGS = np.array(["A", "N", "R"], dtype=object)
LINE_STATUS = np.array(["F", "O"], dtype=object)
SEGMENTS = np.array(["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD",
                     "MACHINERY"], dtype=object)
PRIORITIES = np.array(["1-URGENT", "2-HIGH", "3-MEDIUM",
                       "4-NOT SPECIFIED", "5-LOW"], dtype=object)
SHIP_MODES = np.array(["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP",
                       "TRUCK"], dtype=object)
NATIONS = np.array(
    ["ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "EGYPT", "ETHIOPIA",
     "FRANCE", "GERMANY", "INDIA", "INDONESIA", "IRAN", "IRAQ", "JAPAN",
     "JORDAN", "KENYA", "MOROCCO", "MOZAMBIQUE", "PERU", "CHINA",
     "ROMANIA", "SAUDI ARABIA", "VIETNAM", "RUSSIA", "UNITED KINGDOM",
     "UNITED STATES"], dtype=object)
# TPC-H nation -> region mapping (nation.tbl column 2)
NATION_REGION = np.array([0, 1, 1, 1, 4, 0, 3, 3, 2, 2, 4, 4, 2, 4, 0, 0,
                          0, 1, 2, 3, 4, 2, 3, 3, 1], dtype=np.int64)
REGIONS = np.array(["AFRICA", "AMERICA", "ASIA", "EUROPE",
                    "MIDDLE EAST"], dtype=object)
P_TYPES_1 = np.array(["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY",
                      "PROMO"], dtype=object)
P_TYPES_2 = np.array(["ANODIZED", "BURNISHED", "PLATED", "POLISHED",
                      "BRUSHED"], dtype=object)
P_TYPES_3 = np.array(["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"],
                     dtype=object)
P_CONTAINERS_1 = np.array(["SM", "MED", "LG", "JUMBO", "WRAP"],
                          dtype=object)
P_CONTAINERS_2 = np.array(["CASE", "BOX", "BAG", "JAR", "PKG", "PACK",
                           "CAN", "DRUM"], dtype=object)


def _dates(rng, n, lo_year=1992, hi_year=1998):
    lo = (np.datetime64(f"{lo_year}-01-01") -
          np.datetime64("1970-01-01")).astype(int)
    hi = (np.datetime64(f"{hi_year}-12-31") -
          np.datetime64("1970-01-01")).astype(int)
    days = rng.integers(lo, hi + 1, n)
    return days.astype("datetime64[D]")


#: hot ranks of the skewed generator: rank j (1-based) draws a
#: ``skew / j**2`` fraction of lineitem rows onto one orderkey — a
#: truncated Zipf(s=2) head over real o_orderkey values (multiples of
#: 4), so skewed joins still match orders rows
SKEW_RANKS = 4


def _skewed_orderkeys(rng, orderkey: np.ndarray, skew: float
                      ) -> np.ndarray:
    """Overwrite a ``skew/j**2`` fraction of rows per hot rank j with
    the key ``4*j``; rank 1 carries exactly ``skew`` of all rows (the
    aqe_check fence: --skew 0.5 puts half of lineitem on one key)."""
    n = len(orderkey)
    u = rng.random(n)
    lo = 0.0
    out = orderkey.copy()
    for j in range(1, SKEW_RANKS + 1):
        hi = lo + skew / j ** 2
        out[(u >= lo) & (u < hi)] = 4 * j
        lo = hi
    return out


def _lineitem_chunk(rng, n: int, sf: float, skew: float,
                    date_window=None) -> pa.Table:
    """One lineitem block with the EXACT legacy rng draw order (the
    whole-table generator routes through here, so small scale factors
    stay byte-identical). ``date_window`` = (lo_day, hi_day) epoch-day
    bounds for l_shipdate — the chunked path gives each chunk a
    consecutive window (time-ordered ingest), which is what makes
    row-group shipdate pruning effective on generated data."""
    orderkey = rng.integers(1, max(int(1_500_000 * sf), 25) * 4, n)
    if skew:
        # cap so the rank fractions sum below 1 (sum(1/j^2) < 1.645)
        orderkey = _skewed_orderkeys(rng, orderkey, min(skew, 0.6))
    if date_window is None:
        shipdate = _dates(rng, n)
    else:
        lo, hi = date_window
        shipdate = rng.integers(lo, hi + 1, n).astype("datetime64[D]")
    commit_delta = rng.integers(-30, 61, n)
    receipt_delta = rng.integers(1, 31, n)
    return pa.table({
        "l_orderkey": orderkey.astype(np.int64),
        "l_partkey": rng.integers(1, max(int(200_000 * sf), 10), n
                                  ).astype(np.int64),
        "l_suppkey": rng.integers(1, max(int(10_000 * sf), 5), n
                                  ).astype(np.int64),
        "l_quantity": rng.integers(1, 51, n).astype(np.float64),
        "l_extendedprice": np.round(rng.random(n) * 100_000 + 900, 2),
        "l_discount": np.round(rng.integers(0, 11, n) / 100.0, 2),
        "l_tax": np.round(rng.integers(0, 9, n) / 100.0, 2),
        "l_returnflag": RETURN_FLAGS[rng.integers(0, 3, n)],
        "l_linestatus": LINE_STATUS[rng.integers(0, 2, n)],
        "l_shipdate": shipdate,
        "l_commitdate": shipdate + commit_delta,
        "l_receiptdate": shipdate + receipt_delta,
        "l_shipmode": SHIP_MODES[rng.integers(0, 7, n)],
        "l_shipinstruct": np.array(
            ["DELIVER IN PERSON", "COLLECT COD", "NONE",
             "TAKE BACK RETURN"], dtype=object)[rng.integers(0, 4, n)],
    })


def gen_lineitem(sf: float, seed: int = 11, skew: float = 0.0
                 ) -> pa.Table:
    n = max(int(6_000_000 * sf), 100)
    return _lineitem_chunk(np.random.default_rng(seed), n, sf, skew)


def gen_orders(sf: float, seed: int = 12) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(1_500_000 * sf), 25)
    return pa.table({
        "o_orderkey": np.arange(1, n + 1, dtype=np.int64) * 4,
        "o_custkey": rng.integers(1, max(int(150_000 * sf), 10), n
                                  ).astype(np.int64),
        "o_totalprice": np.round(rng.random(n) * 400_000 + 800, 2),
        "o_orderdate": _dates(rng, n),
        "o_orderpriority": PRIORITIES[rng.integers(0, 5, n)],
        "o_orderstatus": np.array(["F", "O", "P"], dtype=object)[
            rng.integers(0, 3, n)],
        "o_shippriority": np.zeros(n, dtype=np.int32),
    })


def gen_customer(sf: float, seed: int = 13) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(150_000 * sf), 10)
    return pa.table({
        "c_custkey": np.arange(1, n + 1, dtype=np.int64),
        "c_mktsegment": SEGMENTS[rng.integers(0, 5, n)],
        "c_acctbal": np.round(rng.random(n) * 11_000 - 1_000, 2),
        "c_nationkey": rng.integers(0, 25, n).astype(np.int64),
        "c_name": np.array([f"Customer#{i:09d}" for i in range(1, n + 1)],
                           dtype=object),
        "c_phone": np.array(
            [f"{rng.integers(10, 35)}-{i % 900 + 100}-{i % 9000 + 1000}"
             for i in range(n)], dtype=object),
    })


def gen_supplier(sf: float, seed: int = 14) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(10_000 * sf), 5)
    return pa.table({
        "s_suppkey": np.arange(1, n + 1, dtype=np.int64),
        "s_nationkey": rng.integers(0, 25, n).astype(np.int64),
        "s_acctbal": np.round(rng.random(n) * 11_000 - 1_000, 2),
    })


def gen_nation(sf: float, seed: int = 15) -> pa.Table:
    return pa.table({
        "n_nationkey": np.arange(25, dtype=np.int64),
        "n_name": NATIONS,
        "n_regionkey": NATION_REGION,
    })


def gen_region(sf: float, seed: int = 16) -> pa.Table:
    return pa.table({
        "r_regionkey": np.arange(5, dtype=np.int64),
        "r_name": REGIONS,
    })


def gen_part(sf: float, seed: int = 17) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(200_000 * sf), 10)
    t1 = P_TYPES_1[rng.integers(0, 6, n)]
    t2 = P_TYPES_2[rng.integers(0, 5, n)]
    t3 = P_TYPES_3[rng.integers(0, 5, n)]
    c1 = P_CONTAINERS_1[rng.integers(0, 5, n)]
    c2 = P_CONTAINERS_2[rng.integers(0, 8, n)]
    return pa.table({
        "p_partkey": np.arange(1, n + 1, dtype=np.int64),
        "p_brand": np.array(
            [f"Brand#{b}" for b in rng.integers(11, 56, n)], dtype=object),
        "p_type": np.array([f"{a} {b} {c}" for a, b, c in
                            zip(t1, t2, t3)], dtype=object),
        "p_size": rng.integers(1, 51, n).astype(np.int32),
        "p_container": np.array([f"{a} {b}" for a, b in zip(c1, c2)],
                                dtype=object),
    })


def gen_partsupp(sf: float, seed: int = 18) -> pa.Table:
    rng = np.random.default_rng(seed)
    n_part = max(int(200_000 * sf), 10)
    n_supp = max(int(10_000 * sf), 5)
    # 4 suppliers per part (TPC-H shape)
    partkey = np.repeat(np.arange(1, n_part + 1, dtype=np.int64), 4)
    suppkey = rng.integers(1, n_supp + 1, n_part * 4).astype(np.int64)
    return pa.table({
        "ps_partkey": partkey,
        "ps_suppkey": suppkey,
        "ps_availqty": rng.integers(1, 10_000, n_part * 4
                                    ).astype(np.int32),
        "ps_supplycost": np.round(rng.random(n_part * 4) * 1_000 + 1, 2),
    })


GENERATORS = {
    "lineitem": gen_lineitem,
    "orders": gen_orders,
    "customer": gen_customer,
    "supplier": gen_supplier,
    "nation": gen_nation,
    "region": gen_region,
    "part": gen_part,
    "partsupp": gen_partsupp,
}

#: rows one generation chunk materializes at most (~8.4M): large scale
#: factors stream chunk-by-chunk through io/write.write_table_stream
#: instead of building the whole table in host memory (sf100 lineitem
#: is 600M rows — one table would OOM the driver). Tables at or under
#: this take the legacy whole-table path, byte-identical to before.
CHUNK_ROWS = 1 << 23

_SEEDS = {"lineitem": 11, "orders": 12, "customer": 13, "supplier": 14,
          "nation": 15, "region": 16, "part": 17, "partsupp": 18}


def table_rows(name: str, sf: float) -> int:
    """Row count ``name`` generates at ``sf`` (no generation)."""
    return {
        "lineitem": max(int(6_000_000 * sf), 100),
        "orders": max(int(1_500_000 * sf), 25),
        "customer": max(int(150_000 * sf), 10),
        "supplier": max(int(10_000 * sf), 5),
        "nation": 25,
        "region": 5,
        "part": max(int(200_000 * sf), 10),
        "partsupp": max(int(200_000 * sf), 10) * 4,
    }[name]


def _orders_chunk(rng, start, cnt, sf) -> pa.Table:
    return pa.table({
        "o_orderkey": np.arange(start + 1, start + cnt + 1,
                                dtype=np.int64) * 4,
        "o_custkey": rng.integers(1, max(int(150_000 * sf), 10), cnt
                                  ).astype(np.int64),
        "o_totalprice": np.round(rng.random(cnt) * 400_000 + 800, 2),
        "o_orderdate": _dates(rng, cnt),
        "o_orderpriority": PRIORITIES[rng.integers(0, 5, cnt)],
        "o_orderstatus": np.array(["F", "O", "P"], dtype=object)[
            rng.integers(0, 3, cnt)],
        "o_shippriority": np.zeros(cnt, dtype=np.int32),
    })


def _customer_chunk(rng, start, cnt, sf) -> pa.Table:
    return pa.table({
        "c_custkey": np.arange(start + 1, start + cnt + 1,
                               dtype=np.int64),
        "c_mktsegment": SEGMENTS[rng.integers(0, 5, cnt)],
        "c_acctbal": np.round(rng.random(cnt) * 11_000 - 1_000, 2),
        "c_nationkey": rng.integers(0, 25, cnt).astype(np.int64),
        "c_name": np.array([f"Customer#{i:09d}"
                            for i in range(start + 1, start + cnt + 1)],
                           dtype=object),
        "c_phone": np.array(
            [f"{rng.integers(10, 35)}-{i % 900 + 100}-{i % 9000 + 1000}"
             for i in range(start, start + cnt)], dtype=object),
    })


def _supplier_chunk(rng, start, cnt, sf) -> pa.Table:
    return pa.table({
        "s_suppkey": np.arange(start + 1, start + cnt + 1,
                               dtype=np.int64),
        "s_nationkey": rng.integers(0, 25, cnt).astype(np.int64),
        "s_acctbal": np.round(rng.random(cnt) * 11_000 - 1_000, 2),
    })


def _part_chunk(rng, start, cnt, sf) -> pa.Table:
    t1 = P_TYPES_1[rng.integers(0, 6, cnt)]
    t2 = P_TYPES_2[rng.integers(0, 5, cnt)]
    t3 = P_TYPES_3[rng.integers(0, 5, cnt)]
    c1 = P_CONTAINERS_1[rng.integers(0, 5, cnt)]
    c2 = P_CONTAINERS_2[rng.integers(0, 8, cnt)]
    return pa.table({
        "p_partkey": np.arange(start + 1, start + cnt + 1,
                               dtype=np.int64),
        "p_brand": np.array(
            [f"Brand#{b}" for b in rng.integers(11, 56, cnt)],
            dtype=object),
        "p_type": np.array([f"{a} {b} {c}" for a, b, c in
                            zip(t1, t2, t3)], dtype=object),
        "p_size": rng.integers(1, 51, cnt).astype(np.int32),
        "p_container": np.array([f"{a} {b}" for a, b in zip(c1, c2)],
                                dtype=object),
    })


def _partsupp_chunk(rng, start, cnt, sf) -> pa.Table:
    # global row r maps to partkey r//4 + 1 for ANY chunk start — no
    # boundary alignment needed
    n_supp = max(int(10_000 * sf), 5)
    idx = np.arange(start, start + cnt, dtype=np.int64)
    return pa.table({
        "ps_partkey": idx // 4 + 1,
        "ps_suppkey": rng.integers(1, n_supp + 1, cnt).astype(np.int64),
        "ps_availqty": rng.integers(1, 10_000, cnt).astype(np.int32),
        "ps_supplycost": np.round(rng.random(cnt) * 1_000 + 1, 2),
    })


_EPOCH = np.datetime64("1970-01-01")
_CHUNK_FNS = {
    "orders": _orders_chunk,
    "customer": _customer_chunk,
    "supplier": _supplier_chunk,
    "part": _part_chunk,
    "partsupp": _partsupp_chunk,
}


def gen_table_chunks(name: str, sf: float, skew: float = 0.0,
                     chunk_rows: int = 0):
    """Yield ``name``'s rows as bounded-size arrow tables. At or under
    ``chunk_rows`` this is exactly one legacy whole-table chunk; above
    it, per-chunk rngs seeded ``[seed, chunk_index]`` keep generation
    deterministic without a single giant draw. Chunked lineitem gives
    each chunk a consecutive l_shipdate window (time-ordered ingest,
    like real fact tables land) so footer-stat pruning on shipdate has
    real row-group locality to exploit."""
    chunk_rows = chunk_rows or CHUNK_ROWS  # module global: patchable
    n = table_rows(name, sf)
    seed = _SEEDS[name]
    if n <= chunk_rows or name not in ("lineitem", *_CHUNK_FNS):
        if name == "lineitem" and skew:
            yield gen_lineitem(sf, skew=skew)
        else:
            yield GENERATORS[name](sf)
        return
    nchunks = -(-n // chunk_rows)
    lo = (np.datetime64("1992-01-01") - _EPOCH).astype(int)
    hi = (np.datetime64("1998-12-31") - _EPOCH).astype(int)
    span = hi - lo + 1
    start = 0
    for ci in range(nchunks):
        cnt = min(chunk_rows, n - start)
        rng = np.random.default_rng([seed, ci])
        if name == "lineitem":
            window = (lo + (span * ci) // nchunks,
                      lo + (span * (ci + 1)) // nchunks - 1)
            yield _lineitem_chunk(rng, cnt, sf, skew, window)
        else:
            yield _CHUNK_FNS[name](rng, start, cnt, sf)
        start += cnt


def write_tables(data_dir: str, sf: float, tables=None,
                 files_per_table: int = 4, skew: float = 0.0) -> None:
    """Generate and write parquet (multi-file: scan splits become TPU scan
    partitions, like the reference's multi-file parquet layout).
    ``skew`` > 0 concentrates lineitem's l_orderkey onto a few hot keys
    (see :func:`_skewed_orderkeys`); other tables are unaffected.

    Tables above CHUNK_ROWS stream chunk-by-chunk through
    io/write.write_table_stream — peak host memory is one chunk, so
    sf100 generation cannot OOM the driver."""
    import itertools

    from spark_rapids_tpu.io.write import write_table_stream

    os.makedirs(data_dir, exist_ok=True)
    for name in tables or GENERATORS:
        tdir = os.path.join(data_dir, name)
        os.makedirs(tdir, exist_ok=True)
        n = table_rows(name, sf)
        per = -(-n // files_per_table)
        if n <= CHUNK_ROWS:
            # legacy whole-table path, byte-identical for small sf
            if name == "lineitem" and skew:
                table = gen_lineitem(sf, skew=skew)
            else:
                table = GENERATORS[name](sf)
            for i in range(files_per_table):
                chunk = table.slice(i * per, per)
                if chunk.num_rows:
                    pq.write_table(chunk, os.path.join(
                        tdir, f"part-{i:03d}.parquet"))
            continue

        def pieces():
            """(file_index, sub-table) in row order: chunks are cut at
            the same contiguous per-file boundaries the legacy slicing
            used, without materializing the table."""
            row = 0
            for t in gen_table_chunks(name, sf, skew=skew):
                off = 0
                while off < t.num_rows:
                    fi = row // per
                    take = min(per - row % per, t.num_rows - off)
                    yield fi, t.slice(off, take)
                    off += take
                    row += take

        for fi, group in itertools.groupby(pieces(), key=lambda p: p[0]):
            write_table_stream(
                (t for _, t in group),
                os.path.join(tdir, f"part-{fi:03d}.parquet"))
