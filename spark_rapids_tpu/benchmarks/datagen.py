"""TPC-H-like table generators (the CSV->parquet converter role of the
reference's integration_tests tpch/ConvertFiles, but generated directly:
no dbgen in the image). Row counts scale with ``sf`` like TPC-H
(lineitem ~ 6M rows/SF)."""
from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

EPOCH_1992 = (np.datetime64("1992-01-01") -
              np.datetime64("1970-01-01")).astype(int)

RETURN_FLAGS = np.array(["A", "N", "R"], dtype=object)
LINE_STATUS = np.array(["F", "O"], dtype=object)
SEGMENTS = np.array(["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD",
                     "MACHINERY"], dtype=object)
PRIORITIES = np.array(["1-URGENT", "2-HIGH", "3-MEDIUM",
                       "4-NOT SPECIFIED", "5-LOW"], dtype=object)


def _dates(rng, n, lo_year=1992, hi_year=1998):
    lo = (np.datetime64(f"{lo_year}-01-01") -
          np.datetime64("1970-01-01")).astype(int)
    hi = (np.datetime64(f"{hi_year}-12-31") -
          np.datetime64("1970-01-01")).astype(int)
    days = rng.integers(lo, hi + 1, n)
    return days.astype("datetime64[D]")


def gen_lineitem(sf: float, seed: int = 11) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(6_000_000 * sf), 100)
    orderkey = rng.integers(1, max(int(1_500_000 * sf), 25) * 4, n)
    return pa.table({
        "l_orderkey": orderkey.astype(np.int64),
        "l_partkey": rng.integers(1, max(int(200_000 * sf), 10), n
                                  ).astype(np.int64),
        "l_suppkey": rng.integers(1, max(int(10_000 * sf), 5), n
                                  ).astype(np.int64),
        "l_quantity": rng.integers(1, 51, n).astype(np.float64),
        "l_extendedprice": np.round(rng.random(n) * 100_000 + 900, 2),
        "l_discount": np.round(rng.integers(0, 11, n) / 100.0, 2),
        "l_tax": np.round(rng.integers(0, 9, n) / 100.0, 2),
        "l_returnflag": RETURN_FLAGS[rng.integers(0, 3, n)],
        "l_linestatus": LINE_STATUS[rng.integers(0, 2, n)],
        "l_shipdate": _dates(rng, n),
    })


def gen_orders(sf: float, seed: int = 12) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(1_500_000 * sf), 25)
    return pa.table({
        "o_orderkey": np.arange(1, n + 1, dtype=np.int64) * 4,
        "o_custkey": rng.integers(1, max(int(150_000 * sf), 10), n
                                  ).astype(np.int64),
        "o_totalprice": np.round(rng.random(n) * 400_000 + 800, 2),
        "o_orderdate": _dates(rng, n),
        "o_orderpriority": PRIORITIES[rng.integers(0, 5, n)],
        "o_shippriority": np.zeros(n, dtype=np.int32),
    })


def gen_customer(sf: float, seed: int = 13) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(150_000 * sf), 10)
    return pa.table({
        "c_custkey": np.arange(1, n + 1, dtype=np.int64),
        "c_mktsegment": SEGMENTS[rng.integers(0, 5, n)],
        "c_acctbal": np.round(rng.random(n) * 11_000 - 1_000, 2),
    })


GENERATORS = {
    "lineitem": gen_lineitem,
    "orders": gen_orders,
    "customer": gen_customer,
}


def write_tables(data_dir: str, sf: float, tables=None,
                 files_per_table: int = 4) -> None:
    """Generate and write parquet (multi-file: scan splits become TPU scan
    partitions, like the reference's multi-file parquet layout)."""
    os.makedirs(data_dir, exist_ok=True)
    for name in tables or GENERATORS:
        table = GENERATORS[name](sf)
        tdir = os.path.join(data_dir, name)
        os.makedirs(tdir, exist_ok=True)
        n = table.num_rows
        per = -(-n // files_per_table)
        for i in range(files_per_table):
            chunk = table.slice(i * per, per)
            if chunk.num_rows:
                pq.write_table(chunk,
                               os.path.join(tdir, f"part-{i:03d}.parquet"))
