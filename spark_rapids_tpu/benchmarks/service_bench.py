"""Concurrent-service benchmark: replay N TPC-H instances through the
QueryService and report queue-time vs run-time (runner-JSON shaped).

The single-query runner measures how fast ONE query goes; this measures
how the SERVICE multiplexes many — the numbers that matter for the
ROADMAP's serve-heavy-traffic goal: per-query queue time vs run time,
shed counts under a bounded queue, and the cross-query compile-cache
hit rate (instance 2..N of the same shape should be ~all hits).

    python -m spark_rapids_tpu.benchmarks.service_bench \
        --queries 8 --mix tpch_q1,tpch_q6 --tenants 2 --sf 0.01 \
        --data-dir /tmp/rapids_tpu_tpch --output service.json
"""
from __future__ import annotations

import argparse
import json
import time
from typing import List, Optional

from spark_rapids_tpu.config import RapidsConf


def run_service_bench(data_dir: str, sf: float, queries: int = 8,
                      mix: Optional[List[str]] = None, tenants: int = 2,
                      conf: Optional[RapidsConf] = None) -> dict:
    """Submit ``queries`` instances round-robin over ``mix`` plans and
    ``tenants`` submitter keys; returns the runner-style JSON record
    with per-query queue/run splits and the ServiceStats snapshot."""
    from spark_rapids_tpu.benchmarks.runner import (ALL_BENCHMARKS,
                                                    BenchmarkRunner)
    from spark_rapids_tpu.service import QueryService, ServiceOverloaded

    mix = mix or ["tpch_q1", "tpch_q6"]
    conf = conf or RapidsConf()
    runner = BenchmarkRunner(data_dir, sf, conf=conf)
    for name in dict.fromkeys(mix):  # every family in the mix
        runner.ensure_data(name)

    service = QueryService(conf)
    t0 = time.perf_counter()
    handles = []
    shed = 0
    for i in range(queries):
        name = mix[i % len(mix)]
        plan = ALL_BENCHMARKS[name](data_dir)  # fresh plan per instance
        try:
            h = service.submit(plan, tenant=f"tenant{i % tenants}")
            handles.append((name, h))
        except ServiceOverloaded:  # expected under tiny queue limits
            shed += 1
    per_query = []
    for name, h in handles:
        df = h.result(timeout=600)
        info = h.info()
        per_query.append({
            "benchmark": name,
            "tenant": info["tenant"],
            "rows_returned": len(df),
            "queue_time_s": round(info["queue_time_s"] or 0.0, 4),
            "run_time_s": round(info["run_time_s"] or 0.0, 4),
            "slices": info["slices_done"],
        })
    wall = time.perf_counter() - t0
    stats = service.stats()
    service.shutdown()
    qt = [q["queue_time_s"] for q in per_query]
    rt = [q["run_time_s"] for q in per_query]
    return {
        "benchmark": "service_bench",
        "scale_factor": sf,
        "env": BenchmarkRunner._env(),
        "concurrent_queries": queries,
        "mix": mix,
        "tenants": tenants,
        "wall_time_sec": round(wall, 3),
        "queue_time_sec": {"max": max(qt, default=0.0),
                           "mean": round(sum(qt) / len(qt), 4)
                           if qt else 0.0},
        "run_time_sec": {"max": max(rt, default=0.0),
                         "mean": round(sum(rt) / len(rt), 4)
                         if rt else 0.0},
        "per_query": per_query,
        "shed_at_submit": shed,
        "service_stats": stats.to_dict(),
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--queries", type=int, default=8)
    p.add_argument("--mix", default="tpch_q1,tpch_q6",
                   help="comma-separated benchmark names to cycle")
    p.add_argument("--tenants", type=int, default=2)
    p.add_argument("--sf", type=float, default=0.01)
    p.add_argument("--data-dir", default="/tmp/rapids_tpu_tpch")
    p.add_argument("--output", default=None)
    args = p.parse_args(argv)
    result = run_service_bench(args.data_dir, args.sf,
                               queries=args.queries,
                               mix=args.mix.split(","),
                               tenants=args.tenants)
    text = json.dumps(result, indent=2)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    print(text)


if __name__ == "__main__":
    main()
