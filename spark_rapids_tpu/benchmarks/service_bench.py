"""Concurrent-service benchmark: closed-loop replay AND open-loop
sustained-load SLO sweeps over the QueryService (runner-JSON shaped).

The single-query runner measures how fast ONE query goes; this measures
how the SERVICE multiplexes many — the numbers that matter for the
ROADMAP's serve-heavy-traffic goal. Two modes:

- **closed loop** (default): submit N instances, wait for all. Reports
  per-query queue-time vs run-time splits, shed counts under a bounded
  queue, and the cross-query compile-cache hit rate (instance 2..N of
  the same shape should be ~all hits).
- **open loop** (``--open-loop``): Poisson arrivals at each offered
  QPS in ``--qps`` — arrivals do NOT slow down because the service is
  busy, which is what makes the p50/p99 queue+run latency and shed
  rate at each rate a real SLO measurement (service/batching/slo).
  Emits an ``SLO_r*``-style block with the ROADMAP item-4 criterion
  (p99 total latency within ``--ratio`` x serial single-query time)
  evaluated at the highest sustained rate.

    python -m spark_rapids_tpu.benchmarks.service_bench \
        --queries 8 --mix tpch_q1,tpch_q6 --tenants 2 --sf 0.01 \
        --data-dir /tmp/rapids_tpu_tpch --output service.json

    python -m spark_rapids_tpu.benchmarks.service_bench --open-loop \
        --qps 1,2,4 --queries 16 --warmup --sf 0.01
"""
from __future__ import annotations

import argparse
import json
import time
from typing import List, Optional

from spark_rapids_tpu.config import RapidsConf


def _serial_single_query_s(runner, mix: List[str],
                           data_dir: str) -> dict:
    """Warm serial reference per template (second run of two — the
    first pays tracing/compiles), plus the max across the mix: the
    denominator of the ratio-based SLO criterion."""
    from spark_rapids_tpu.execs.base import collect
    from spark_rapids_tpu.plan.overrides import apply_overrides
    from spark_rapids_tpu.benchmarks.runner import ALL_BENCHMARKS

    per = {}
    for name in dict.fromkeys(mix):
        dt = 0.0
        for _ in range(2):
            plan = ALL_BENCHMARKS[name](data_dir)
            t0 = time.perf_counter()
            collect(apply_overrides(plan, runner.conf))
            dt = time.perf_counter() - t0
        per[name] = round(dt, 4)
    return {"per_template": per, "max_s": max(per.values())}


def run_service_bench(data_dir: str, sf: float, queries: int = 8,
                      mix: Optional[List[str]] = None, tenants: int = 2,
                      conf: Optional[RapidsConf] = None,
                      warmup: bool = False) -> dict:
    """Closed loop: submit ``queries`` instances round-robin over
    ``mix`` plans and ``tenants`` submitter keys; returns the
    runner-style JSON record with per-query queue/run splits, latency
    percentiles, and the ServiceStats snapshot."""
    from spark_rapids_tpu.benchmarks.runner import (ALL_BENCHMARKS,
                                                    BenchmarkRunner)
    from spark_rapids_tpu.service import QueryService, ServiceOverloaded
    from spark_rapids_tpu.service.batching import slo

    mix = mix or ["tpch_q1", "tpch_q6"]
    conf = conf or RapidsConf()
    runner = BenchmarkRunner(data_dir, sf, conf=conf)
    for name in dict.fromkeys(mix):  # every family in the mix
        runner.ensure_data(name)

    service = QueryService(conf)
    warmup_report = None
    if warmup:
        for name in dict.fromkeys(mix):
            service.register_template(ALL_BENCHMARKS[name](data_dir),
                                      name)
        warmup_report = service.warmup()
    t0 = time.perf_counter()
    handles = []
    shed = 0
    for i in range(queries):
        name = mix[i % len(mix)]
        plan = ALL_BENCHMARKS[name](data_dir)  # fresh plan per instance
        try:
            h = service.submit(plan, tenant=f"tenant{i % tenants}")
            handles.append((name, h))
        except ServiceOverloaded:  # expected under tiny queue limits
            shed += 1
    per_query = []
    for name, h in handles:
        df = h.result(timeout=600)
        info = h.info()
        per_query.append({
            "benchmark": name,
            "tenant": info["tenant"],
            "rows_returned": len(df),
            "queue_time_s": round(info["queue_time_s"] or 0.0, 4),
            "run_time_s": round(info["run_time_s"] or 0.0, 4),
            "slices": info["slices_done"],
        })
    wall = time.perf_counter() - t0
    stats = service.stats()
    service.shutdown()
    qt = [q["queue_time_s"] for q in per_query]
    rt = [q["run_time_s"] for q in per_query]
    tot = [a + b for a, b in zip(qt, rt)]
    out = {
        "benchmark": "service_bench",
        "scale_factor": sf,
        "env": BenchmarkRunner._env(),
        "concurrent_queries": queries,
        "mix": mix,
        "tenants": tenants,
        "wall_time_sec": round(wall, 3),
        "queue_time_sec": {"max": max(qt, default=0.0),
                           "mean": round(sum(qt) / len(qt), 4)
                           if qt else 0.0,
                           "p50": round(slo.percentile(qt, 50), 4),
                           "p99": round(slo.percentile(qt, 99), 4)},
        "run_time_sec": {"max": max(rt, default=0.0),
                         "mean": round(sum(rt) / len(rt), 4)
                         if rt else 0.0,
                         "p50": round(slo.percentile(rt, 50), 4),
                         "p99": round(slo.percentile(rt, 99), 4)},
        "total_time_sec": {"p50": round(slo.percentile(tot, 50), 4),
                           "p99": round(slo.percentile(tot, 99), 4)},
        "per_query": per_query,
        "shed_at_submit": shed,
        "service_stats": stats.to_dict(),
    }
    if warmup_report is not None:
        out["warmup"] = warmup_report
    return out


def run_slo_sweep(data_dir: str, sf: float,
                  qps_list: List[float], queries_per_rate: int = 16,
                  mix: Optional[List[str]] = None, tenants: int = 4,
                  conf: Optional[RapidsConf] = None,
                  warmup: bool = True, ratio: float = 3.0,
                  seed: int = 7) -> dict:
    """Open-loop offered-QPS sweep: Poisson arrivals at each rate in
    ``qps_list`` (``queries_per_rate`` fresh instances each), through
    ONE warmed service. Returns the ``SLO_r*``-style record."""
    from spark_rapids_tpu.benchmarks.runner import (ALL_BENCHMARKS,
                                                    BenchmarkRunner)
    from spark_rapids_tpu.service import QueryService
    from spark_rapids_tpu.service.batching import slo

    mix = mix or ["tpch_q1", "tpch_q6"]
    conf = conf or RapidsConf()
    runner = BenchmarkRunner(data_dir, sf, conf=conf)
    for name in dict.fromkeys(mix):
        runner.ensure_data(name)
    serial = _serial_single_query_s(runner, mix, data_dir)

    service = QueryService(conf)
    warmup_report = None
    if warmup:
        for name in dict.fromkeys(mix):
            service.register_template(ALL_BENCHMARKS[name](data_dir),
                                      name)
        warmup_report = service.warmup()

    def make_query(i: int):
        return ALL_BENCHMARKS[mix[i % len(mix)]](data_dir)

    sweep = []
    for qps in qps_list:
        sweep.append(slo.run_open_loop(
            service, make_query, qps, queries_per_rate,
            tenants=tenants, seed=seed))
    stats = service.stats()
    service.shutdown()
    out = {
        "benchmark": "service_slo",
        "scale_factor": sf,
        "env": BenchmarkRunner._env(),
        "mix": mix,
        "tenants": tenants,
        "queries_per_rate": queries_per_rate,
        "serial": serial,
        "slo": slo.slo_block(sweep, serial["max_s"], ratio=ratio),
        "service_stats": stats.to_dict(),
    }
    if warmup_report is not None:
        out["warmup"] = warmup_report
    return out


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--queries", type=int, default=8,
                   help="closed loop: total; open loop: per rate")
    p.add_argument("--mix", default="tpch_q1,tpch_q6",
                   help="comma-separated benchmark names to cycle")
    p.add_argument("--tenants", type=int, default=2)
    p.add_argument("--sf", type=float, default=0.01)
    p.add_argument("--data-dir", default="/tmp/rapids_tpu_tpch")
    p.add_argument("--output", default=None)
    p.add_argument("--warmup", action="store_true",
                   help="register the mix as templates and AOT-warm "
                        "before measuring")
    p.add_argument("--open-loop", action="store_true",
                   help="Poisson-arrival offered-QPS sweep instead of "
                        "closed-loop replay")
    p.add_argument("--qps", default="1,2,4",
                   help="open loop: comma-separated offered rates")
    p.add_argument("--ratio", type=float, default=3.0,
                   help="open loop: SLO criterion = p99 total within "
                        "ratio x serial single-query time")
    p.add_argument("--seed", type=int, default=7)
    args = p.parse_args(argv)
    if args.open_loop:
        result = run_slo_sweep(
            args.data_dir, args.sf,
            qps_list=[float(q) for q in args.qps.split(",")],
            queries_per_rate=args.queries, mix=args.mix.split(","),
            tenants=args.tenants, warmup=args.warmup,
            ratio=args.ratio, seed=args.seed)
    else:
        result = run_service_bench(args.data_dir, args.sf,
                                   queries=args.queries,
                                   mix=args.mix.split(","),
                                   tenants=args.tenants,
                                   warmup=args.warmup)
    text = json.dumps(result, indent=2)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    print(text)


if __name__ == "__main__":
    main()
