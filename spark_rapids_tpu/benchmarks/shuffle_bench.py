"""Wide-shuffle benchmark (BASELINE config #4): repartition + groupBy over
a device mesh, exercising the fused all_to_all shuffle/aggregate step.

The reference's analogous measurement is shuffle GB/s between executor
GPUs over UCX (SURVEY.md §2.8); here the transport is XLA's all_to_all
over ICI inside one compiled program, so the benchmark times the whole
exchange+aggregate step and reports rows/s and shuffled GB/s per chip.

    python -m spark_rapids_tpu.benchmarks.shuffle_bench \
        --rows 4000000 --keys 65536 --devices 8 --iterations 3
"""
from __future__ import annotations

import argparse
import json
import time

import numpy as np


def run(rows: int, n_keys: int, n_devices: int = 0,
        iterations: int = 3, warmup: int = 1, seed: int = 7) -> dict:
    import jax

    import spark_rapids_tpu  # noqa: F401  (x64 on)
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.ops.groupby import AggSpec
    from spark_rapids_tpu.parallel import (
        DistributedGroupByStep,
        data_mesh,
        distributed_batch_from_host,
        gather_distributed_result,
    )

    if n_devices:
        from spark_rapids_tpu.parallel.mesh import force_cpu_mesh

        force_cpu_mesh(n_devices)
    n_dev = n_devices or len(jax.devices())
    mesh = data_mesh(n_dev)

    rng = np.random.default_rng(seed)
    keys = rng.integers(0, n_keys, rows).astype(np.int64)
    vals = rng.random(rows)
    dtypes = [dt.INT64, dt.FLOAT64]
    datas, valids, counts, _cap = distributed_batch_from_host(
        mesh, [keys, vals], dtypes)
    step = DistributedGroupByStep(
        mesh, dtypes, [0],
        [AggSpec("sum", 1), AggSpec("count_star")])

    times = []
    for i in range(warmup + iterations):
        t0 = time.perf_counter()
        out_d, out_v, ng = step(datas, valids, counts)
        jax.block_until_ready(out_d)
        if i >= warmup:
            times.append(time.perf_counter() - t0)

    # every row carries both columns' payload + validity across the wire
    # at most once (hash routing): bytes ~ rows * (8 + 8 + 2)
    payload_bytes = rows * (8 + 8 + 2)
    best = min(times)
    result = {
        "benchmark": "wide_shuffle",
        "rows": rows,
        "distinct_keys": n_keys,
        "devices": n_dev,
        "backend": jax.devices()[0].platform,
        "times_sec": times,
        "min_time_sec": best,
        "rows_per_sec": rows / best,
        "shuffle_gb_per_sec_per_chip": payload_bytes / best / 1e9 / n_dev,
    }
    res = gather_distributed_result(out_d, out_v, ng,
                                    step.output_dtypes(), n_dev)
    result["groups"] = res.realized_num_rows()
    # correctness pin: global sum survives the exchange exactly
    df = res.to_pandas()
    result["sum_ok"] = bool(abs(float(df.iloc[:, 1].sum()) -
                                float(vals.sum())) < 1e-6 * rows)
    return result


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--rows", type=int, default=4_000_000)
    p.add_argument("--keys", type=int, default=65_536)
    p.add_argument("--devices", type=int, default=0,
                   help="0 = all available devices; N forces a virtual "
                        "N-device CPU mesh when fewer are attached")
    p.add_argument("--iterations", type=int, default=3)
    p.add_argument("--warmup", type=int, default=1)
    args = p.parse_args(argv)
    print(json.dumps(run(args.rows, args.keys, args.devices,
                         args.iterations, args.warmup)))


if __name__ == "__main__":
    main()
