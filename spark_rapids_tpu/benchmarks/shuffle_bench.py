"""Wide-shuffle benchmark (BASELINE config #4): repartition + groupBy over
a device mesh, exercising the fused all_to_all shuffle/aggregate step.

The reference's analogous measurement is shuffle GB/s between executor
GPUs over UCX (SURVEY.md §2.8); here the transport is XLA's all_to_all
over ICI inside one compiled program, so the benchmark times the whole
exchange+aggregate step and reports rows/s and shuffled GB/s per chip.

    python -m spark_rapids_tpu.benchmarks.shuffle_bench \
        --rows 4000000 --keys 65536 --devices 8 --iterations 3
"""
from __future__ import annotations

import argparse
import json
import time

import numpy as np


def run(rows: int, n_keys: int, n_devices: int = 0,
        iterations: int = 3, warmup: int = 1, seed: int = 7) -> dict:
    import jax

    import spark_rapids_tpu  # noqa: F401  (x64 on)
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.ops.groupby import AggSpec
    from spark_rapids_tpu.parallel import (
        DistributedGroupByStep,
        data_mesh,
        distributed_batch_from_host,
        gather_distributed_result,
    )

    if n_devices:
        from spark_rapids_tpu.parallel.mesh import force_cpu_mesh

        force_cpu_mesh(n_devices)
    n_dev = n_devices or len(jax.devices())
    mesh = data_mesh(n_dev)

    rng = np.random.default_rng(seed)
    keys = rng.integers(0, n_keys, rows).astype(np.int64)
    vals = rng.random(rows)
    dtypes = [dt.INT64, dt.FLOAT64]
    datas, valids, counts, _cap = distributed_batch_from_host(
        mesh, [keys, vals], dtypes)
    step = DistributedGroupByStep(
        mesh, dtypes, [0],
        [AggSpec("sum", 1), AggSpec("count_star")])

    times = []
    for i in range(warmup + iterations):
        t0 = time.perf_counter()
        out_d, out_v, ng = step(datas, valids, counts)
        jax.block_until_ready(out_d)
        if i >= warmup:
            times.append(time.perf_counter() - t0)

    # every row carries both columns' payload + validity across the wire
    # at most once (hash routing): bytes ~ rows * (8 + 8 + 2)
    payload_bytes = rows * (8 + 8 + 2)
    best = min(times)
    result = {
        "benchmark": "wide_shuffle",
        "rows": rows,
        "distinct_keys": n_keys,
        "devices": n_dev,
        "backend": jax.devices()[0].platform,
        "times_sec": times,
        "min_time_sec": best,
        "rows_per_sec": rows / best,
        "shuffle_gb_per_sec_per_chip": payload_bytes / best / 1e9 / n_dev,
    }
    res = gather_distributed_result(out_d, out_v, ng,
                                    step.output_dtypes(), n_dev)
    result["groups"] = res.realized_num_rows()
    # correctness pin: global sum survives the exchange exactly
    df = res.to_pandas()
    result["sum_ok"] = bool(abs(float(df.iloc[:, 1].sum()) -
                                float(vals.sum())) < 1e-6 * rows)
    return result


def run_head_to_head(rows: int, n_keys: int, n_devices: int = 0,
                     iterations: int = 3, warmup: int = 1,
                     seed: int = 7) -> dict:
    """TCP transport vs in-program ``all_to_all`` at matched partition
    counts and (statistically) matched partition sizes: the same rows
    shuffle once per iteration through each transport, and the record
    reports bytes-moved and wall-clock PER EXCHANGE for both.

    The in-program side times ONE compiled hash-route + all_to_all
    launch (parallel/shuffle.DistributedShuffleStep — the exchange
    ShuffleExchangeExec's in-program mode runs). The TCP side times
    write_map_output + read_partition over shuffle/tcp.py's real
    sockets with pre-partitioned blocks, so the clock covers transport
    (metadata, windowed chunks, reassembly) and not the partition
    kernel — the fair analogue of the collective, which also excludes
    upstream compute.
    """
    import tempfile

    import jax

    import spark_rapids_tpu  # noqa: F401  (x64 on)
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.column import Column
    from spark_rapids_tpu.parallel import (data_mesh,
                                           distributed_batch_from_host)
    from spark_rapids_tpu.parallel.shuffle import DistributedShuffleStep
    from spark_rapids_tpu.shuffle import LocalCluster

    if n_devices:
        from spark_rapids_tpu.parallel.mesh import force_cpu_mesh

        force_cpu_mesh(n_devices)
    n_dev = n_devices or len(jax.devices())
    n_parts = n_dev  # matched partition count across both transports
    mesh = data_mesh(n_dev)

    rng = np.random.default_rng(seed)
    keys = rng.integers(0, n_keys, rows).astype(np.int64)
    vals = rng.random(rows)
    dtypes = [dt.INT64, dt.FLOAT64]
    # live payload crossing the exchange: key + value + validity per row
    payload_bytes = rows * (8 + 8 + 2)

    # ---- in-program all_to_all ------------------------------------
    datas, valids, counts, cap = distributed_batch_from_host(
        mesh, [keys, vals], dtypes)
    step = DistributedShuffleStep(mesh, dtypes, [0], n_parts)
    prog_times = []
    for i in range(warmup + iterations):
        t0 = time.perf_counter()
        out = step(datas, valids, counts)
        jax.block_until_ready(out)
        if i >= warmup:
            prog_times.append(time.perf_counter() - t0)
    # the collective physically moves full padded blocks: each device
    # sends its (n_dev, cap) block per column (+ pid + valids)
    prog_wire = n_dev * n_dev * cap * (8 + 8 + 8 + 1 + 1 + 1)

    # ---- TCP transport --------------------------------------------
    # pre-partition OUTSIDE the clock: one map input per executor,
    # blocks cut by a cheap balanced pid (sizes match the hash route
    # statistically — both are uniform over n_parts)
    pid = (keys % n_parts).astype(np.int64)
    maps = np.array_split(np.arange(rows), n_parts)
    map_blocks = []
    for m in range(n_parts):
        rows_m = maps[m]
        out = {}
        for p in range(n_parts):
            idx = rows_m[pid[rows_m] == p]
            if not len(idx):
                continue
            out[p] = ColumnarBatch(
                [Column.from_numpy(keys[idx], dt.INT64),
                 Column.from_numpy(vals[idx], dt.FLOAT64)], len(idx))
        map_blocks.append(out)
    tmp = tempfile.mkdtemp(prefix="srt_shuffle_h2h_")
    cluster = LocalCluster(n_parts, spill_dir=tmp, transport="tcp")
    tcp_times = []
    tcp_wire = 0
    try:
        for i in range(warmup + iterations):
            sid = i + 1
            t0 = time.perf_counter()
            for m in range(n_parts):
                cluster.write_map_output(sid, m, m, map_blocks[m])
            got = 0
            for p in range(n_parts):
                for b in cluster.read_partition(
                        sid, p, reader_executor_index=p):
                    got += b.realized_num_rows()
            elapsed = time.perf_counter() - t0
            assert got == rows, (got, rows)
            if i >= warmup:
                tcp_times.append(elapsed)
        # serialized block bytes actually registered for the exchange
        tcp_wire = sum(
            sum(b.capacity * (8 + 8 + 2) for b in out.values())
            for out in map_blocks)
    finally:
        cluster.shutdown()

    prog_best, tcp_best = min(prog_times), min(tcp_times)
    return {
        "benchmark": "shuffle_head_to_head",
        "rows": rows,
        "distinct_keys": n_keys,
        "devices": n_dev,
        "partitions": n_parts,
        "backend": jax.devices()[0].platform,
        "payload_bytes_per_exchange": payload_bytes,
        "in_program": {
            "transport": "all_to_all (in-program collective)",
            "times_sec": prog_times,
            "wall_per_exchange_s": prog_best,
            "bytes_moved_per_exchange": prog_wire,
            "payload_gb_per_sec": payload_bytes / prog_best / 1e9,
        },
        "tcp": {
            "transport": "tcp (shuffle/tcp.py sockets)",
            "times_sec": tcp_times,
            "wall_per_exchange_s": tcp_best,
            "bytes_moved_per_exchange": tcp_wire,
            "payload_gb_per_sec": payload_bytes / tcp_best / 1e9,
        },
        "in_program_speedup": tcp_best / prog_best,
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--rows", type=int, default=4_000_000)
    p.add_argument("--keys", type=int, default=65_536)
    p.add_argument("--devices", type=int, default=0,
                   help="0 = all available devices; N forces a virtual "
                        "N-device CPU mesh when fewer are attached")
    p.add_argument("--iterations", type=int, default=3)
    p.add_argument("--warmup", type=int, default=1)
    p.add_argument("--head-to-head", action="store_true",
                   help="also time the SAME exchange through the TCP "
                        "transport at matched partition counts/sizes "
                        "and report bytes-moved + wall per exchange")
    args = p.parse_args(argv)
    out = run(args.rows, args.keys, args.devices,
              args.iterations, args.warmup)
    if args.head_to_head:
        out = {"wide_shuffle": out,
               "head_to_head": run_head_to_head(
                   args.rows, args.keys, args.devices,
                   args.iterations, args.warmup)}
    print(json.dumps(out))


if __name__ == "__main__":
    main()
