"""Benchmark suites (the reference's integration_tests benchmark layer,
SURVEY.md §2.14: TpchLikeSpark.scala hand-written query definitions +
BenchmarkRunner CLI + BenchUtils.compareResults verification).

"-like" has the same meaning as in the reference: schema- and
shape-faithful versions of the TPC queries over generated data, NOT
audited TPC runs (reference README disclaimer)."""
from spark_rapids_tpu.benchmarks import datagen, tpch
from spark_rapids_tpu.benchmarks.runner import BenchmarkRunner

__all__ = ["datagen", "tpch", "BenchmarkRunner"]
