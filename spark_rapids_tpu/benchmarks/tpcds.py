"""TPC-DS-like tables and query plans (TpcdsLikeSpark.scala analogue:
integration_tests/src/main/scala/.../tpcds/TpcdsLikeSpark.scala defines the
full table schemas + hand-written DataFrame queries; this module generates
the subset of tables the -like queries read and defines each query as a
function data_dir -> plan).

Queries: the classic reporting shape (q3/q42/q52/q55: fact x date_dim x
item, filtered group-by revenue) plus a q72-like (catalog_sales x
inventory x warehouse x item x date_dim with an inter-fact inequality — the
multi-way join headline of BASELINE config #3)."""
from __future__ import annotations

import functools
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions import aggregates as A
from spark_rapids_tpu.expressions import arithmetic as ar
from spark_rapids_tpu.expressions import predicates as P
from spark_rapids_tpu.expressions.base import Alias, BoundReference, Literal
from spark_rapids_tpu.io import ParquetSource
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.plan import nodes as pn

CATEGORIES = np.array(["Books", "Electronics", "Home", "Jewelry", "Men",
                       "Music", "Shoes", "Sports", "Children", "Women"],
                      dtype=object)


# ---------------------------------------------------------------------------
# datagen


def gen_date_dim(sf: float, seed: int = 31) -> pa.Table:
    # one row per day 1998-2002, d_date_sk dense from 2450815 (dsdgen's
    # julian base is arbitrary; dense sks keep joins realistic)
    days = np.arange(np.datetime64("1998-01-01"),
                     np.datetime64("2003-01-01"))
    n = len(days)
    years = days.astype("datetime64[Y]").astype(int) + 1970
    months = days.astype("datetime64[M]").astype(int) % 12 + 1
    week_seq = (days - np.datetime64("1998-01-01")).astype(int) // 7
    return pa.table({
        "d_date_sk": np.arange(2450815, 2450815 + n, dtype=np.int64),
        "d_date": days,
        "d_year": years.astype(np.int32),
        "d_moy": months.astype(np.int32),
        "d_week_seq": week_seq.astype(np.int32),
    })


def gen_item(sf: float, seed: int = 32) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(18_000 * sf), 50)
    brand_id = rng.integers(1, 1000, n).astype(np.int32)
    cat_id = rng.integers(0, 10, n)
    return pa.table({
        "i_item_sk": np.arange(1, n + 1, dtype=np.int64),
        "i_brand_id": brand_id,
        "i_brand": np.array([f"brand#{b}" for b in brand_id],
                            dtype=object),
        "i_category_id": cat_id.astype(np.int32),
        "i_category": CATEGORIES[cat_id],
        "i_class_id": rng.integers(1, 9, n).astype(np.int32),
        "i_manufact_id": rng.integers(1, 1000, n).astype(np.int32),
        "i_manager_id": rng.integers(1, 100, n).astype(np.int32),
        "i_item_id": np.array([f"AAAAAAAA{i:08d}" for i in range(1, n + 1)],
                              dtype=object),
        "i_current_price": np.round(0.5 + rng.random(n) * 2.0, 2),
        "i_item_desc": np.array([f"item description {i % 997}"
                                 for i in range(n)], dtype=object),
    })


def _date_sks(rng, n):
    return rng.integers(2450815, 2450815 + 5 * 365, n).astype(np.int64)


@functools.lru_cache(maxsize=2)  # returns generators re-sample the same fact table
def gen_store_sales(sf: float, seed: int = 33) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(2_880_000 * sf), 200)
    n_item = max(int(18_000 * sf), 50)
    return pa.table({
        "ss_sold_date_sk": _date_sks(rng, n),
        "ss_sold_time_sk": rng.integers(0, 86_400, n).astype(np.int64),
        "ss_item_sk": rng.integers(1, n_item + 1, n).astype(np.int64),
        "ss_customer_sk": rng.integers(1, max(int(100_000 * sf), 20), n
                                       ).astype(np.int64),
        "ss_cdemo_sk": rng.integers(1, max(int(1_000 * sf), 20) + 1, n
                                    ).astype(np.int64),
        "ss_hdemo_sk": rng.integers(1, 7201, n).astype(np.int64),
        "ss_promo_sk": rng.integers(1, max(int(300 * sf), 10) + 1, n
                                    ).astype(np.int64),
        "ss_store_sk": rng.integers(1, max(int(12 * sf), 2) + 1, n
                                    ).astype(np.int64),
        "ss_ticket_number": rng.integers(1, max(n // 3, 2), n
                                         ).astype(np.int64),
        "ss_quantity": rng.integers(1, 101, n).astype(np.int32),
        "ss_sales_price": np.round(rng.random(n) * 200, 2),
        "ss_net_paid": np.round(rng.random(n) * 250, 2),
        "ss_list_price": np.round(rng.random(n) * 250, 2),
        "ss_coupon_amt": np.round(rng.random(n) * 50, 2),
        "ss_ext_list_price": np.round(rng.random(n) * 25_000, 2),
        "ss_ext_wholesale_cost": np.round(rng.random(n) * 10_000, 2),
        "ss_ext_discount_amt": np.round(rng.random(n) * 4_000, 2),
        "ss_ext_sales_price": np.round(rng.random(n) * 20_000, 2),
        "ss_net_profit": np.round(rng.random(n) * 4_000 - 2_000, 2),
    })


def gen_catalog_sales(sf: float, seed: int = 34) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(1_440_000 * sf), 150)
    n_item = max(int(18_000 * sf), 50)
    return pa.table({
        "cs_sold_date_sk": _date_sks(rng, n),
        "cs_ship_date_sk": _date_sks(rng, n) + rng.integers(1, 30, n),
        "cs_item_sk": rng.integers(1, n_item + 1, n).astype(np.int64),
        "cs_quantity": rng.integers(1, 101, n).astype(np.int32),
        "cs_ext_sales_price": np.round(rng.random(n) * 20_000, 2),
    })


def gen_inventory(sf: float, seed: int = 35) -> pa.Table:
    rng = np.random.default_rng(seed)
    n_item = max(int(18_000 * sf), 50)
    n_wh = max(int(5 * sf), 2)
    # weekly snapshots: every item x warehouse x ~26 weeks
    weeks = 26
    n = n_item * n_wh * weeks
    item = np.tile(np.arange(1, n_item + 1, dtype=np.int64), n_wh * weeks)
    wh = np.repeat(np.arange(1, n_wh + 1, dtype=np.int64), n_item * weeks)
    week_start = rng.integers(2450815, 2450815 + 5 * 365 - 7,
                              weeks)
    date_sk = np.tile(np.repeat(week_start, n_item), n_wh)
    return pa.table({
        "inv_date_sk": date_sk.astype(np.int64),
        "inv_item_sk": item,
        "inv_warehouse_sk": wh,
        "inv_quantity_on_hand": rng.integers(0, 120, n).astype(np.int32),
    })


def gen_warehouse(sf: float, seed: int = 36) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(5 * sf), 2)
    states = np.array(["CA", "TX", "NY", "WA", "GA"], dtype=object)
    return pa.table({
        "w_warehouse_sk": np.arange(1, n + 1, dtype=np.int64),
        "w_warehouse_name": np.array([f"Warehouse {i}"
                                      for i in range(1, n + 1)],
                                     dtype=object),
        "w_state": states[rng.integers(0, 5, n)],
    })


def gen_store_returns(sf: float, seed: int = 48) -> pa.Table:
    """~8% of store_sales rows return; key columns are SAMPLED from the
    sales table so multi-key joins (q21's ticket+item+customer) hit."""
    rng = np.random.default_rng(seed)
    sales = gen_store_sales(sf)
    n_s = sales.num_rows
    n = max(n_s // 12, 30)
    idx = rng.choice(n_s, n, replace=False)
    item = sales["ss_item_sk"].to_numpy()[idx]
    cust = sales["ss_customer_sk"].to_numpy()[idx]
    ticket = sales["ss_ticket_number"].to_numpy()[idx]
    sold = sales["ss_sold_date_sk"].to_numpy()[idx]
    return pa.table({
        "sr_item_sk": item,
        "sr_customer_sk": cust,
        "sr_ticket_number": ticket,
        "sr_returned_date_sk": sold + rng.integers(1, 90, n),
        "sr_return_quantity": rng.integers(1, 20, n).astype(np.int32),
        "sr_return_amt": np.round(rng.random(n) * 150, 2),
    })


def gen_web_page(sf: float, seed: int = 49) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(60 * sf), 5)
    return pa.table({
        "wp_web_page_sk": np.arange(1, n + 1, dtype=np.int64),
        "wp_char_count": rng.integers(4000, 7001, n).astype(np.int32),
    })


def gen_customer_demographics(sf: float, seed: int = 37) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(1_000 * sf), 20)
    return pa.table({
        "cd_demo_sk": np.arange(1, n + 1, dtype=np.int64),
        "cd_gender": np.array(["M", "F"], dtype=object)[
            rng.integers(0, 2, n)],
        "cd_marital_status": np.array(["M", "S", "D", "W", "U"],
                                      dtype=object)[rng.integers(0, 5, n)],
        "cd_education_status": np.array(
            ["Primary", "Secondary", "College", "2 yr Degree",
             "4 yr Degree", "Advanced Degree", "Unknown"],
            dtype=object)[rng.integers(0, 7, n)],
    })


def gen_promotion(sf: float, seed: int = 38) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(300 * sf), 10)
    return pa.table({
        "p_promo_sk": np.arange(1, n + 1, dtype=np.int64),
        "p_channel_email": np.array(["Y", "N"], dtype=object)[
            rng.integers(0, 2, n)],
        "p_channel_event": np.array(["Y", "N"], dtype=object)[
            rng.integers(0, 2, n)],
        "p_channel_dmail": np.array(["Y", "N"], dtype=object)[
            rng.integers(0, 2, n)],
        "p_channel_tv": np.array(["Y", "N"], dtype=object)[
            rng.integers(0, 2, n)],
    })


def gen_household_demographics(sf: float, seed: int = 39) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = 7200  # fixed-size dim in TPC-DS
    return pa.table({
        "hd_demo_sk": np.arange(1, n + 1, dtype=np.int64),
        "hd_dep_count": rng.integers(0, 10, n).astype(np.int32),
    })


def gen_time_dim(sf: float, seed: int = 40) -> pa.Table:
    secs = np.arange(86_400, dtype=np.int64)
    return pa.table({
        "t_time_sk": secs,
        "t_hour": (secs // 3600).astype(np.int32),
        "t_minute": (secs // 60 % 60).astype(np.int32),
    })


def gen_store(sf: float, seed: int = 41) -> pa.Table:
    n = max(int(12 * sf), 2)
    rng = np.random.default_rng(seed)
    return pa.table({
        "s_store_sk": np.arange(1, n + 1, dtype=np.int64),
        "s_store_id": np.array([f"AAAAAAAA{i:04d}" for i in range(1, n + 1)],
                               dtype=object),
        "s_store_name": np.array([f"ese{i}" for i in range(1, n + 1)],
                                 dtype=object),
        "s_gmt_offset": np.where(rng.random(n) < 0.7, -5.0, -6.0),
    })


GENERATORS = {
    "date_dim": gen_date_dim,
    "item": gen_item,
    "store_sales": gen_store_sales,
    "catalog_sales": gen_catalog_sales,
    "inventory": gen_inventory,
    "warehouse": gen_warehouse,
    "customer_demographics": gen_customer_demographics,
    "promotion": gen_promotion,
    "household_demographics": gen_household_demographics,
    "time_dim": gen_time_dim,
    "store": gen_store,
    "store_returns": gen_store_returns,
    "web_page": gen_web_page,
}


def write_tables(data_dir: str, sf: float, tables=None,
                 files_per_table: int = 4) -> None:
    os.makedirs(data_dir, exist_ok=True)
    for name in tables or GENERATORS:
        table = GENERATORS[name](sf)
        tdir = os.path.join(data_dir, name)
        os.makedirs(tdir, exist_ok=True)
        per = -(-table.num_rows // files_per_table)
        for i in range(files_per_table):
            chunk = table.slice(i * per, per)
            if chunk.num_rows:
                pq.write_table(chunk,
                               os.path.join(tdir,
                                            f"part-{i:03d}.parquet"))


# ---------------------------------------------------------------------------
# queries


def ref(i, t):
    return BoundReference(i, t)


def _scan(data_dir: str, table: str, columns):
    return pn.ScanNode(ParquetSource(os.path.join(data_dir, table),
                                     columns=columns))


def _report_query(data_dir: str, item_filter, group_ordinal_names,
                  date_filter_moy=11, date_filter_year=None):
    """The q3/q42/q52/q55 family: date_dim x store_sales x item,
    filtered on month (and maybe year) + an item attribute, grouped on
    (d_year, item attrs), sum(ss_ext_sales_price) descending."""
    dd_cond = P.EqualTo(ref(1, dt.INT32),
                        Literal(date_filter_moy, dt.INT32))
    if date_filter_year is not None:
        dd_cond = P.And(dd_cond,
                        P.EqualTo(ref(2, dt.INT32),
                                  Literal(date_filter_year, dt.INT32)))
    date_dim = pn.FilterNode(
        dd_cond, _scan(data_dir, "date_dim",
                       ["d_date_sk", "d_moy", "d_year"]))
    sales = _scan(data_dir, "store_sales",
                  ["ss_sold_date_sk", "ss_item_sk",
                   "ss_ext_sales_price"])
    item_cols, item_pred, group_item_ordinals = item_filter
    item = pn.FilterNode(item_pred, _scan(data_dir, "item", item_cols))
    # [d_date_sk 0, d_moy 1, d_year 2, ss_sold_date_sk 3, ss_item_sk 4,
    #  ss_ext_sales_price 5]
    ds = pn.JoinNode("inner", date_dim, sales, [0], [0])
    # + item cols at 6..
    dsi = pn.JoinNode("inner", ds, item, [4], [0])
    group_refs = [ref(2, dt.INT32)] + \
        [ref(6 + o, t) for o, t in group_item_ordinals]
    proj = pn.ProjectNode(
        [Alias(e, n) for e, n in zip(group_refs, group_ordinal_names)] +
        [Alias(ref(5, dt.FLOAT64), "price")], dsi)
    k = len(group_refs)
    agg = pn.AggregateNode(
        [ref(i, e.dtype) for i, e in enumerate(group_refs)],
        [pn.AggCall(A.Sum(ref(k, dt.FLOAT64)), "sum_agg")],
        proj, grouping_names=group_ordinal_names)
    sort = pn.SortNode(
        [SortKeySpec.spark_default(k, ascending=False)] +
        [SortKeySpec.spark_default(i) for i in range(k)], agg)
    return pn.LimitNode(100, sort)


def q3(data_dir: str) -> pn.PlanNode:
    """Brand revenue for one manufacturer in November
    (TpcdsLikeSpark.scala q3)."""
    item_filter = (["i_item_sk", "i_brand_id", "i_brand",
                    "i_manufact_id"],
                   P.EqualTo(ref(3, dt.INT32), Literal(128, dt.INT32)),
                   [(1, dt.INT32), (2, dt.STRING)])
    return _report_query(data_dir, item_filter,
                         ["d_year", "brand_id", "brand"])


def q42(data_dir: str) -> pn.PlanNode:
    """Category revenue for one manager-year (q42)."""
    item_filter = (["i_item_sk", "i_category_id", "i_category",
                    "i_manager_id"],
                   P.EqualTo(ref(3, dt.INT32), Literal(1, dt.INT32)),
                   [(1, dt.INT32), (2, dt.STRING)])
    return _report_query(data_dir, item_filter,
                         ["d_year", "i_category_id", "i_category"],
                         date_filter_year=2000)


def q52(data_dir: str) -> pn.PlanNode:
    """Brand revenue for one manager-year (q52)."""
    item_filter = (["i_item_sk", "i_brand_id", "i_brand",
                    "i_manager_id"],
                   P.EqualTo(ref(3, dt.INT32), Literal(1, dt.INT32)),
                   [(1, dt.INT32), (2, dt.STRING)])
    return _report_query(data_dir, item_filter,
                         ["d_year", "brand_id", "brand"],
                         date_filter_year=2000)


def q55(data_dir: str) -> pn.PlanNode:
    """Brand revenue, manager 28, one month (q55)."""
    item_filter = (["i_item_sk", "i_brand_id", "i_brand",
                    "i_manager_id"],
                   P.EqualTo(ref(3, dt.INT32), Literal(28, dt.INT32)),
                   [(1, dt.INT32), (2, dt.STRING)])
    return _report_query(data_dir, item_filter,
                         ["d_year", "brand_id", "brand"],
                         date_filter_year=1999)


def q72(data_dir: str) -> pn.PlanNode:
    """q72-like: catalog_sales x inventory (same item, on-hand below
    ordered quantity) x warehouse x item x date_dim — the infamous
    expansion join, simplified to the tables generated here."""
    cs = _scan(data_dir, "catalog_sales",
               ["cs_sold_date_sk", "cs_item_sk", "cs_quantity"])
    inv = _scan(data_dir, "inventory",
                ["inv_date_sk", "inv_item_sk", "inv_warehouse_sk",
                 "inv_quantity_on_hand"])
    # join on item; keep only rows where on-hand < ordered (the q72
    # shortage condition) — an equi-join with an inter-fact residual
    # [cs 0-2, inv 3-6]
    short = pn.JoinNode(
        "inner", cs, inv, [1], [1],
        condition=P.LessThan(ref(6, dt.INT32), ref(2, dt.INT32)))
    wh = _scan(data_dir, "warehouse",
               ["w_warehouse_sk", "w_warehouse_name"])
    # + [w_warehouse_sk 7, w_warehouse_name 8]
    sw = pn.JoinNode("inner", short, wh, [5], [0])
    item = _scan(data_dir, "item", ["i_item_sk", "i_item_desc"])
    # + [i_item_sk 9, i_item_desc 10]
    swi = pn.JoinNode("inner", sw, item, [1], [0])
    dd = _scan(data_dir, "date_dim", ["d_date_sk", "d_week_seq"])
    # + [d_date_sk 11, d_week_seq 12]
    swid = pn.JoinNode("inner", swi, dd, [0], [0])
    agg = pn.AggregateNode(
        [ref(10, dt.STRING), ref(8, dt.STRING), ref(12, dt.INT32)],
        [pn.AggCall(A.Count(), "no_promo")],
        swid, grouping_names=["i_item_desc", "w_warehouse_name",
                              "d_week_seq"])
    sort = pn.SortNode([SortKeySpec.spark_default(3, ascending=False),
                        SortKeySpec.spark_default(0),
                        SortKeySpec.spark_default(1),
                        SortKeySpec.spark_default(2)], agg)
    return pn.LimitNode(100, sort)


def q7(data_dir: str) -> pn.PlanNode:
    """Promotional-item averages per item for one demographic slice
    (TpcdsLikeSpark q7): 5-way join + multi-average group-by."""
    ss = _scan(data_dir, "store_sales",
               ["ss_sold_date_sk", "ss_item_sk", "ss_cdemo_sk",
                "ss_promo_sk", "ss_quantity", "ss_list_price",
                "ss_coupon_amt", "ss_sales_price"])
    cd = pn.FilterNode(
        P.And(P.EqualTo(ref(1, dt.STRING), Literal("M")),
              P.And(P.EqualTo(ref(2, dt.STRING), Literal("S")),
                    P.EqualTo(ref(3, dt.STRING), Literal("College")))),
        _scan(data_dir, "customer_demographics",
              ["cd_demo_sk", "cd_gender", "cd_marital_status",
               "cd_education_status"]))
    # + [cd 8..11]
    s1 = pn.JoinNode("inner", ss, cd, [2], [0])
    dd = pn.FilterNode(
        P.EqualTo(ref(1, dt.INT32), Literal(2000, dt.INT32)),
        _scan(data_dir, "date_dim", ["d_date_sk", "d_year"]))
    # + [d_date_sk 12, d_year 13]
    s2 = pn.JoinNode("inner", s1, dd, [0], [0])
    promo = pn.FilterNode(
        P.Or(P.EqualTo(ref(1, dt.STRING), Literal("N")),
             P.EqualTo(ref(2, dt.STRING), Literal("N"))),
        _scan(data_dir, "promotion",
              ["p_promo_sk", "p_channel_email", "p_channel_event"]))
    # + [p_promo_sk 14, p_channel_email 15, p_channel_event 16]
    s3 = pn.JoinNode("inner", s2, promo, [3], [0])
    item = _scan(data_dir, "item", ["i_item_sk", "i_item_desc"])
    # + [i_item_sk 17, i_item_desc 18]
    s4 = pn.JoinNode("inner", s3, item, [1], [0])
    from spark_rapids_tpu.expressions.cast import Cast

    agg = pn.AggregateNode(
        [ref(18, dt.STRING)],
        [pn.AggCall(A.Average(Cast(ref(4, dt.INT32), dt.FLOAT64)),
                    "agg1"),
         pn.AggCall(A.Average(ref(5, dt.FLOAT64)), "agg2"),
         pn.AggCall(A.Average(ref(6, dt.FLOAT64)), "agg3"),
         pn.AggCall(A.Average(ref(7, dt.FLOAT64)), "agg4")],
        s4, grouping_names=["i_item_desc"])
    sort = pn.SortNode([SortKeySpec.spark_default(0)], agg)
    return pn.LimitNode(100, sort)


def q96(data_dir: str) -> pn.PlanNode:
    """Count of evening purchases by large households at one store
    (TpcdsLikeSpark q96): pure 4-way join + count."""
    ss = _scan(data_dir, "store_sales",
               ["ss_sold_time_sk", "ss_hdemo_sk", "ss_store_sk"])
    hd = pn.FilterNode(
        P.EqualTo(ref(1, dt.INT32), Literal(7, dt.INT32)),
        _scan(data_dir, "household_demographics",
              ["hd_demo_sk", "hd_dep_count"]))
    td = pn.FilterNode(
        P.And(P.EqualTo(ref(1, dt.INT32), Literal(20, dt.INT32)),
              P.GreaterThanOrEqual(ref(2, dt.INT32),
                                   Literal(30, dt.INT32))),
        _scan(data_dir, "time_dim", ["t_time_sk", "t_hour", "t_minute"]))
    store = pn.FilterNode(
        P.EqualTo(ref(1, dt.STRING), Literal("ese1")),
        _scan(data_dir, "store", ["s_store_sk", "s_store_name"]))
    s1 = pn.JoinNode("inner", ss, hd, [1], [0])
    s2 = pn.JoinNode("inner", s1, td, [0], [0])
    s3 = pn.JoinNode("inner", s2, store, [2], [0])
    return pn.AggregateNode([], [pn.AggCall(A.Count(), "cnt")], s3)


def q98(data_dir: str) -> pn.PlanNode:
    """Revenue share within item class (TpcdsLikeSpark q98): the
    windowed-aggregate shape — per-item revenue plus a partitioned
    window SUM over the class for the ratio."""
    ss = _scan(data_dir, "store_sales",
               ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    dd = pn.FilterNode(
        P.EqualTo(ref(2, dt.INT32), Literal(1999, dt.INT32)),
        _scan(data_dir, "date_dim",
              ["d_date_sk", "d_moy", "d_year"]))
    item = pn.FilterNode(
        P.In(ref(2, dt.STRING),
             [Literal("Sports"), Literal("Books"), Literal("Home")]),
        _scan(data_dir, "item",
              ["i_item_sk", "i_class_id", "i_category",
               "i_item_desc"]))
    s1 = pn.JoinNode("inner", ss, dd, [0], [0])
    # + item at 6..9
    s2 = pn.JoinNode("inner", s1, item, [1], [0])
    per_item = pn.AggregateNode(
        [ref(9, dt.STRING), ref(7, dt.INT32), ref(8, dt.STRING)],
        [pn.AggCall(A.Sum(ref(2, dt.FLOAT64)), "itemrevenue")],
        s2, grouping_names=["i_item_desc", "i_class_id", "i_category"])
    # windowed class total: partition by class, unbounded frame sum
    win = pn.WindowNode(
        [1], [],
        [pn.WindowCall(A.Sum(ref(3, dt.FLOAT64)), "classrevenue",
                       pn.WindowFrame(None, None))],
        per_item)
    share = pn.ProjectNode(
        [Alias(ref(0, dt.STRING), "i_item_desc"),
         Alias(ref(2, dt.STRING), "i_category"),
         Alias(ref(3, dt.FLOAT64), "itemrevenue"),
         Alias(ar.Multiply(
             Literal(100.0),
             ar.Divide(ref(3, dt.FLOAT64), ref(4, dt.FLOAT64))),
             "revenueratio")], win)
    sort = pn.SortNode([SortKeySpec.spark_default(1),
                        SortKeySpec.spark_default(3),
                        SortKeySpec.spark_default(0)], share)
    return pn.LimitNode(100, sort)


QUERIES = {"tpcds_q3": q3, "tpcds_q7": q7, "tpcds_q42": q42,
           "tpcds_q52": q52, "tpcds_q55": q55, "tpcds_q72": q72,
           "tpcds_q96": q96, "tpcds_q98": q98}
