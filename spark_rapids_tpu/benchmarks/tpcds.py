"""TPC-DS-like tables and query plans (TpcdsLikeSpark.scala analogue:
integration_tests/src/main/scala/.../tpcds/TpcdsLikeSpark.scala defines the
full table schemas + hand-written DataFrame queries; this module generates
the subset of tables the -like queries read and defines each query as a
function data_dir -> plan).

Queries: the classic reporting shape (q3/q42/q52/q55: fact x date_dim x
item, filtered group-by revenue) plus a q72-like (catalog_sales x
inventory x warehouse x item x date_dim with an inter-fact inequality — the
multi-way join headline of BASELINE config #3)."""
from __future__ import annotations

import functools
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions import aggregates as A
from spark_rapids_tpu.expressions import arithmetic as ar
from spark_rapids_tpu.expressions import predicates as P
from spark_rapids_tpu.expressions.base import Alias, BoundReference, Literal
from spark_rapids_tpu.io import ParquetSource
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.plan import nodes as pn

CATEGORIES = np.array(["Books", "Electronics", "Home", "Jewelry", "Men",
                       "Music", "Shoes", "Sports", "Children", "Women"],
                      dtype=object)


# ---------------------------------------------------------------------------
# datagen


def gen_date_dim(sf: float, seed: int = 31) -> pa.Table:
    # one row per day 1998-2002, d_date_sk dense from 2450815 (dsdgen's
    # julian base is arbitrary; dense sks keep joins realistic)
    days = np.arange(np.datetime64("1998-01-01"),
                     np.datetime64("2003-01-01"))
    n = len(days)
    years = days.astype("datetime64[Y]").astype(int) + 1970
    months = days.astype("datetime64[M]").astype(int) % 12 + 1
    week_seq = (days - np.datetime64("1998-01-01")).astype(int) // 7
    # TPC-DS d_dow: 0=Sunday .. 6=Saturday; numpy weekday: 0=Monday
    dow = (days.astype("datetime64[D]").view("int64") + 4) % 7
    day_names = np.array(["Sunday", "Monday", "Tuesday", "Wednesday",
                          "Thursday", "Friday", "Saturday"], dtype=object)
    dom = (days - days.astype("datetime64[M]")).astype(int) + 1
    month_seq = (years - 1998) * 12 + (months - 1)
    return pa.table({
        "d_date_sk": np.arange(2450815, 2450815 + n, dtype=np.int64),
        "d_date": days,
        "d_year": years.astype(np.int32),
        "d_moy": months.astype(np.int32),
        "d_dom": dom.astype(np.int32),
        "d_dow": dow.astype(np.int32),
        "d_day_name": day_names[dow],
        "d_week_seq": week_seq.astype(np.int32),
        "d_month_seq": month_seq.astype(np.int32),
        "d_qoy": ((months - 1) // 3 + 1).astype(np.int32),
    })


def gen_item(sf: float, seed: int = 32) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(18_000 * sf), 50)
    brand_id = rng.integers(1, 1000, n).astype(np.int32)
    cat_id = rng.integers(0, 10, n)
    return pa.table({
        "i_item_sk": np.arange(1, n + 1, dtype=np.int64),
        "i_brand_id": brand_id,
        "i_brand": np.array([f"brand#{b}" for b in brand_id],
                            dtype=object),
        "i_category_id": cat_id.astype(np.int32),
        "i_category": CATEGORIES[cat_id],
        "i_class_id": rng.integers(1, 9, n).astype(np.int32),
        "i_manufact_id": rng.integers(1, 1000, n).astype(np.int32),
        "i_manager_id": rng.integers(1, 100, n).astype(np.int32),
        "i_item_id": np.array([f"AAAAAAAA{i:08d}" for i in range(1, n + 1)],
                              dtype=object),
        "i_current_price": np.round(0.5 + rng.random(n) * 2.0, 2),
        "i_wholesale_cost": np.round(0.2 + rng.random(n) * 1.5, 2),
        "i_manufact": np.array(
            [f"manufact{m % 200}" for m in rng.integers(1, 1000, n)],
            dtype=object),
        "i_class": np.array(
            [f"class{c}" for c in rng.integers(1, 9, n)], dtype=object),
        "i_item_desc": np.array([f"item description {i % 997}"
                                 for i in range(n)], dtype=object),
    })


def _date_sks(rng, n):
    return rng.integers(2450815, 2450815 + 5 * 365, n).astype(np.int64)


@functools.lru_cache(maxsize=2)  # returns generators re-sample the same fact table
def gen_store_sales(sf: float, seed: int = 33) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(2_880_000 * sf), 200)
    n_item = max(int(18_000 * sf), 50)
    return pa.table({
        "ss_sold_date_sk": _date_sks(rng, n),
        "ss_sold_time_sk": rng.integers(0, 86_400, n).astype(np.int64),
        "ss_item_sk": rng.integers(1, n_item + 1, n).astype(np.int64),
        "ss_customer_sk": rng.integers(1, max(int(100_000 * sf), 20), n
                                       ).astype(np.int64),
        "ss_cdemo_sk": rng.integers(1, max(int(1_000 * sf), 20) + 1, n
                                    ).astype(np.int64),
        "ss_hdemo_sk": rng.integers(1, 7201, n).astype(np.int64),
        "ss_promo_sk": rng.integers(1, max(int(300 * sf), 10) + 1, n
                                    ).astype(np.int64),
        "ss_store_sk": rng.integers(1, max(int(12 * sf), 2) + 1, n
                                    ).astype(np.int64),
        "ss_ticket_number": rng.integers(1, max(n // 3, 2), n
                                         ).astype(np.int64),
        "ss_addr_sk": rng.integers(1, max(int(50_000 * sf), 15) + 1, n
                                   ).astype(np.int64),
        "ss_quantity": rng.integers(1, 101, n).astype(np.int32),
        "ss_sales_price": np.round(rng.random(n) * 200, 2),
        "ss_net_paid": np.round(rng.random(n) * 250, 2),
        "ss_ext_tax": np.round(rng.random(n) * 20, 2),
        "ss_wholesale_cost": np.round(rng.random(n) * 100, 2),
        "ss_list_price": np.round(rng.random(n) * 250, 2),
        "ss_coupon_amt": np.round(rng.random(n) * 50, 2),
        "ss_ext_list_price": np.round(rng.random(n) * 25_000, 2),
        "ss_ext_wholesale_cost": np.round(rng.random(n) * 10_000, 2),
        "ss_ext_discount_amt": np.round(rng.random(n) * 4_000, 2),
        "ss_ext_sales_price": np.round(rng.random(n) * 20_000, 2),
        "ss_net_profit": np.round(rng.random(n) * 4_000 - 2_000, 2),
    })


@functools.lru_cache(maxsize=2)  # returns sample it
def gen_catalog_sales(sf: float, seed: int = 34) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(1_440_000 * sf), 150)
    n_item = max(int(18_000 * sf), 50)
    n_cust = max(int(100_000 * sf), 20)
    n_addr = max(int(50_000 * sf), 15)
    n_wh = max(int(5 * sf), 2)
    return pa.table({
        "cs_sold_date_sk": _date_sks(rng, n),
        "cs_ship_date_sk": _date_sks(rng, n) + rng.integers(1, 30, n),
        "cs_item_sk": rng.integers(1, n_item + 1, n).astype(np.int64),
        "cs_bill_customer_sk": rng.integers(1, n_cust + 1, n
                                            ).astype(np.int64),
        "cs_bill_addr_sk": rng.integers(1, n_addr + 1, n
                                        ).astype(np.int64),
        "cs_order_number": rng.integers(1, max(n // 3, 2), n
                                        ).astype(np.int64),
        "cs_warehouse_sk": rng.integers(1, n_wh + 1, n).astype(np.int64),
        "cs_sold_time_sk": rng.integers(0, 86_400, n).astype(np.int64),
        "cs_quantity": rng.integers(1, 101, n).astype(np.int32),
        "cs_sales_price": np.round(rng.random(n) * 200, 2),
        "cs_ext_discount_amt": np.round(rng.random(n) * 4_000, 2),
        "cs_net_profit": np.round(rng.random(n) * 4_000 - 2_000, 2),
        "cs_ext_sales_price": np.round(rng.random(n) * 20_000, 2),
    })


def gen_inventory(sf: float, seed: int = 35) -> pa.Table:
    rng = np.random.default_rng(seed)
    n_item = max(int(18_000 * sf), 50)
    n_wh = max(int(5 * sf), 2)
    # weekly snapshots: every item x warehouse x ~26 weeks
    weeks = 26
    n = n_item * n_wh * weeks
    item = np.tile(np.arange(1, n_item + 1, dtype=np.int64), n_wh * weeks)
    wh = np.repeat(np.arange(1, n_wh + 1, dtype=np.int64), n_item * weeks)
    week_start = rng.integers(2450815, 2450815 + 5 * 365 - 7,
                              weeks)
    date_sk = np.tile(np.repeat(week_start, n_item), n_wh)
    return pa.table({
        "inv_date_sk": date_sk.astype(np.int64),
        "inv_item_sk": item,
        "inv_warehouse_sk": wh,
        "inv_quantity_on_hand": rng.integers(0, 120, n).astype(np.int32),
    })


def gen_warehouse(sf: float, seed: int = 36) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(5 * sf), 2)
    states = np.array(["CA", "TX", "NY", "WA", "GA"], dtype=object)
    return pa.table({
        "w_warehouse_sk": np.arange(1, n + 1, dtype=np.int64),
        "w_warehouse_name": np.array([f"Warehouse {i}"
                                      for i in range(1, n + 1)],
                                     dtype=object),
        "w_state": states[rng.integers(0, 5, n)],
    })


def gen_store_returns(sf: float, seed: int = 48) -> pa.Table:
    """~8% of store_sales rows return; key columns are SAMPLED from the
    sales table so multi-key joins (q21's ticket+item+customer) hit."""
    rng = np.random.default_rng(seed)
    sales = gen_store_sales(sf)
    n_s = sales.num_rows
    n = max(n_s // 12, 30)
    idx = rng.choice(n_s, n, replace=False)
    item = sales["ss_item_sk"].to_numpy()[idx]
    cust = sales["ss_customer_sk"].to_numpy()[idx]
    ticket = sales["ss_ticket_number"].to_numpy()[idx]
    sold = sales["ss_sold_date_sk"].to_numpy()[idx]
    return pa.table({
        "sr_item_sk": item,
        "sr_customer_sk": cust,
        "sr_ticket_number": ticket,
        "sr_returned_date_sk": sold + rng.integers(1, 90, n),
        "sr_return_quantity": rng.integers(1, 20, n).astype(np.int32),
        "sr_return_amt": np.round(rng.random(n) * 150, 2),
        "sr_net_loss": np.round(rng.random(n) * 80, 2),
        "sr_reason_sk": rng.integers(1, 36, n).astype(np.int64),
    })


def gen_web_page(sf: float, seed: int = 49) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(60 * sf), 5)
    return pa.table({
        "wp_web_page_sk": np.arange(1, n + 1, dtype=np.int64),
        "wp_char_count": rng.integers(4000, 7001, n).astype(np.int32),
    })


def gen_customer_demographics(sf: float, seed: int = 37) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(1_000 * sf), 20)
    return pa.table({
        "cd_demo_sk": np.arange(1, n + 1, dtype=np.int64),
        "cd_gender": np.array(["M", "F"], dtype=object)[
            rng.integers(0, 2, n)],
        "cd_marital_status": np.array(["M", "S", "D", "W", "U"],
                                      dtype=object)[rng.integers(0, 5, n)],
        "cd_education_status": np.array(
            ["Primary", "Secondary", "College", "2 yr Degree",
             "4 yr Degree", "Advanced Degree", "Unknown"],
            dtype=object)[rng.integers(0, 7, n)],
    })


def gen_promotion(sf: float, seed: int = 38) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(300 * sf), 10)
    return pa.table({
        "p_promo_sk": np.arange(1, n + 1, dtype=np.int64),
        "p_channel_email": np.array(["Y", "N"], dtype=object)[
            rng.integers(0, 2, n)],
        "p_channel_event": np.array(["Y", "N"], dtype=object)[
            rng.integers(0, 2, n)],
        "p_channel_dmail": np.array(["Y", "N"], dtype=object)[
            rng.integers(0, 2, n)],
        "p_channel_tv": np.array(["Y", "N"], dtype=object)[
            rng.integers(0, 2, n)],
    })


def gen_household_demographics(sf: float, seed: int = 39) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = 7200  # fixed-size dim in TPC-DS
    pots = np.array([">10000", "5001-10000", "1001-5000", "unknown"],
                    dtype=object)
    return pa.table({
        "hd_demo_sk": np.arange(1, n + 1, dtype=np.int64),
        "hd_dep_count": rng.integers(0, 10, n).astype(np.int32),
        "hd_vehicle_count": rng.integers(0, 6, n).astype(np.int32),
        "hd_buy_potential": pots[rng.integers(0, 4, n)],
    })


def gen_time_dim(sf: float, seed: int = 40) -> pa.Table:
    secs = np.arange(86_400, dtype=np.int64)
    hours = secs // 3600
    meal = np.where(
        (hours >= 6) & (hours <= 9), "breakfast",
        np.where((hours >= 11) & (hours <= 13), "lunch",
                 np.where((hours >= 17) & (hours <= 20), "dinner", "")))
    return pa.table({
        "t_time_sk": secs,
        "t_hour": hours.astype(np.int32),
        "t_minute": (secs // 60 % 60).astype(np.int32),
        "t_meal_time": meal.astype(object),
    })


def gen_store(sf: float, seed: int = 41) -> pa.Table:
    n = max(int(12 * sf), 2)
    rng = np.random.default_rng(seed)
    cities = np.array(["Midway", "Fairview", "Oakdale", "Riverside"],
                      dtype=object)
    counties = np.array(["Williamson County", "Franklin Parish",
                         "Bronx County", "Orange County"], dtype=object)
    states = np.array(["TN", "TX", "OH", "CA"], dtype=object)
    stypes = np.array(["Ave", "St", "Blvd"], dtype=object)
    return pa.table({
        "s_store_sk": np.arange(1, n + 1, dtype=np.int64),
        "s_store_id": np.array([f"AAAAAAAA{i:04d}" for i in range(1, n + 1)],
                               dtype=object),
        "s_store_name": np.array([f"ese{i}" for i in range(1, n + 1)],
                                 dtype=object),
        "s_gmt_offset": np.where(rng.random(n) < 0.7, -5.0, -6.0),
        "s_city": cities[rng.integers(0, 4, n)],
        "s_county": counties[rng.integers(0, 4, n)],
        "s_state": states[rng.integers(0, 4, n)],
        "s_zip": np.array([f"{z:05d}" for z in
                           rng.integers(10000, 99999, n)], dtype=object),
        "s_street_number": np.array([str(i * 10) for i in range(1, n + 1)],
                                    dtype=object),
        "s_street_name": np.array([f"Main {i}" for i in range(1, n + 1)],
                                  dtype=object),
        "s_street_type": stypes[rng.integers(0, 3, n)],
        "s_suite_number": np.array([f"Suite {i}" for i in range(1, n + 1)],
                                   dtype=object),
        "s_number_employees": rng.integers(200, 300, n).astype(np.int32),
        "s_company_id": rng.integers(1, 3, n).astype(np.int32),
    })




def gen_reason(sf: float, seed: int = 50) -> pa.Table:
    n = 35
    return pa.table({
        "r_reason_sk": np.arange(1, n + 1, dtype=np.int64),
        "r_reason_desc": np.array([f"reason {i}" for i in range(1, n + 1)],
                                  dtype=object),
    })


def gen_catalog_returns(sf: float, seed: int = 51) -> pa.Table:
    """~8% of catalog_sales return; keys sampled so (order, item) joins
    hit (q40)."""
    rng = np.random.default_rng(seed)
    sales = gen_catalog_sales(sf)
    n_s = sales.num_rows
    n = max(n_s // 12, 20)
    idx = rng.choice(n_s, n, replace=False)
    return pa.table({
        "cr_item_sk": sales["cs_item_sk"].to_numpy()[idx],
        "cr_order_number": sales["cs_order_number"].to_numpy()[idx],
        "cr_refunded_cash": np.round(rng.random(n) * 100, 2),
    })


def gen_customer(sf: float, seed: int = 42) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(100_000 * sf), 20)
    n_demo = max(int(1_000 * sf), 10)
    n_addr = max(int(50_000 * sf), 15)
    firsts = np.array(["James", "Mary", "John", "Ana", "Wei", "Olu",
                       "Kei", "Lena"], dtype=object)
    lasts = np.array(["Smith", "Garcia", "Chen", "Okafor", "Sato",
                      "Novak"], dtype=object)
    sals = np.array(["Mr.", "Ms.", "Dr.", "Sir"], dtype=object)
    return pa.table({
        "c_customer_sk": np.arange(1, n + 1, dtype=np.int64),
        "c_customer_id": np.array(
            [f"AAAAAAAA{i:08d}" for i in range(1, n + 1)], dtype=object),
        "c_current_cdemo_sk": rng.integers(1, n_demo + 1, n
                                           ).astype(np.int64),
        "c_current_addr_sk": rng.integers(1, n_addr + 1, n
                                          ).astype(np.int64),
        "c_first_name": firsts[rng.integers(0, len(firsts), n)],
        "c_last_name": lasts[rng.integers(0, len(lasts), n)],
        "c_salutation": sals[rng.integers(0, 4, n)],
        "c_preferred_cust_flag": np.array(["Y", "N"], dtype=object)[
            rng.integers(0, 2, n)],
    })


_CA_STATES = np.array(["KY", "GA", "NM", "MT", "OR", "IN", "WI", "MO",
                       "WV", "CA", "TX", "NY"], dtype=object)
_CA_ZIP_POOL = np.array(
    ["85669", "86197", "88274", "83405", "86475", "85392", "85460",
     "80348", "81792", "10001", "94103", "73301", "30301", "98101",
     "60601", "33101"], dtype=object)


def gen_customer_address(sf: float, seed: int = 44) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(50_000 * sf), 15)
    countries = np.array(["United States", "Canada", "Mexico"],
                         dtype=object)
    cities = np.array(["Midway", "Fairview", "Oakdale", "Riverside",
                       "Pleasant Hill"], dtype=object)
    return pa.table({
        "ca_address_sk": np.arange(1, n + 1, dtype=np.int64),
        "ca_country": countries[rng.integers(0, 3, n)],
        "ca_state": _CA_STATES[rng.integers(0, 12, n)],
        "ca_city": cities[rng.integers(0, 5, n)],
        "ca_zip": _CA_ZIP_POOL[rng.integers(0, len(_CA_ZIP_POOL), n)],
        "ca_gmt_offset": np.where(rng.random(n) < 0.6, -5.0, -7.0),
    })


@functools.lru_cache(maxsize=2)  # returns generators re-sample it
def gen_web_sales(sf: float, seed: int = 46) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(700_000 * sf), 200)
    n_cust = max(int(100_000 * sf), 20)
    n_item = max(int(18_000 * sf), 50)
    n_addr = max(int(50_000 * sf), 15)
    n_wp = max(int(60 * sf), 5)
    n_wh = max(int(5 * sf), 2)
    return pa.table({
        "ws_sold_date_sk": rng.integers(2450815, 2450815 + 5 * 365, n
                                        ).astype(np.int64),
        "ws_sold_time_sk": rng.integers(0, 86_400, n).astype(np.int64),
        "ws_bill_customer_sk": rng.integers(1, n_cust + 1, n
                                            ).astype(np.int64),
        "ws_bill_addr_sk": rng.integers(1, n_addr + 1, n
                                        ).astype(np.int64),
        "ws_item_sk": rng.integers(1, n_item + 1, n).astype(np.int64),
        "ws_order_number": rng.integers(1, max(n // 3, 2), n
                                        ).astype(np.int64),
        "ws_quantity": rng.integers(1, 101, n).astype(np.int32),
        "ws_warehouse_sk": rng.integers(1, n_wh + 1, n).astype(np.int64),
        "ws_web_page_sk": rng.integers(1, n_wp + 1, n).astype(np.int64),
        "ws_ship_hdemo_sk": rng.integers(1, 7201, n).astype(np.int64),
        "ws_sales_price": np.round(rng.random(n) * 200, 2),
        "ws_net_paid": np.round(rng.random(n) * 300, 2),
        "ws_ext_list_price": np.round(rng.random(n) * 250, 2),
        "ws_ext_wholesale_cost": np.round(rng.random(n) * 100, 2),
        "ws_ext_discount_amt": np.round(rng.random(n) * 40, 2),
        "ws_ext_sales_price": np.round(rng.random(n) * 200, 2),
    })


def gen_web_returns(sf: float, seed: int = 48) -> pa.Table:
    """~10% of web_sales return; keys sampled from the sales so the
    (order, item) two-key left join hits."""
    rng = np.random.default_rng(seed)
    sales = gen_web_sales(sf)
    n_s = sales.num_rows
    n = max(n_s // 10, 20)
    idx = rng.choice(n_s, n, replace=False)
    return pa.table({
        "wr_order_number": sales["ws_order_number"].to_numpy()[idx],
        "wr_item_sk": sales["ws_item_sk"].to_numpy()[idx],
        "wr_refunded_cash": np.round(rng.random(n) * 100, 2),
    })


GENERATORS = {
    "date_dim": gen_date_dim,
    "item": gen_item,
    "store_sales": gen_store_sales,
    "catalog_sales": gen_catalog_sales,
    "inventory": gen_inventory,
    "warehouse": gen_warehouse,
    "customer_demographics": gen_customer_demographics,
    "promotion": gen_promotion,
    "household_demographics": gen_household_demographics,
    "time_dim": gen_time_dim,
    "store": gen_store,
    "store_returns": gen_store_returns,
    "web_page": gen_web_page,
    "reason": gen_reason,
    "catalog_returns": gen_catalog_returns,
    "customer": gen_customer,
    "customer_address": gen_customer_address,
    "web_sales": gen_web_sales,
    "web_returns": gen_web_returns,
}


def write_tables(data_dir: str, sf: float, tables=None,
                 files_per_table: int = 4) -> None:
    os.makedirs(data_dir, exist_ok=True)
    for name in tables or GENERATORS:
        table = GENERATORS[name](sf)
        tdir = os.path.join(data_dir, name)
        os.makedirs(tdir, exist_ok=True)
        per = -(-table.num_rows // files_per_table)
        for i in range(files_per_table):
            chunk = table.slice(i * per, per)
            if chunk.num_rows:
                pq.write_table(chunk,
                               os.path.join(tdir,
                                            f"part-{i:03d}.parquet"))


# ---------------------------------------------------------------------------
# queries


def ref(i, t):
    return BoundReference(i, t)


def _scan(data_dir: str, table: str, columns):
    return pn.ScanNode(ParquetSource(os.path.join(data_dir, table),
                                     columns=columns))


def _report_query(data_dir: str, item_filter, group_ordinal_names,
                  date_filter_moy=11, date_filter_year=None):
    """The q3/q42/q52/q55 family: date_dim x store_sales x item,
    filtered on month (and maybe year) + an item attribute, grouped on
    (d_year, item attrs), sum(ss_ext_sales_price) descending."""
    dd_cond = P.EqualTo(ref(1, dt.INT32),
                        Literal(date_filter_moy, dt.INT32))
    if date_filter_year is not None:
        dd_cond = P.And(dd_cond,
                        P.EqualTo(ref(2, dt.INT32),
                                  Literal(date_filter_year, dt.INT32)))
    date_dim = pn.FilterNode(
        dd_cond, _scan(data_dir, "date_dim",
                       ["d_date_sk", "d_moy", "d_year"]))
    sales = _scan(data_dir, "store_sales",
                  ["ss_sold_date_sk", "ss_item_sk",
                   "ss_ext_sales_price"])
    item_cols, item_pred, group_item_ordinals = item_filter
    item = pn.FilterNode(item_pred, _scan(data_dir, "item", item_cols))
    # [d_date_sk 0, d_moy 1, d_year 2, ss_sold_date_sk 3, ss_item_sk 4,
    #  ss_ext_sales_price 5]
    ds = pn.JoinNode("inner", date_dim, sales, [0], [0])
    # + item cols at 6..
    dsi = pn.JoinNode("inner", ds, item, [4], [0])
    group_refs = [ref(2, dt.INT32)] + \
        [ref(6 + o, t) for o, t in group_item_ordinals]
    proj = pn.ProjectNode(
        [Alias(e, n) for e, n in zip(group_refs, group_ordinal_names)] +
        [Alias(ref(5, dt.FLOAT64), "price")], dsi)
    k = len(group_refs)
    agg = pn.AggregateNode(
        [ref(i, e.dtype) for i, e in enumerate(group_refs)],
        [pn.AggCall(A.Sum(ref(k, dt.FLOAT64)), "sum_agg")],
        proj, grouping_names=group_ordinal_names)
    sort = pn.SortNode(
        [SortKeySpec.spark_default(k, ascending=False)] +
        [SortKeySpec.spark_default(i) for i in range(k)], agg)
    return pn.LimitNode(100, sort)


def q3(data_dir: str) -> pn.PlanNode:
    """Brand revenue for one manufacturer in November
    (TpcdsLikeSpark.scala q3)."""
    item_filter = (["i_item_sk", "i_brand_id", "i_brand",
                    "i_manufact_id"],
                   P.EqualTo(ref(3, dt.INT32), Literal(128, dt.INT32)),
                   [(1, dt.INT32), (2, dt.STRING)])
    return _report_query(data_dir, item_filter,
                         ["d_year", "brand_id", "brand"])


def q42(data_dir: str) -> pn.PlanNode:
    """Category revenue for one manager-year (q42)."""
    item_filter = (["i_item_sk", "i_category_id", "i_category",
                    "i_manager_id"],
                   P.EqualTo(ref(3, dt.INT32), Literal(1, dt.INT32)),
                   [(1, dt.INT32), (2, dt.STRING)])
    return _report_query(data_dir, item_filter,
                         ["d_year", "i_category_id", "i_category"],
                         date_filter_year=2000)


def q52(data_dir: str) -> pn.PlanNode:
    """Brand revenue for one manager-year (q52)."""
    item_filter = (["i_item_sk", "i_brand_id", "i_brand",
                    "i_manager_id"],
                   P.EqualTo(ref(3, dt.INT32), Literal(1, dt.INT32)),
                   [(1, dt.INT32), (2, dt.STRING)])
    return _report_query(data_dir, item_filter,
                         ["d_year", "brand_id", "brand"],
                         date_filter_year=2000)


def q55(data_dir: str) -> pn.PlanNode:
    """Brand revenue, manager 28, one month (q55)."""
    item_filter = (["i_item_sk", "i_brand_id", "i_brand",
                    "i_manager_id"],
                   P.EqualTo(ref(3, dt.INT32), Literal(28, dt.INT32)),
                   [(1, dt.INT32), (2, dt.STRING)])
    return _report_query(data_dir, item_filter,
                         ["d_year", "brand_id", "brand"],
                         date_filter_year=1999)


def q72(data_dir: str) -> pn.PlanNode:
    """q72-like: catalog_sales x inventory (same item, on-hand below
    ordered quantity) x warehouse x item x date_dim — the infamous
    expansion join, simplified to the tables generated here."""
    cs = _scan(data_dir, "catalog_sales",
               ["cs_sold_date_sk", "cs_item_sk", "cs_quantity"])
    inv = _scan(data_dir, "inventory",
                ["inv_date_sk", "inv_item_sk", "inv_warehouse_sk",
                 "inv_quantity_on_hand"])
    # join on item; keep only rows where on-hand < ordered (the q72
    # shortage condition) — an equi-join with an inter-fact residual
    # [cs 0-2, inv 3-6]
    short = pn.JoinNode(
        "inner", cs, inv, [1], [1],
        condition=P.LessThan(ref(6, dt.INT32), ref(2, dt.INT32)))
    wh = _scan(data_dir, "warehouse",
               ["w_warehouse_sk", "w_warehouse_name"])
    # + [w_warehouse_sk 7, w_warehouse_name 8]
    sw = pn.JoinNode("inner", short, wh, [5], [0])
    item = _scan(data_dir, "item", ["i_item_sk", "i_item_desc"])
    # + [i_item_sk 9, i_item_desc 10]
    swi = pn.JoinNode("inner", sw, item, [1], [0])
    dd = _scan(data_dir, "date_dim", ["d_date_sk", "d_week_seq"])
    # + [d_date_sk 11, d_week_seq 12]
    swid = pn.JoinNode("inner", swi, dd, [0], [0])
    agg = pn.AggregateNode(
        [ref(10, dt.STRING), ref(8, dt.STRING), ref(12, dt.INT32)],
        [pn.AggCall(A.Count(), "no_promo")],
        swid, grouping_names=["i_item_desc", "w_warehouse_name",
                              "d_week_seq"])
    sort = pn.SortNode([SortKeySpec.spark_default(3, ascending=False),
                        SortKeySpec.spark_default(0),
                        SortKeySpec.spark_default(1),
                        SortKeySpec.spark_default(2)], agg)
    return pn.LimitNode(100, sort)


def q7(data_dir: str) -> pn.PlanNode:
    """Promotional-item averages per item for one demographic slice
    (TpcdsLikeSpark q7): 5-way join + multi-average group-by."""
    ss = _scan(data_dir, "store_sales",
               ["ss_sold_date_sk", "ss_item_sk", "ss_cdemo_sk",
                "ss_promo_sk", "ss_quantity", "ss_list_price",
                "ss_coupon_amt", "ss_sales_price"])
    cd = pn.FilterNode(
        P.And(P.EqualTo(ref(1, dt.STRING), Literal("M")),
              P.And(P.EqualTo(ref(2, dt.STRING), Literal("S")),
                    P.EqualTo(ref(3, dt.STRING), Literal("College")))),
        _scan(data_dir, "customer_demographics",
              ["cd_demo_sk", "cd_gender", "cd_marital_status",
               "cd_education_status"]))
    # + [cd 8..11]
    s1 = pn.JoinNode("inner", ss, cd, [2], [0])
    dd = pn.FilterNode(
        P.EqualTo(ref(1, dt.INT32), Literal(2000, dt.INT32)),
        _scan(data_dir, "date_dim", ["d_date_sk", "d_year"]))
    # + [d_date_sk 12, d_year 13]
    s2 = pn.JoinNode("inner", s1, dd, [0], [0])
    promo = pn.FilterNode(
        P.Or(P.EqualTo(ref(1, dt.STRING), Literal("N")),
             P.EqualTo(ref(2, dt.STRING), Literal("N"))),
        _scan(data_dir, "promotion",
              ["p_promo_sk", "p_channel_email", "p_channel_event"]))
    # + [p_promo_sk 14, p_channel_email 15, p_channel_event 16]
    s3 = pn.JoinNode("inner", s2, promo, [3], [0])
    item = _scan(data_dir, "item", ["i_item_sk", "i_item_desc"])
    # + [i_item_sk 17, i_item_desc 18]
    s4 = pn.JoinNode("inner", s3, item, [1], [0])
    from spark_rapids_tpu.expressions.cast import Cast

    agg = pn.AggregateNode(
        [ref(18, dt.STRING)],
        [pn.AggCall(A.Average(Cast(ref(4, dt.INT32), dt.FLOAT64)),
                    "agg1"),
         pn.AggCall(A.Average(ref(5, dt.FLOAT64)), "agg2"),
         pn.AggCall(A.Average(ref(6, dt.FLOAT64)), "agg3"),
         pn.AggCall(A.Average(ref(7, dt.FLOAT64)), "agg4")],
        s4, grouping_names=["i_item_desc"])
    sort = pn.SortNode([SortKeySpec.spark_default(0)], agg)
    return pn.LimitNode(100, sort)


def q96(data_dir: str) -> pn.PlanNode:
    """Count of evening purchases by large households at one store
    (TpcdsLikeSpark q96): pure 4-way join + count."""
    ss = _scan(data_dir, "store_sales",
               ["ss_sold_time_sk", "ss_hdemo_sk", "ss_store_sk"])
    hd = pn.FilterNode(
        P.EqualTo(ref(1, dt.INT32), Literal(7, dt.INT32)),
        _scan(data_dir, "household_demographics",
              ["hd_demo_sk", "hd_dep_count"]))
    td = pn.FilterNode(
        P.And(P.EqualTo(ref(1, dt.INT32), Literal(20, dt.INT32)),
              P.GreaterThanOrEqual(ref(2, dt.INT32),
                                   Literal(30, dt.INT32))),
        _scan(data_dir, "time_dim", ["t_time_sk", "t_hour", "t_minute"]))
    store = pn.FilterNode(
        P.EqualTo(ref(1, dt.STRING), Literal("ese1")),
        _scan(data_dir, "store", ["s_store_sk", "s_store_name"]))
    s1 = pn.JoinNode("inner", ss, hd, [1], [0])
    s2 = pn.JoinNode("inner", s1, td, [0], [0])
    s3 = pn.JoinNode("inner", s2, store, [2], [0])
    return pn.AggregateNode([], [pn.AggCall(A.Count(), "cnt")], s3)


def q98(data_dir: str) -> pn.PlanNode:
    """Revenue share within item class (TpcdsLikeSpark q98): the
    windowed-aggregate shape — per-item revenue plus a partitioned
    window SUM over the class for the ratio."""
    ss = _scan(data_dir, "store_sales",
               ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    dd = pn.FilterNode(
        P.EqualTo(ref(2, dt.INT32), Literal(1999, dt.INT32)),
        _scan(data_dir, "date_dim",
              ["d_date_sk", "d_moy", "d_year"]))
    item = pn.FilterNode(
        P.In(ref(2, dt.STRING),
             [Literal("Sports"), Literal("Books"), Literal("Home")]),
        _scan(data_dir, "item",
              ["i_item_sk", "i_class_id", "i_category",
               "i_item_desc"]))
    s1 = pn.JoinNode("inner", ss, dd, [0], [0])
    # + item at 6..9
    s2 = pn.JoinNode("inner", s1, item, [1], [0])
    per_item = pn.AggregateNode(
        [ref(9, dt.STRING), ref(7, dt.INT32), ref(8, dt.STRING)],
        [pn.AggCall(A.Sum(ref(2, dt.FLOAT64)), "itemrevenue")],
        s2, grouping_names=["i_item_desc", "i_class_id", "i_category"])
    # windowed class total: partition by class, unbounded frame sum
    win = pn.WindowNode(
        [1], [],
        [pn.WindowCall(A.Sum(ref(3, dt.FLOAT64)), "classrevenue",
                       pn.WindowFrame(None, None))],
        per_item)
    share = pn.ProjectNode(
        [Alias(ref(0, dt.STRING), "i_item_desc"),
         Alias(ref(2, dt.STRING), "i_category"),
         Alias(ref(3, dt.FLOAT64), "itemrevenue"),
         Alias(ar.Multiply(
             Literal(100.0),
             ar.Divide(ref(3, dt.FLOAT64), ref(4, dt.FLOAT64))),
             "revenueratio")], win)
    sort = pn.SortNode([SortKeySpec.spark_default(1),
                        SortKeySpec.spark_default(3),
                        SortKeySpec.spark_default(0)], share)
    return pn.LimitNode(100, sort)


QUERIES = {"tpcds_q3": q3, "tpcds_q7": q7, "tpcds_q42": q42,
           "tpcds_q52": q52, "tpcds_q55": q55, "tpcds_q72": q72,
           "tpcds_q96": q96, "tpcds_q98": q98}

# ---------------------------------------------------------------------------
# SQL-text queries (TpcdsLikeSpark.scala embeds the public TPC-DS SQL; here
# the same spec queries run through the engine's own SQL front end).
# Literals are adapted to the generated data's ranges: dates 1998-2002
# (d_month_seq 0-59 from 1998-01), item prices 0.5-2.5, coupon amounts
# 0-50, store names "ese<i>"; q13/q48 hoist the equi-join conjuncts every
# OR branch repeats (semantics-preserving factoring the Spark optimizer
# performs); q50's backtick aliases and q90's decimal casts use portable
# spellings.
# ---------------------------------------------------------------------------


def _session(data_dir: str):
    from spark_rapids_tpu.api import Session

    s = Session()
    for t in GENERATORS:
        s.register_parquet(t, os.path.join(data_dir, t))
    return s


def _sql_query(final_sql: str):
    def factory(data_dir: str) -> pn.PlanNode:
        return _session(data_dir).sql(final_sql)._plan

    return factory


TPCDS_SQL = {
    "q6": """
SELECT a.ca_state state, count(*) cnt
FROM customer_address a, customer c, store_sales s, date_dim d, item i,
  (SELECT i_category cat, avg(i_current_price) * 1.2 AS thresh
   FROM item GROUP BY i_category) avgp
WHERE a.ca_address_sk = c.c_current_addr_sk
AND c.c_customer_sk = s.ss_customer_sk
AND s.ss_sold_date_sk = d.d_date_sk
AND s.ss_item_sk = i.i_item_sk
AND d.d_month_seq = (SELECT min(d_month_seq) FROM date_dim
                     WHERE d_year = 2001 AND d_moy = 1)
AND avgp.cat = i.i_category
AND i.i_current_price > avgp.thresh
GROUP BY a.ca_state HAVING count(*) >= 10
ORDER BY cnt, state LIMIT 100
""",
    "q9": """
SELECT CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20) > 409
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20) END bucket1,
       CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40) > 512
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40) END bucket2,
       CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60) > 622
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60) END bucket3
FROM reason WHERE r_reason_sk = 1
""",
    "q13": """
SELECT avg(ss_quantity), avg(ss_ext_sales_price),
       avg(ss_ext_wholesale_cost), sum(ss_ext_wholesale_cost)
FROM store_sales, store, customer_demographics,
     household_demographics, customer_address, date_dim
WHERE s_store_sk = ss_store_sk
AND ss_sold_date_sk = d_date_sk AND d_year = 2001
AND ss_hdemo_sk = hd_demo_sk
AND cd_demo_sk = ss_cdemo_sk
AND ss_addr_sk = ca_address_sk
AND ((cd_marital_status = 'M' AND cd_education_status = 'Advanced Degree'
      AND ss_sales_price BETWEEN 100.0 AND 150.0 AND hd_dep_count = 3)
  OR (cd_marital_status = 'S' AND cd_education_status = 'College'
      AND ss_sales_price BETWEEN 50.0 AND 100.0 AND hd_dep_count = 1)
  OR (cd_marital_status = 'W' AND cd_education_status = '2 yr Degree'
      AND ss_sales_price BETWEEN 150.0 AND 200.0 AND hd_dep_count = 1))
AND ((ca_country = 'United States' AND ca_state IN ('TX', 'OR', 'KY')
      AND ss_net_profit BETWEEN 100 AND 200)
  OR (ca_country = 'United States' AND ca_state IN ('OR', 'NM', 'KY')
      AND ss_net_profit BETWEEN 150 AND 300)
  OR (ca_country = 'United States' AND ca_state IN ('CA', 'TX', 'MO')
      AND ss_net_profit BETWEEN 50 AND 250))
""",
    "q15": """
SELECT ca_zip, sum(cs_sales_price) AS total
FROM catalog_sales, customer, customer_address, date_dim
WHERE cs_bill_customer_sk = c_customer_sk
AND c_current_addr_sk = ca_address_sk
AND (substring(ca_zip, 1, 5) IN ('85669', '86197', '88274', '83405',
                                 '86475', '85392', '85460', '80348',
                                 '81792')
     OR ca_state IN ('CA', 'WI', 'GA')
     OR cs_sales_price > 180)
AND cs_sold_date_sk = d_date_sk
AND d_qoy = 2 AND d_year = 2001
GROUP BY ca_zip ORDER BY ca_zip LIMIT 100
""",
    "q19": """
SELECT i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item, customer, customer_address, store
WHERE d_date_sk = ss_sold_date_sk
AND ss_item_sk = i_item_sk
AND i_manager_id = 8
AND d_moy = 11 AND d_year = 1998
AND ss_customer_sk = c_customer_sk
AND c_current_addr_sk = ca_address_sk
AND substring(ca_zip, 1, 5) <> substring(s_zip, 1, 5)
AND ss_store_sk = s_store_sk
GROUP BY i_brand, i_brand_id, i_manufact_id, i_manufact
ORDER BY ext_price DESC, brand, brand_id, i_manufact_id, i_manufact
LIMIT 100
""",
    "q25": """
SELECT i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) AS store_sales_profit,
       sum(sr_net_loss) AS store_returns_loss,
       sum(cs_net_profit) AS catalog_sales_profit
FROM store_sales, store_returns, catalog_sales, date_dim d1,
     date_dim d2, date_dim d3, store, item
WHERE d1.d_moy = 4 AND d1.d_year = 2001
AND d1.d_date_sk = ss_sold_date_sk
AND i_item_sk = ss_item_sk
AND s_store_sk = ss_store_sk
AND ss_customer_sk = sr_customer_sk
AND ss_item_sk = sr_item_sk
AND ss_ticket_number = sr_ticket_number
AND sr_returned_date_sk = d2.d_date_sk
AND d2.d_moy BETWEEN 4 AND 10 AND d2.d_year = 2001
AND sr_customer_sk = cs_bill_customer_sk
AND sr_item_sk = cs_item_sk
AND cs_sold_date_sk = d3.d_date_sk
AND d3.d_moy BETWEEN 4 AND 10 AND d3.d_year = 2001
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id, i_item_desc, s_store_id, s_store_name
LIMIT 100
""",
    "q28": """
SELECT * FROM
(SELECT avg(ss_list_price) B1_LP, count(ss_list_price) B1_CNT,
        count(DISTINCT ss_list_price) B1_CNTD
 FROM store_sales WHERE ss_quantity BETWEEN 0 AND 5
 AND (ss_list_price BETWEEN 8 AND 18
      OR ss_coupon_amt BETWEEN 10 AND 20
      OR ss_wholesale_cost BETWEEN 57 AND 77)) B1 CROSS JOIN
(SELECT avg(ss_list_price) B2_LP, count(ss_list_price) B2_CNT,
        count(DISTINCT ss_list_price) B2_CNTD
 FROM store_sales WHERE ss_quantity BETWEEN 6 AND 10
 AND (ss_list_price BETWEEN 90 AND 100
      OR ss_coupon_amt BETWEEN 20 AND 30
      OR ss_wholesale_cost BETWEEN 31 AND 51)) B2 CROSS JOIN
(SELECT avg(ss_list_price) B3_LP, count(ss_list_price) B3_CNT,
        count(DISTINCT ss_list_price) B3_CNTD
 FROM store_sales WHERE ss_quantity BETWEEN 11 AND 15
 AND (ss_list_price BETWEEN 142 AND 152
      OR ss_coupon_amt BETWEEN 30 AND 40
      OR ss_wholesale_cost BETWEEN 79 AND 99)) B3
LIMIT 100
""",
    "q33": """
WITH ss AS (
  SELECT i_manufact_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category IN ('Electronics'))
  AND ss_item_sk = i_item_sk
  AND ss_sold_date_sk = d_date_sk
  AND d_year = 1998 AND d_moy = 5
  AND ss_addr_sk = ca_address_sk AND ca_gmt_offset = -5.0
  GROUP BY i_manufact_id),
cs AS (
  SELECT i_manufact_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category IN ('Electronics'))
  AND cs_item_sk = i_item_sk
  AND cs_sold_date_sk = d_date_sk
  AND d_year = 1998 AND d_moy = 5
  AND cs_bill_addr_sk = ca_address_sk AND ca_gmt_offset = -5.0
  GROUP BY i_manufact_id),
ws AS (
  SELECT i_manufact_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category IN ('Electronics'))
  AND ws_item_sk = i_item_sk
  AND ws_sold_date_sk = d_date_sk
  AND d_year = 1998 AND d_moy = 5
  AND ws_bill_addr_sk = ca_address_sk AND ca_gmt_offset = -5.0
  GROUP BY i_manufact_id)
SELECT i_manufact_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss UNION ALL
      SELECT * FROM cs UNION ALL
      SELECT * FROM ws) tmp1
GROUP BY i_manufact_id
ORDER BY total_sales, i_manufact_id
LIMIT 100
""",
    "q37": """
SELECT i_item_id, i_item_desc, i_current_price
FROM item, inventory, date_dim, catalog_sales
WHERE i_current_price BETWEEN 1.0 AND 1.8
AND inv_item_sk = i_item_sk
AND d_date_sk = inv_date_sk
AND d_date BETWEEN cast('2000-02-01' AS date)
              AND (cast('2000-02-01' AS date) + INTERVAL '60' day)
AND i_manufact_id IN (677, 940, 694, 808)
AND inv_quantity_on_hand BETWEEN 100 AND 500
AND cs_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id LIMIT 100
""",
    "q40": """
SELECT w_state, i_item_id,
  sum(CASE WHEN (d_date < cast('2000-03-11' AS date))
      THEN cs_sales_price - coalesce(cr_refunded_cash, 0)
      ELSE 0 END) AS sales_before,
  sum(CASE WHEN (d_date >= cast('2000-03-11' AS date))
      THEN cs_sales_price - coalesce(cr_refunded_cash, 0)
      ELSE 0 END) AS sales_after
FROM catalog_sales LEFT OUTER JOIN catalog_returns ON
  (cs_order_number = cr_order_number AND cs_item_sk = cr_item_sk),
  warehouse, item, date_dim
WHERE i_current_price BETWEEN 0.99 AND 1.49
AND i_item_sk = cs_item_sk
AND cs_warehouse_sk = w_warehouse_sk
AND cs_sold_date_sk = d_date_sk
AND d_date BETWEEN (cast('2000-03-11' AS date) - INTERVAL '30' day)
              AND (cast('2000-03-11' AS date) + INTERVAL '30' day)
GROUP BY w_state, i_item_id
ORDER BY w_state, i_item_id
LIMIT 100
""",
    "q43": """
SELECT s_store_name, s_store_id,
  sum(CASE WHEN (d_day_name = 'Sunday') THEN ss_sales_price
      ELSE null END) sun_sales,
  sum(CASE WHEN (d_day_name = 'Monday') THEN ss_sales_price
      ELSE null END) mon_sales,
  sum(CASE WHEN (d_day_name = 'Tuesday') THEN ss_sales_price
      ELSE null END) tue_sales,
  sum(CASE WHEN (d_day_name = 'Wednesday') THEN ss_sales_price
      ELSE null END) wed_sales,
  sum(CASE WHEN (d_day_name = 'Thursday') THEN ss_sales_price
      ELSE null END) thu_sales,
  sum(CASE WHEN (d_day_name = 'Friday') THEN ss_sales_price
      ELSE null END) fri_sales,
  sum(CASE WHEN (d_day_name = 'Saturday') THEN ss_sales_price
      ELSE null END) sat_sales
FROM date_dim, store_sales, store
WHERE d_date_sk = ss_sold_date_sk
AND s_store_sk = ss_store_sk
AND s_gmt_offset = -5.0
AND d_year = 2000
GROUP BY s_store_name, s_store_id
ORDER BY s_store_name, s_store_id, sun_sales, mon_sales, tue_sales,
         wed_sales, thu_sales, fri_sales, sat_sales
LIMIT 100
""",
    "q46": """
SELECT c_last_name, c_first_name, ca_city, bought_city,
       ss_ticket_number, amt, profit
FROM (SELECT ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      FROM store_sales, date_dim, store, household_demographics,
           customer_address
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
      AND store_sales.ss_store_sk = store.s_store_sk
      AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
      AND store_sales.ss_addr_sk = customer_address.ca_address_sk
      AND (household_demographics.hd_dep_count = 4 OR
           household_demographics.hd_vehicle_count = 3)
      AND date_dim.d_dow IN (6, 0)
      AND date_dim.d_year IN (1999, 2000, 2001)
      AND store.s_city IN ('Fairview', 'Midway')
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk,
               ca_city) dn, customer, customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
AND customer.c_current_addr_sk = current_addr.ca_address_sk
AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, c_first_name, ca_city, bought_city,
         ss_ticket_number
LIMIT 100
""",
    "q48": """
SELECT sum(ss_quantity) AS q
FROM store_sales, store, customer_demographics, customer_address,
     date_dim
WHERE s_store_sk = ss_store_sk
AND ss_sold_date_sk = d_date_sk AND d_year = 2000
AND cd_demo_sk = ss_cdemo_sk
AND ss_addr_sk = ca_address_sk
AND ((cd_marital_status = 'M' AND cd_education_status = '4 yr Degree'
      AND ss_sales_price BETWEEN 100.0 AND 150.0)
  OR (cd_marital_status = 'D' AND cd_education_status = '2 yr Degree'
      AND ss_sales_price BETWEEN 50.0 AND 100.0)
  OR (cd_marital_status = 'S' AND cd_education_status = 'College'
      AND ss_sales_price BETWEEN 150.0 AND 200.0))
AND ((ca_country = 'United States' AND ca_state IN ('CA', 'OR', 'TX')
      AND ss_net_profit BETWEEN 0 AND 2000)
  OR (ca_country = 'United States' AND ca_state IN ('OR', 'NM', 'KY')
      AND ss_net_profit BETWEEN 150 AND 3000)
  OR (ca_country = 'United States' AND ca_state IN ('GA', 'TX', 'MO')
      AND ss_net_profit BETWEEN 50 AND 25000))
""",
    "q50": """
SELECT s_store_name, s_company_id, s_street_number, s_street_name,
       s_street_type, s_suite_number, s_city, s_county, s_state, s_zip,
  sum(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk <= 30)
      THEN 1 ELSE 0 END) AS d30,
  sum(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk > 30) AND
           (sr_returned_date_sk - ss_sold_date_sk <= 60)
      THEN 1 ELSE 0 END) AS d31_60,
  sum(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk > 60) AND
           (sr_returned_date_sk - ss_sold_date_sk <= 90)
      THEN 1 ELSE 0 END) AS d61_90,
  sum(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk > 90)
      THEN 1 ELSE 0 END) AS d_over_90
FROM store_sales, store_returns, store, date_dim d1, date_dim d2
WHERE d2.d_year = 2001 AND d2.d_moy = 8
AND ss_ticket_number = sr_ticket_number
AND ss_item_sk = sr_item_sk
AND ss_sold_date_sk = d1.d_date_sk
AND sr_returned_date_sk = d2.d_date_sk
AND ss_customer_sk = sr_customer_sk
AND ss_store_sk = s_store_sk
GROUP BY s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state,
         s_zip
ORDER BY s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state,
         s_zip
LIMIT 100
""",
    "q59": """
WITH wss AS (
  SELECT d_week_seq, ss_store_sk,
    sum(CASE WHEN (d_day_name = 'Sunday') THEN ss_sales_price
        ELSE null END) sun_sales,
    sum(CASE WHEN (d_day_name = 'Monday') THEN ss_sales_price
        ELSE null END) mon_sales,
    sum(CASE WHEN (d_day_name = 'Friday') THEN ss_sales_price
        ELSE null END) fri_sales,
    sum(CASE WHEN (d_day_name = 'Saturday') THEN ss_sales_price
        ELSE null END) sat_sales
  FROM store_sales, date_dim
  WHERE d_date_sk = ss_sold_date_sk
  GROUP BY d_week_seq, ss_store_sk)
SELECT s_store_name1, s_store_id1, d_week_seq1,
       sun_sales1 / sun_sales2, mon_sales1 / mon_sales2,
       fri_sales1 / fri_sales2, sat_sales1 / sat_sales2
FROM
(SELECT s_store_name s_store_name1, wss.d_week_seq d_week_seq1,
        s_store_id s_store_id1, sun_sales sun_sales1,
        mon_sales mon_sales1, fri_sales fri_sales1,
        sat_sales sat_sales1
 FROM wss, store, date_dim d
 WHERE d.d_week_seq = wss.d_week_seq AND ss_store_sk = s_store_sk
 AND d_month_seq BETWEEN 24 AND 35) y,
(SELECT s_store_name s_store_name2, wss.d_week_seq d_week_seq2,
        s_store_id s_store_id2, sun_sales sun_sales2,
        mon_sales mon_sales2, fri_sales fri_sales2,
        sat_sales sat_sales2
 FROM wss, store, date_dim d
 WHERE d.d_week_seq = wss.d_week_seq AND ss_store_sk = s_store_sk
 AND d_month_seq BETWEEN 36 AND 47) x
WHERE s_store_id1 = s_store_id2
AND d_week_seq1 = d_week_seq2 - 52
ORDER BY s_store_name1, s_store_id1, d_week_seq1
LIMIT 100
""",
    "q65": """
SELECT s_store_name, i_item_desc, sc.revenue, i_current_price,
       i_wholesale_cost, i_brand
FROM store, item,
  (SELECT ss_store_sk, avg(revenue) AS ave
   FROM (SELECT ss_store_sk, ss_item_sk,
                sum(ss_sales_price) AS revenue
         FROM store_sales, date_dim
         WHERE ss_sold_date_sk = d_date_sk
         AND d_month_seq BETWEEN 24 AND 35
         GROUP BY ss_store_sk, ss_item_sk) sa
   GROUP BY ss_store_sk) sb,
  (SELECT ss_store_sk, ss_item_sk, sum(ss_sales_price) AS revenue
   FROM store_sales, date_dim
   WHERE ss_sold_date_sk = d_date_sk
   AND d_month_seq BETWEEN 24 AND 35
   GROUP BY ss_store_sk, ss_item_sk) sc
WHERE sb.ss_store_sk = sc.ss_store_sk
AND sc.revenue <= 0.1 * sb.ave
AND s_store_sk = sc.ss_store_sk
AND i_item_sk = sc.ss_item_sk
ORDER BY s_store_name, i_item_desc, sc.revenue
LIMIT 100
""",
    "q68": """
SELECT c_last_name, c_first_name, ca_city, bought_city,
       ss_ticket_number, extended_price, extended_tax, list_price
FROM (SELECT ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_ext_sales_price) extended_price,
             sum(ss_ext_list_price) list_price,
             sum(ss_ext_tax) extended_tax
      FROM store_sales, date_dim, store, household_demographics,
           customer_address
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
      AND store_sales.ss_store_sk = store.s_store_sk
      AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
      AND store_sales.ss_addr_sk = customer_address.ca_address_sk
      AND date_dim.d_dom BETWEEN 1 AND 2
      AND (household_demographics.hd_dep_count = 4 OR
           household_demographics.hd_vehicle_count = 3)
      AND date_dim.d_year IN (1999, 2000, 2001)
      AND store.s_city IN ('Midway', 'Fairview')
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk,
               ca_city) dn, customer, customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
AND customer.c_current_addr_sk = current_addr.ca_address_sk
AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, ss_ticket_number
LIMIT 100
""",
    "q73": """
SELECT c_last_name, c_first_name, c_salutation,
       c_preferred_cust_flag, ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
      AND store_sales.ss_store_sk = store.s_store_sk
      AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
      AND date_dim.d_dom BETWEEN 1 AND 2
      AND (household_demographics.hd_buy_potential = '>10000' OR
           household_demographics.hd_buy_potential = 'unknown')
      AND household_demographics.hd_vehicle_count > 0
      AND CASE WHEN household_demographics.hd_vehicle_count > 0
          THEN household_demographics.hd_dep_count /
               household_demographics.hd_vehicle_count
          ELSE null END > 1
      AND date_dim.d_year IN (1999, 2000, 2001)
      AND store.s_county IN ('Williamson County', 'Franklin Parish',
                             'Bronx County', 'Orange County')
      GROUP BY ss_ticket_number, ss_customer_sk) dj, customer
WHERE ss_customer_sk = c_customer_sk
AND cnt BETWEEN 1 AND 5
ORDER BY cnt DESC, c_last_name ASC, ss_ticket_number
LIMIT 1000
""",
    "q79": """
SELECT c_last_name, c_first_name,
       substring(s_city, 1, 30) AS city30, ss_ticket_number, amt,
       profit
FROM (SELECT ss_ticket_number, ss_customer_sk, store.s_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      FROM store_sales, date_dim, store, household_demographics
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
      AND store_sales.ss_store_sk = store.s_store_sk
      AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
      AND (household_demographics.hd_dep_count = 6 OR
           household_demographics.hd_vehicle_count > 2)
      AND date_dim.d_dow = 1
      AND date_dim.d_year IN (1999, 2000, 2001)
      AND store.s_number_employees BETWEEN 200 AND 295
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk,
               store.s_city) ms, customer
WHERE ss_customer_sk = c_customer_sk
ORDER BY c_last_name, c_first_name, city30, profit, ss_ticket_number
LIMIT 100
""",
    "q82": """
SELECT i_item_id, i_item_desc, i_current_price
FROM item, inventory, date_dim, store_sales
WHERE i_current_price BETWEEN 1.0 AND 1.8
AND inv_item_sk = i_item_sk
AND d_date_sk = inv_date_sk
AND d_date BETWEEN cast('2000-05-25' AS date)
              AND (cast('2000-05-25' AS date) + INTERVAL '60' day)
AND i_manufact_id IN (129, 270, 821, 423)
AND inv_quantity_on_hand BETWEEN 100 AND 500
AND ss_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id LIMIT 100
""",
    "q88": """
SELECT * FROM
(SELECT count(*) h8_30_to_9 FROM store_sales, household_demographics,
       time_dim, store
 WHERE ss_sold_time_sk = time_dim.t_time_sk
 AND ss_hdemo_sk = household_demographics.hd_demo_sk
 AND ss_store_sk = s_store_sk
 AND time_dim.t_hour = 8 AND time_dim.t_minute >= 30
 AND ((household_demographics.hd_dep_count = 4 AND
       household_demographics.hd_vehicle_count <= 6) OR
      (household_demographics.hd_dep_count = 2 AND
       household_demographics.hd_vehicle_count <= 4) OR
      (household_demographics.hd_dep_count = 0 AND
       household_demographics.hd_vehicle_count <= 2))
 AND store.s_store_name = 'ese1') s1 CROSS JOIN
(SELECT count(*) h9_to_9_30 FROM store_sales, household_demographics,
       time_dim, store
 WHERE ss_sold_time_sk = time_dim.t_time_sk
 AND ss_hdemo_sk = household_demographics.hd_demo_sk
 AND ss_store_sk = s_store_sk
 AND time_dim.t_hour = 9 AND time_dim.t_minute < 30
 AND ((household_demographics.hd_dep_count = 4 AND
       household_demographics.hd_vehicle_count <= 6) OR
      (household_demographics.hd_dep_count = 2 AND
       household_demographics.hd_vehicle_count <= 4) OR
      (household_demographics.hd_dep_count = 0 AND
       household_demographics.hd_vehicle_count <= 2))
 AND store.s_store_name = 'ese1') s2 CROSS JOIN
(SELECT count(*) h9_30_to_10 FROM store_sales,
       household_demographics, time_dim, store
 WHERE ss_sold_time_sk = time_dim.t_time_sk
 AND ss_hdemo_sk = household_demographics.hd_demo_sk
 AND ss_store_sk = s_store_sk
 AND time_dim.t_hour = 9 AND time_dim.t_minute >= 30
 AND ((household_demographics.hd_dep_count = 4 AND
       household_demographics.hd_vehicle_count <= 6) OR
      (household_demographics.hd_dep_count = 2 AND
       household_demographics.hd_vehicle_count <= 4) OR
      (household_demographics.hd_dep_count = 0 AND
       household_demographics.hd_vehicle_count <= 2))
 AND store.s_store_name = 'ese1') s3
""",
    "q90": """
SELECT cast(amc AS double) / cast(pmc AS double) am_pm_ratio
FROM (SELECT count(*) amc FROM web_sales, household_demographics,
            time_dim, web_page
      WHERE ws_sold_time_sk = time_dim.t_time_sk
      AND ws_ship_hdemo_sk = household_demographics.hd_demo_sk
      AND ws_web_page_sk = web_page.wp_web_page_sk
      AND time_dim.t_hour BETWEEN 8 AND 9
      AND household_demographics.hd_dep_count = 6
      AND web_page.wp_char_count BETWEEN 5000 AND 5200) at CROSS JOIN
     (SELECT count(*) pmc FROM web_sales, household_demographics,
            time_dim, web_page
      WHERE ws_sold_time_sk = time_dim.t_time_sk
      AND ws_ship_hdemo_sk = household_demographics.hd_demo_sk
      AND ws_web_page_sk = web_page.wp_web_page_sk
      AND time_dim.t_hour BETWEEN 19 AND 20
      AND household_demographics.hd_dep_count = 6
      AND web_page.wp_char_count BETWEEN 5000 AND 5200) pt
ORDER BY am_pm_ratio
LIMIT 100
""",
    "q93": """
SELECT ss_customer_sk, sum(act_sales) sumsales
FROM (SELECT ss_item_sk, ss_ticket_number, ss_customer_sk,
             CASE WHEN sr_return_quantity IS NOT NULL
             THEN (ss_quantity - sr_return_quantity) * ss_sales_price
             ELSE (ss_quantity * ss_sales_price) END act_sales
      FROM store_sales LEFT OUTER JOIN store_returns
        ON (sr_item_sk = ss_item_sk AND
            sr_ticket_number = ss_ticket_number), reason
      WHERE sr_reason_sk = r_reason_sk
      AND r_reason_desc = 'reason 28') t
GROUP BY ss_customer_sk
ORDER BY sumsales, ss_customer_sk
LIMIT 100
""",
    "q97": """
WITH ssci AS (
  SELECT ss_customer_sk customer_sk, ss_item_sk item_sk
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk
  AND d_month_seq BETWEEN 24 AND 35
  GROUP BY ss_customer_sk, ss_item_sk),
csci AS (
  SELECT cs_bill_customer_sk customer_sk, cs_item_sk item_sk
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk
  AND d_month_seq BETWEEN 24 AND 35
  GROUP BY cs_bill_customer_sk, cs_item_sk)
SELECT sum(CASE WHEN ssci.customer_sk IS NOT NULL
                AND csci.customer_sk IS NULL
           THEN 1 ELSE 0 END) store_only,
       sum(CASE WHEN ssci.customer_sk IS NULL
                AND csci.customer_sk IS NOT NULL
           THEN 1 ELSE 0 END) catalog_only,
       sum(CASE WHEN ssci.customer_sk IS NOT NULL
                AND csci.customer_sk IS NOT NULL
           THEN 1 ELSE 0 END) store_and_catalog
FROM ssci FULL OUTER JOIN csci
  ON (ssci.customer_sk = csci.customer_sk
      AND ssci.item_sk = csci.item_sk)
LIMIT 100
""",
}

for _name, _sql in TPCDS_SQL.items():
    QUERIES[f"tpcds_{_name}"] = _sql_query(_sql)
TPCDS_SQL["q1"] = """
WITH customer_total_return AS
  (SELECT sr_customer_sk AS ctr_customer_sk,
          ss_store_sk AS ctr_store_sk,
          sum(sr_return_amt) AS ctr_total_return
   FROM store_returns, store_sales, date_dim
   WHERE sr_ticket_number = ss_ticket_number
   AND sr_item_sk = ss_item_sk
   AND sr_returned_date_sk = d_date_sk AND d_year = 2000
   GROUP BY sr_customer_sk, ss_store_sk),
store_avg AS
  (SELECT ctr_store_sk AS avg_store_sk,
          avg(ctr_total_return) * 1.2 AS thresh
   FROM customer_total_return GROUP BY ctr_store_sk)
SELECT c_customer_id
FROM customer_total_return ctr1, store_avg, store, customer
WHERE ctr1.ctr_store_sk = store_avg.avg_store_sk
AND ctr1.ctr_total_return > store_avg.thresh
AND s_store_sk = ctr1.ctr_store_sk
AND s_state = 'TN'
AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id LIMIT 100
"""

TPCDS_SQL["q12"] = """
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
  sum(ws_ext_sales_price) AS itemrevenue,
  sum(ws_ext_sales_price) * 100 / sum(sum(ws_ext_sales_price)) OVER
    (PARTITION BY i_class) AS revenueratio
FROM web_sales, item, date_dim
WHERE ws_item_sk = i_item_sk
AND i_category IN ('Sports', 'Books', 'Home')
AND ws_sold_date_sk = d_date_sk
AND d_date BETWEEN cast('1999-02-22' AS date)
              AND (cast('1999-02-22' AS date) + INTERVAL '30' day)
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
LIMIT 100
"""

TPCDS_SQL["q20"] = """
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
  sum(cs_ext_sales_price) AS itemrevenue,
  sum(cs_ext_sales_price) * 100 / sum(sum(cs_ext_sales_price)) OVER
    (PARTITION BY i_class) AS revenueratio
FROM catalog_sales, item, date_dim
WHERE cs_item_sk = i_item_sk
AND i_category IN ('Sports', 'Books', 'Home')
AND cs_sold_date_sk = d_date_sk
AND d_date BETWEEN cast('1999-02-22' AS date)
              AND (cast('1999-02-22' AS date) + INTERVAL '30' day)
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
LIMIT 100
"""

TPCDS_SQL["q21"] = """
SELECT * FROM (
  SELECT w_warehouse_name, i_item_id,
    sum(CASE WHEN d_date < cast('2000-03-11' AS date)
        THEN inv_quantity_on_hand ELSE 0 END) AS inv_before,
    sum(CASE WHEN d_date >= cast('2000-03-11' AS date)
        THEN inv_quantity_on_hand ELSE 0 END) AS inv_after
  FROM inventory, warehouse, item, date_dim
  WHERE i_current_price BETWEEN 0.99 AND 1.49
  AND i_item_sk = inv_item_sk
  AND inv_warehouse_sk = w_warehouse_sk
  AND inv_date_sk = d_date_sk
  AND d_date BETWEEN (cast('2000-03-11' AS date) - INTERVAL '30' day)
                AND (cast('2000-03-11' AS date) + INTERVAL '30' day)
  GROUP BY w_warehouse_name, i_item_id) x
WHERE (CASE WHEN inv_before > 0 THEN inv_after / inv_before
       ELSE null END) BETWEEN 2.0 / 3.0 AND 3.0 / 2.0
ORDER BY w_warehouse_name, i_item_id
LIMIT 100
"""

TPCDS_SQL["q29"] = """
SELECT i_item_id, i_item_desc, s_store_id, s_store_name,
  sum(ss_quantity) AS store_sales_quantity,
  sum(sr_return_quantity) AS store_returns_quantity,
  sum(cs_quantity) AS catalog_sales_quantity
FROM store_sales, store_returns, catalog_sales, date_dim d1,
     date_dim d2, date_dim d3, store, item
WHERE d1.d_moy = 4 AND d1.d_year = 1999
AND d1.d_date_sk = ss_sold_date_sk
AND i_item_sk = ss_item_sk
AND s_store_sk = ss_store_sk
AND ss_customer_sk = sr_customer_sk
AND ss_item_sk = sr_item_sk
AND ss_ticket_number = sr_ticket_number
AND sr_returned_date_sk = d2.d_date_sk
AND d2.d_moy BETWEEN 4 AND 7 AND d2.d_year = 1999
AND sr_customer_sk = cs_bill_customer_sk
AND sr_item_sk = cs_item_sk
AND cs_sold_date_sk = d3.d_date_sk
AND d3.d_year IN (1999, 2000, 2001)
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id, i_item_desc, s_store_id, s_store_name
LIMIT 100
"""

# q32/q92: the spec's correlated per-item scalar subquery decorrelates
# into a grouped-average join (the rewrite Spark's optimizer performs)
TPCDS_SQL["q32"] = """
SELECT sum(cs_ext_discount_amt) AS excess_discount_amount
FROM catalog_sales, item, date_dim,
  (SELECT cs_item_sk AS t_item_sk,
          1.3 * avg(cs_ext_discount_amt) AS thresh
   FROM catalog_sales, date_dim
   WHERE d_date BETWEEN cast('2000-01-27' AS date)
                   AND (cast('2000-01-27' AS date) + INTERVAL '90' day)
   AND d_date_sk = cs_sold_date_sk
   GROUP BY cs_item_sk) t
WHERE i_manufact_id = 977
AND i_item_sk = cs_item_sk
AND t.t_item_sk = cs_item_sk
AND d_date BETWEEN cast('2000-01-27' AS date)
              AND (cast('2000-01-27' AS date) + INTERVAL '90' day)
AND d_date_sk = cs_sold_date_sk
AND cs_ext_discount_amt > t.thresh
LIMIT 100
"""

TPCDS_SQL["q34"] = """
SELECT c_last_name, c_first_name, c_salutation,
       c_preferred_cust_flag, ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
      AND store_sales.ss_store_sk = store.s_store_sk
      AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
      AND (date_dim.d_dom BETWEEN 1 AND 3 OR
           date_dim.d_dom BETWEEN 25 AND 28)
      AND (household_demographics.hd_buy_potential = '>10000' OR
           household_demographics.hd_buy_potential = 'unknown')
      AND household_demographics.hd_vehicle_count > 0
      AND (CASE WHEN household_demographics.hd_vehicle_count > 0
           THEN household_demographics.hd_dep_count /
                household_demographics.hd_vehicle_count
           ELSE null END) > 1.2
      AND date_dim.d_year IN (1999, 2000, 2001)
      AND store.s_county IN ('Williamson County')
      GROUP BY ss_ticket_number, ss_customer_sk) dn, customer
WHERE ss_customer_sk = c_customer_sk
AND cnt BETWEEN 2 AND 20
ORDER BY c_last_name, c_first_name, c_salutation,
         c_preferred_cust_flag DESC, ss_ticket_number
LIMIT 1000
"""

# q39: the spec's simple-CASE (case mean when 0 ...) spelled searched
TPCDS_SQL["q39"] = """
WITH inv AS
  (SELECT w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy,
          stdev, mean,
          CASE WHEN mean = 0 THEN null ELSE stdev / mean END cov
   FROM (SELECT w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy,
                stddev_samp(inv_quantity_on_hand) stdev,
                avg(inv_quantity_on_hand) mean
         FROM inventory, item, warehouse, date_dim
         WHERE inv_item_sk = i_item_sk
         AND inv_warehouse_sk = w_warehouse_sk
         AND inv_date_sk = d_date_sk
         AND d_year = 2001
         GROUP BY w_warehouse_name, w_warehouse_sk, i_item_sk,
                  d_moy) foo
   WHERE CASE WHEN mean = 0 THEN 0 ELSE stdev / mean END > 1)
SELECT inv1.w_warehouse_sk AS w1, inv1.i_item_sk AS i1,
       inv1.d_moy AS moy1, inv1.mean AS mean1, inv1.cov AS cov1,
       inv2.w_warehouse_sk AS w2, inv2.i_item_sk AS i2,
       inv2.d_moy AS moy2, inv2.mean AS mean2, inv2.cov AS cov2
FROM inv inv1, inv inv2
WHERE inv1.i_item_sk = inv2.i_item_sk
AND inv1.w_warehouse_sk = inv2.w_warehouse_sk
AND inv1.d_moy = 1 AND inv2.d_moy = 2
ORDER BY w1, i1, moy1, mean1, cov1, moy2, mean2, cov2
"""

# q53/q89: brand-literal pools adapted to the generated category/class
# values (brands are random; the plan shape — OR'd pools + windowed
# average deviation — is what the query exercises)
TPCDS_SQL["q53"] = """
SELECT * FROM
  (SELECT i_manufact_id, sum(ss_sales_price) sum_sales,
          avg(sum(ss_sales_price)) OVER
            (PARTITION BY i_manufact_id) avg_quarterly_sales
   FROM item, store_sales, date_dim, store
   WHERE ss_item_sk = i_item_sk AND
   ss_sold_date_sk = d_date_sk AND
   ss_store_sk = s_store_sk AND
   d_month_seq IN (24, 25, 26, 27, 28, 29, 30, 31, 32, 33, 34, 35) AND
   ((i_category IN ('Books', 'Children', 'Electronics') AND
     i_class IN ('class1', 'class2', 'class3', 'class4'))
    OR (i_category IN ('Women', 'Music', 'Men') AND
        i_class IN ('class5', 'class6', 'class7', 'class8')))
   GROUP BY i_manufact_id, d_qoy) tmp1
WHERE CASE WHEN avg_quarterly_sales > 0
      THEN abs(sum_sales - avg_quarterly_sales) / avg_quarterly_sales
      ELSE null END > 0.1
ORDER BY avg_quarterly_sales, sum_sales, i_manufact_id
LIMIT 100
"""

TPCDS_SQL["q60"] = """
WITH ss AS (
  SELECT i_item_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category IN ('Music'))
  AND ss_item_sk = i_item_sk
  AND ss_sold_date_sk = d_date_sk
  AND d_year = 1998 AND d_moy = 9
  AND ss_addr_sk = ca_address_sk AND ca_gmt_offset = -5.0
  GROUP BY i_item_id),
cs AS (
  SELECT i_item_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category IN ('Music'))
  AND cs_item_sk = i_item_sk
  AND cs_sold_date_sk = d_date_sk
  AND d_year = 1998 AND d_moy = 9
  AND cs_bill_addr_sk = ca_address_sk AND ca_gmt_offset = -5.0
  GROUP BY i_item_id),
ws AS (
  SELECT i_item_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category IN ('Music'))
  AND ws_item_sk = i_item_sk
  AND ws_sold_date_sk = d_date_sk
  AND d_year = 1998 AND d_moy = 9
  AND ws_bill_addr_sk = ca_address_sk AND ca_gmt_offset = -5.0
  GROUP BY i_item_id)
SELECT i_item_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss UNION ALL
      SELECT * FROM cs UNION ALL
      SELECT * FROM ws) tmp1
GROUP BY i_item_id
ORDER BY i_item_id, total_sales
LIMIT 100
"""

TPCDS_SQL["q71"] = """
SELECT i_brand_id brand_id, i_brand brand, t_hour, t_minute,
       sum(ext_price) ext_price
FROM item,
  (SELECT ws_ext_sales_price AS ext_price,
          ws_sold_date_sk AS sold_date_sk,
          ws_item_sk AS sold_item_sk,
          ws_sold_time_sk AS time_sk
   FROM web_sales, date_dim
   WHERE d_date_sk = ws_sold_date_sk AND d_moy = 11 AND d_year = 1999
   UNION ALL
   SELECT cs_ext_sales_price AS ext_price,
          cs_sold_date_sk AS sold_date_sk,
          cs_item_sk AS sold_item_sk,
          cs_sold_time_sk AS time_sk
   FROM catalog_sales, date_dim
   WHERE d_date_sk = cs_sold_date_sk AND d_moy = 11 AND d_year = 1999
   UNION ALL
   SELECT ss_ext_sales_price AS ext_price,
          ss_sold_date_sk AS sold_date_sk,
          ss_item_sk AS sold_item_sk,
          ss_sold_time_sk AS time_sk
   FROM store_sales, date_dim
   WHERE d_date_sk = ss_sold_date_sk AND d_moy = 11 AND d_year = 1999
  ) tmp, time_dim
WHERE sold_item_sk = i_item_sk
AND i_manager_id = 1
AND time_sk = t_time_sk
AND (t_meal_time = 'breakfast' OR t_meal_time = 'dinner')
GROUP BY i_brand, i_brand_id, t_hour, t_minute
ORDER BY ext_price DESC, i_brand_id, t_hour, t_minute
LIMIT 1000
"""

TPCDS_SQL["q89"] = """
SELECT * FROM (
  SELECT i_category, i_class, i_brand, s_store_name, s_store_id,
         d_moy, sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price)) OVER
           (PARTITION BY i_category, i_brand, s_store_name, s_store_id)
         avg_monthly_sales
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk AND
  ss_sold_date_sk = d_date_sk AND
  ss_store_sk = s_store_sk AND
  d_year IN (1999) AND
  ((i_category IN ('Books', 'Electronics', 'Sports') AND
    i_class IN ('class1', 'class2', 'class3'))
   OR (i_category IN ('Men', 'Jewelry', 'Women') AND
       i_class IN ('class4', 'class5', 'class6')))
  GROUP BY i_category, i_class, i_brand, s_store_name, s_store_id,
           d_moy) tmp1
WHERE CASE WHEN avg_monthly_sales <> 0
      THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
      ELSE null END > 0.1
ORDER BY sum_sales - avg_monthly_sales, s_store_name, s_store_id,
         i_category, i_class, i_brand, d_moy
LIMIT 100
"""

TPCDS_SQL["q92"] = """
SELECT sum(ws_ext_discount_amt) AS excess_discount_amount
FROM web_sales, item, date_dim,
  (SELECT ws_item_sk AS t_item_sk,
          1.3 * avg(ws_ext_discount_amt) AS thresh
   FROM web_sales, date_dim
   WHERE d_date BETWEEN cast('2000-01-27' AS date)
                   AND (cast('2000-01-27' AS date) + INTERVAL '90' day)
   AND d_date_sk = ws_sold_date_sk
   GROUP BY ws_item_sk) t
WHERE i_manufact_id = 350
AND i_item_sk = ws_item_sk
AND t.t_item_sk = ws_item_sk
AND d_date BETWEEN cast('2000-01-27' AS date)
              AND (cast('2000-01-27' AS date) + INTERVAL '90' day)
AND d_date_sk = ws_sold_date_sk
AND ws_ext_discount_amt > t.thresh
ORDER BY excess_discount_amount
LIMIT 100
"""

# re-iterate the dict: every TPCDS_SQL entry registers, so a query
# added anywhere above cannot silently skip oracle testing
for _name, _sql in TPCDS_SQL.items():
    QUERIES[f"tpcds_{_name}"] = _sql_query(_sql)

